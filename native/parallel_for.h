// Shared worker-pool helper for the native library's translation units.
#ifndef SAV_TPU_NATIVE_PARALLEL_FOR_H_
#define SAV_TPU_NATIVE_PARALLEL_FOR_H_

#include <atomic>
#include <cstdint>
#include <thread>
#include <vector>

namespace sav {

// Run fn(i) for i in [0, n) over `threads` workers.
template <typename F>
void parallel_for(int64_t n, int threads, F fn) {
  if (threads <= 1 || n < 2) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  std::vector<std::thread> pool;
  pool.reserve(threads);
  for (int t = 0; t < threads; ++t) {
    pool.emplace_back([&] {
      for (int64_t i = next.fetch_add(1); i < n; i = next.fetch_add(1)) fn(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace sav

#endif  // SAV_TPU_NATIVE_PARALLEL_FOR_H_
