// sav_tpu native loader core.
//
// The reference's only native-code surface is TF's C++ tf.data runtime and
// JPEG ops (SURVEY.md §2.8). This library is the TPU-framework equivalent
// for the host-side hot loop the survey singles out (input_pipeline.py
// :187-196, 226-243): batch normalization (uint8 → float, mean/std in
// 0-255 scale), the NHWC→HWCN double-transpose, float32→bfloat16
// conversion (the "late cast"), and batch gather/assembly — all threaded
// and SIMD-friendly, exported with a C ABI for ctypes (no pybind11 in the
// image).
//
// Build: `make -C native` → native/libsavtpu_loader.so

#include <cstdint>
#include <cstring>
#include <vector>

#include "parallel_for.h"

namespace {

using sav::parallel_for;

inline uint16_t f32_to_bf16_scalar(float x) {
  uint32_t bits;
  std::memcpy(&bits, &x, sizeof(bits));
  // NaN must stay NaN: the rounding add below would carry into the exponent
  // and produce Inf. Quiet the NaN like ml_dtypes does.
  if ((bits & 0x7F800000u) == 0x7F800000u && (bits & 0x007FFFFFu)) {
    return static_cast<uint16_t>((bits >> 16) | 0x0040u);
  }
  // Round-to-nearest-even on the truncated mantissa.
  uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

}  // namespace

extern "C" {

// uint8 [N,H,W,C] → float32, normalized (x - mean[c]) / std[c].
// transpose == 0: out is [N,H,W,C]; transpose == 1: out is [H,W,C,N]
// (the reference's HWCN device-feed layout).
void sav_normalize_batch(const uint8_t* in, float* out, int64_t n, int64_t h,
                         int64_t w, int64_t c, const float* mean,
                         const float* stddev, int transpose, int threads) {
  const int64_t hwc = h * w * c;
  std::vector<float> inv(c);
  for (int64_t k = 0; k < c; ++k) inv[k] = 1.0f / stddev[k];
  parallel_for(n, threads, [&](int64_t i) {
    const uint8_t* src = in + i * hwc;
    if (!transpose) {
      float* dst = out + i * hwc;
      for (int64_t j = 0; j < hwc; ++j) {
        const int64_t ch = j % c;
        dst[j] = (static_cast<float>(src[j]) - mean[ch]) * inv[ch];
      }
    } else {
      // out[(j * n) + i] for flattened pixel index j: [H,W,C,N].
      for (int64_t j = 0; j < hwc; ++j) {
        const int64_t ch = j % c;
        out[j * n + i] = (static_cast<float>(src[j]) - mean[ch]) * inv[ch];
      }
    }
  });
}

// float32 → bfloat16 (round-to-nearest-even), elementwise.
void sav_f32_to_bf16(const float* in, uint16_t* out, int64_t count,
                     int threads) {
  const int64_t chunk = 1 << 16;
  const int64_t n_chunks = (count + chunk - 1) / chunk;
  parallel_for(n_chunks, threads, [&](int64_t ci) {
    const int64_t lo = ci * chunk;
    const int64_t hi = lo + chunk < count ? lo + chunk : count;
    for (int64_t i = lo; i < hi; ++i) out[i] = f32_to_bf16_scalar(in[i]);
  });
}

// Gather items from a contiguous pool into a batch: out[i] = pool[indices[i]].
void sav_gather_batch(const uint8_t* pool, const int32_t* indices,
                      uint8_t* out, int64_t n, int64_t item_bytes,
                      int threads) {
  parallel_for(n, threads, [&](int64_t i) {
    std::memcpy(out + i * item_bytes,
                pool + static_cast<int64_t>(indices[i]) * item_bytes,
                item_bytes);
  });
}

// NHWC float32 → HWCN float32 (double-transpose device-feed layout).
void sav_transpose_nhwc_to_hwcn(const float* in, float* out, int64_t n,
                                int64_t h, int64_t w, int64_t c, int threads) {
  const int64_t hwc = h * w * c;
  parallel_for(n, threads, [&](int64_t i) {
    const float* src = in + i * hwc;
    for (int64_t j = 0; j < hwc; ++j) out[j * n + i] = src[j];
  });
}

// uint8 [N,H,W,C] → uint8 [N,H,W,C] batch assembly with optional per-image
// horizontal flip (flip != NULL && flip[i] != 0 reverses W). This is the
// uint8-on-the-wire path's only host byte transform (device_preprocess
// ships raw post-augment uint8; normalize/cast run in the jitted step), so
// it must not bounce through float: threaded memcpy rows, GIL released.
void sav_u8_passthrough_batch(const uint8_t* in, uint8_t* out, int64_t n,
                              int64_t h, int64_t w, int64_t c,
                              const uint8_t* flip, int threads) {
  const int64_t hwc = h * w * c;
  const int64_t wc = w * c;
  parallel_for(n, threads, [&](int64_t i) {
    const uint8_t* src = in + i * hwc;
    uint8_t* dst = out + i * hwc;
    if (flip == nullptr || !flip[i]) {
      std::memcpy(dst, src, static_cast<size_t>(hwc));
      return;
    }
    for (int64_t y = 0; y < h; ++y) {
      const uint8_t* srow = src + y * wc;
      uint8_t* drow = dst + y * wc;
      for (int64_t x = 0; x < w; ++x) {
        std::memcpy(drow + x * c, srow + (w - 1 - x) * c,
                    static_cast<size_t>(c));
      }
    }
  });
}

int sav_loader_abi_version() { return 1; }

}  // extern "C"
