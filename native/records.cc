// sav_tpu native record IO: the framework's tf.data-C++ equivalent.
//
// The reference's data runtime was TF's C++ tf.data + TFRecord readers
// (SURVEY.md §2.8). This is the native IO path for sav_tpu's own on-disk
// format ("SavRecord v1"): a mmap'd fixed-shape image/label container with
// an offsets table, read by threaded batch gathers straight into
// caller-owned numpy buffers (zero intermediate copies). Host-sharded
// epoch iteration is orchestrated in Python (sav_tpu/data/records.py);
// all byte movement happens here with the GIL released.
//
// Layout (little-endian):
//   0x00  magic  "SAVREC01"                     (8 bytes)
//   0x08  u32 version (=1), u32 reserved
//   0x10  u64 num_records
//   0x18  u32 height, u32 width, u32 channels, u32 label_bytes (=4)
//   0x28  u64 offsets[num_records + 1]   // payload-relative byte offsets
//   ...   payload: per record, image bytes (h*w*c u8) then label (i32)
//
// Build: part of `make -C native` → libsavtpu_loader.so

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>

#include "parallel_for.h"

namespace {

constexpr char kMagic[8] = {'S', 'A', 'V', 'R', 'E', 'C', '0', '1'};

struct SavRecFile {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  uint64_t num_records = 0;
  uint32_t height = 0, width = 0, channels = 0, label_bytes = 0;
  const uint64_t* offsets = nullptr;  // [num_records + 1]
  const uint8_t* payload = nullptr;
};

}  // namespace

extern "C" {

// Open + validate + mmap. Returns an opaque handle, or null on any error
// (missing file, bad magic/version, truncated header or payload).
void* sav_rec_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < 0x28) {
    ::close(fd);
    return nullptr;
  }
  size_t len = static_cast<size_t>(st.st_size);
  void* map = ::mmap(nullptr, len, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  const uint8_t* base = static_cast<const uint8_t*>(map);
  auto fail = [&]() {
    ::munmap(map, len);
    ::close(fd);
    return nullptr;
  };
  if (std::memcmp(base, kMagic, sizeof(kMagic)) != 0) return fail();
  uint32_t version;
  std::memcpy(&version, base + 0x08, 4);
  if (version != 1) return fail();
  auto* f = new SavRecFile;
  f->fd = fd;
  f->map = base;
  f->map_len = len;
  std::memcpy(&f->num_records, base + 0x10, 8);
  std::memcpy(&f->height, base + 0x18, 4);
  std::memcpy(&f->width, base + 0x1C, 4);
  std::memcpy(&f->channels, base + 0x20, 4);
  std::memcpy(&f->label_bytes, base + 0x24, 4);
  // Overflow-safe truncation check: divide, never multiply a corrupt count.
  // `avail` is how many u64 slots fit after the header; the offsets table
  // needs num_records + 1 of them, so a header-only file (avail == 0) must
  // fail before the subtraction, not wrap it around.
  const size_t avail = (len - 0x28) / sizeof(uint64_t);
  if (avail == 0 || f->num_records > avail - 1) {
    delete f;
    return fail();
  }
  const size_t offsets_bytes = (f->num_records + 1) * sizeof(uint64_t);
  f->offsets = reinterpret_cast<const uint64_t*>(base + 0x28);
  f->payload = base + 0x28 + offsets_bytes;
  const size_t payload_len = len - 0x28 - offsets_bytes;
  // Validate the whole offsets table once at open so read_batch can trust
  // it: monotonic, in-bounds, and every record exactly image+label bytes.
  const uint64_t rec_bytes =
      static_cast<uint64_t>(f->height) * f->width * f->channels +
      f->label_bytes;
  if (f->offsets[f->num_records] > payload_len || rec_bytes == 0) {
    delete f;
    return fail();
  }
  for (uint64_t i = 0; i < f->num_records; ++i) {
    if (f->offsets[i + 1] < f->offsets[i] ||
        f->offsets[i + 1] - f->offsets[i] != rec_bytes) {
      delete f;
      return fail();
    }
  }
  return f;
}

int64_t sav_rec_count(const void* handle) {
  return static_cast<const SavRecFile*>(handle)->num_records;
}

// meta_out: [height, width, channels, label_bytes]
void sav_rec_meta(const void* handle, int64_t* meta_out) {
  const auto* f = static_cast<const SavRecFile*>(handle);
  meta_out[0] = f->height;
  meta_out[1] = f->width;
  meta_out[2] = f->channels;
  meta_out[3] = f->label_bytes;
}

// Gather `n` records by index into images_out [n, h*w*c] u8 and
// labels_out [n] i32. Returns 0 on success, -1 on any out-of-range index.
int sav_rec_read_batch(const void* handle, const int64_t* indices, int64_t n,
                       uint8_t* images_out, int32_t* labels_out, int threads) {
  const auto* f = static_cast<const SavRecFile*>(handle);
  const int64_t image_bytes =
      static_cast<int64_t>(f->height) * f->width * f->channels;
  std::atomic<int> bad(0);
  sav::parallel_for(n, threads, [&](int64_t i) {
    const int64_t idx = indices[i];
    if (idx < 0 || static_cast<uint64_t>(idx) >= f->num_records) {
      bad.store(1);
      return;
    }
    const uint8_t* rec = f->payload + f->offsets[idx];
    std::memcpy(images_out + i * image_bytes, rec, image_bytes);
    std::memcpy(labels_out + i, rec + image_bytes, sizeof(int32_t));
  });
  return bad.load() ? -1 : 0;
}

void sav_rec_close(void* handle) {
  auto* f = static_cast<SavRecFile*>(handle);
  ::munmap(const_cast<uint8_t*>(f->map), f->map_len);
  ::close(f->fd);
  delete f;
}

}  // extern "C"
