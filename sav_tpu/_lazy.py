"""Shared PEP 562 lazy re-export machinery for the package ``__init__``s.

Four subpackages (:mod:`sav_tpu.utils`, :mod:`sav_tpu.obs`,
:mod:`sav_tpu.data`, :mod:`sav_tpu.train`) carry the same import
contract: their stdlib-only submodules (``backend_probe``, ``manifest``,
``synthetic``, ``supervisor`` ...) must be importable without dragging
``jax``/TF into the process — the backend probe and the elasticity
supervisor run on exactly the paths (down relay, on-chip parent) where a
heavy import hangs or delays the abort decision. One factory instead of
four hand-copied ``__getattr__``/``__dir__`` bodies keeps the contract's
implementation in one place.

Stdlib-only, and importing it only executes ``sav_tpu/__init__``'s
docstring — free on every path.
"""

from __future__ import annotations

from typing import Iterable


def install_lazy_exports(
    namespace: dict, exports: dict, submodules: Iterable[str] = ()
):
    """Build a package's lazy ``(__getattr__, __dir__)`` pair.

    Args:
      namespace: the package ``__init__``'s ``globals()`` — resolved
        names are cached into it so each import happens once.
      exports: re-export name -> defining module (``"TrainConfig":
        "sav_tpu.train.config"``).
      submodules: names that resolve to the submodule itself (keeps
        ``sav_tpu.utils.metrics``-after-``import sav_tpu.utils`` working
        the way eager imports used to bind them).

    Usage in an ``__init__.py``::

        _EXPORTS = {...}
        __all__ = list(_EXPORTS)
        __getattr__, __dir__ = install_lazy_exports(
            globals(), _EXPORTS, {"submodule", ...}
        )
    """
    package = namespace["__name__"]
    submodules = frozenset(submodules)

    def __getattr__(name: str):
        import importlib

        if name in submodules:
            module = importlib.import_module(f"{package}.{name}")
            namespace[name] = module
            return module
        target = exports.get(name)
        if target is None:
            raise AttributeError(
                f"module {package!r} has no attribute {name!r}"
            )
        value = getattr(importlib.import_module(target), name)
        namespace[name] = value
        return value

    def __dir__():
        return sorted(set(namespace) | set(exports) | submodules)

    return __getattr__, __dir__
