"""Augment-string mini-DSL parser.

The reference's de-facto augmentation config system (SURVEY.md §2.4):
strings like ``'cutmix_mixup_randaugment_405'`` select batch-mix ops and
AA/RA policies. Grammar (reference semantics,
/root/reference/input_pipeline.py:161-182, 414-441):

  - ``cutmix``            — CutMix on (part of) the batch
  - ``mixup``             — MixUp, Beta(0.2) ratio by default
  - ``mixup_<alpha>``     — override the Beta alpha (e.g. ``mixup_0.4``)
  - ``randaugment_<M>``   — RandAugment; M < 100 → (2 layers, mag M),
                            M ≥ 100 → (M // 100 layers, mag M % 100),
                            so ``randaugment_405`` = 4 layers, magnitude 5
  - ``autoaugment``       — AutoAugment-v0 policy
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional


@dataclasses.dataclass(frozen=True)
class AugmentSpec:
    cutmix: bool = False
    mixup: bool = False
    mixup_alpha: float = 0.2
    cutmix_alpha: float = 1.0
    randaugment: Optional[tuple[int, int]] = None  # (num_layers, magnitude)
    autoaugment: bool = False

    @property
    def mixes(self) -> bool:
        return self.cutmix or self.mixup


def parse_augment_spec(name: Optional[str]) -> AugmentSpec:
    if not name or name == "none":
        return AugmentSpec()
    cutmix = "cutmix" in name
    mixup = "mixup" in name
    mixup_alpha = 0.2
    m = re.search(r"mixup_([0-9.]+)", name)
    if m:
        mixup_alpha = float(m.group(1))
    randaug = None
    m = re.search(r"randaugment_(\d+)", name)
    if m:
        code = int(m.group(1))
        if code >= 100:
            randaug = (code // 100, code % 100)
        else:
            randaug = (2, code)
    autoaug = "autoaugment" in name and "randaugment" not in name
    spec = AugmentSpec(
        cutmix=cutmix,
        mixup=mixup,
        mixup_alpha=mixup_alpha,
        randaugment=randaug,
        autoaugment=autoaug,
    )
    return spec
