"""tf.data input pipeline.

Capability parity with the reference's DeepMind-lineage ImageNet pipeline
(/root/reference/input_pipeline.py, SURVEY.md §2.4), TPU-first:

  - ``Split`` enum with the same example-count semantics (VALID carved from
    the TFDS train split, TEST = TFDS validation).
  - per-host data sharding (``np.array_split`` over example ranges →
    TFDS ReadInstruction / per-host file sharding).
  - JPEG-bytes cropping: crops computed on raw bytes via
    ``tf.image.decode_and_crop_jpeg`` so full decode never happens
    (input_pipeline.py:126, 536-544 — a real throughput optimization).
  - Inception-style distorted-bbox random crop + flip + bicubic resize;
    ``crop_resize`` / ``resize_crop_{pct}`` eval preprocessing.
  - RandAugment / AutoAugment on uint8, CutMix/MixUp on normalized floats,
    augment-string DSL (:mod:`sav_tpu.data.augment_spec`).
  - double-transpose trick (images emitted HWCN) + late bf16 cast on the
    host (halves host→device bytes; the model transposes back on-device).

Sources: TFDS when installed, a TFRecord directory, or an in-memory
``(images, labels)`` pair (JPEG-encoded on the fly so tests exercise the
real bytes path). ``fake_data=True`` yields correctly-shaped zero batches
without any backing data (input_pipeline.py:104-113 parity).
"""

from __future__ import annotations

import enum
from typing import Generator, Optional, Sequence

import numpy as np

try:  # TF is only needed for the real pipeline, not for fake data.
    import tensorflow as tf
except ImportError:  # pragma: no cover
    tf = None

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

MEAN_RGB = (0.485 * 255, 0.456 * 255, 0.406 * 255)
STDDEV_RGB = (0.229 * 255, 0.224 * 255, 0.225 * 255)


class Split(enum.Enum):
    """ImageNet splits (input_pipeline.py:38-62 semantics)."""

    TRAIN = 1
    TRAIN_AND_VALID = 2
    VALID = 3
    TEST = 4

    @property
    def num_examples(self) -> int:
        return {
            Split.TRAIN: 1_271_167,
            Split.TRAIN_AND_VALID: 1_281_167,
            Split.VALID: 10_000,
            Split.TEST: 50_000,
        }[self]


def _host_shard_range(
    split: Split, process_index: int, process_count: int
) -> tuple[int, int]:
    """[start, end) absolute example indices for this host
    (input_pipeline.py:369-380 behavior)."""
    arange = np.arange(split.num_examples)
    shard = np.array_split(arange, process_count)[process_index]
    # VALID lives at the tail of TRAIN_AND_VALID (train[:10000] carve-out in
    # the reference is from the front of tfds train; we use offsets below).
    return int(shard[0]), int(shard[-1]) + 1


# --------------------------------------------------------------- decoding


def _distorted_bbox_crop_window(image_bytes: "tf.Tensor") -> "tf.Tensor":
    """Inception-style random crop window on raw JPEG bytes
    (input_pipeline.py:479-497)."""
    shape = tf.image.extract_jpeg_shape(image_bytes)
    bbox = tf.constant([0.0, 0.0, 1.0, 1.0], shape=[1, 1, 4])
    begin, size, _ = tf.image.sample_distorted_bounding_box(
        shape,
        bounding_boxes=bbox,
        min_object_covered=0.1,
        aspect_ratio_range=(3.0 / 4.0, 4.0 / 3.0),
        area_range=(0.08, 1.0),
        max_attempts=10,
        use_image_if_no_bounding_boxes=True,
    )
    y, x, _ = tf.unstack(begin)
    h, w, _ = tf.unstack(size)
    return tf.stack([y, x, h, w])


def _center_crop_window(image_bytes, image_size: int):
    """Aspect-preserving center crop padded by 32px (input_pipeline.py:500-524)."""
    shape = tf.image.extract_jpeg_shape(image_bytes)
    h, w = shape[0], shape[1]
    ratio = tf.cast(image_size, tf.float32) / (tf.cast(image_size, tf.float32) + 32.0)
    crop = tf.cast(
        ratio * tf.cast(tf.minimum(h, w), tf.float32), tf.int32
    )
    y = (h - crop + 1) // 2
    x = (w - crop + 1) // 2
    return tf.stack([y, x, crop, crop])


def _decode_crop(image_bytes, window):
    return tf.image.decode_and_crop_jpeg(image_bytes, window, channels=3)


def _resize_bicubic(image, image_size: int):
    out = tf.image.resize(
        tf.cast(image, tf.float32), [image_size, image_size], tf.image.ResizeMethod.BICUBIC
    )
    return tf.cast(tf.clip_by_value(out, 0.0, 255.0), tf.uint8)


def _train_preprocess(image_bytes, image_size: int):
    window = _distorted_bbox_crop_window(image_bytes)
    image = _decode_crop(image_bytes, window)
    image = tf.image.random_flip_left_right(image)
    return _resize_bicubic(image, image_size)


def _eval_preprocess(image_bytes, image_size: int, eval_preproc: str):
    if eval_preproc == "crop_resize":
        image = _decode_crop(image_bytes, _center_crop_window(image_bytes, image_size))
        return _resize_bicubic(image, image_size)
    if eval_preproc.startswith("resize_crop_"):
        # Resize so that image_size/pct fits, then center-crop to image_size
        # (input_pipeline.py:547-566).
        pct = float(eval_preproc[len("resize_crop_") :])
        image = tf.io.decode_jpeg(image_bytes, channels=3)
        resize_to = tf.cast(tf.cast(image_size, tf.float32) / pct, tf.int32)
        image = tf.image.resize(
            tf.cast(image, tf.float32), [resize_to, resize_to], tf.image.ResizeMethod.BICUBIC
        )
        image = tf.image.resize_with_crop_or_pad(image, image_size, image_size)
        return tf.cast(tf.clip_by_value(image, 0.0, 255.0), tf.uint8)
    raise ValueError(f"unknown eval_preproc {eval_preproc!r}")


def _normalize(image):
    image = tf.cast(image, tf.float32)
    image = image - tf.constant(MEAN_RGB, shape=[1, 1, 3])
    return image / tf.constant(STDDEV_RGB, shape=[1, 1, 3])


# ----------------------------------------------------------------- sources


def _tfds_source(split: Split, data_dir, start: int, end: int, is_training: bool):
    import tensorflow_datasets as tfds

    if split in (Split.TRAIN, Split.TRAIN_AND_VALID, Split.VALID):
        base = "train"
        # VALID is the reference's train[:10000] carve-out; TRAIN skips it.
        offset = 0 if split is Split.VALID else (
            10_000 if split is Split.TRAIN else 0
        )
    else:
        base, offset = "validation", 0
    instruction = tfds.core.ReadInstruction(
        base, from_=start + offset, to=end + offset, unit="abs"
    )
    ds = tfds.load(
        "imagenet2012:5.*.*",
        split=instruction,
        data_dir=data_dir,
        decoders={"image": tfds.decode.SkipDecoding()},
        shuffle_files=is_training,
    )
    return ds.map(lambda d: {"image_bytes": d["image"], "label": d["label"]})


def _tfrecord_source(split: Split, data_dir: str, start: int, end: int):
    """Deterministic record stream with the same carve-out/range semantics as
    the TFDS path: VALID = first 10k of the train stream, TRAIN skips them,
    and [start, end) is this host's shard within the split."""
    pattern = {
        Split.TRAIN: "train-*",
        Split.TRAIN_AND_VALID: "train-*",
        Split.VALID: "train-*",
        Split.TEST: "validation-*",
    }[split]
    files = tf.io.gfile.glob(f"{data_dir.rstrip('/')}/{pattern}")
    if not files:
        raise FileNotFoundError(f"no TFRecords matching {pattern} under {data_dir}")
    # Files read in sorted order, sequentially, so absolute example indices
    # are stable across hosts (shuffling happens later, after sharding).
    ds = tf.data.TFRecordDataset(sorted(files))
    offset = 10_000 if split is Split.TRAIN else 0
    ds = ds.skip(offset + start).take(end - start)
    features = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64),
    }

    def parse(record):
        ex = tf.io.parse_single_example(record, features)
        # ImageNet TFRecords label in [1, 1000] → [0, 999].
        return {
            "image_bytes": ex["image/encoded"],
            "label": tf.cast(ex["image/class/label"], tf.int32) - 1,
        }

    return ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)


def _memory_source(images: np.ndarray, labels: np.ndarray, start: int, end: int):
    """In-memory uint8 images, JPEG-encoded so the bytes path is exercised."""
    end = min(end, len(images))
    start = min(start, end)
    encoded = [
        tf.io.encode_jpeg(images[i]).numpy() for i in range(start, end)
    ]
    ds = tf.data.Dataset.from_tensor_slices(
        {
            "image_bytes": tf.constant(encoded),
            "label": tf.constant(labels[start:end], tf.int32),
        }
    )
    return ds


# -------------------------------------------------------------------- load


def load(
    split: Split,
    *,
    data_dir: Optional[str] = None,
    source: Optional[tuple[np.ndarray, np.ndarray]] = None,
    is_training: bool,
    batch_dims: Sequence[int],
    image_size: int = 224,
    augment_name: Optional[str] = None,
    eval_preproc: str = "crop_resize",
    augment_before_mix: bool = True,
    transpose: bool = False,
    bfloat16: bool = False,
    fake_data: bool = False,
    shuffle_buffer: Optional[int] = None,
    seed: Optional[int] = None,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> Generator[dict, None, None]:
    """Build the input generator. See module docstring.

    ``batch_dims``: leading batch shape, outermost first (reference
    semantics: ``[local_devices, per_device_bs]``; pjit callers typically
    pass a single global-per-host dim).

    ``augment_before_mix``: apply RandAugment/AutoAugment before (True,
    default) or after CutMix/MixUp — the reference's toggle
    (input_pipeline.py:180-182, 218-222). The after-mix path re-quantizes
    the mixed images to uint8 for the augment ops, exactly like the
    reference's ``unbatch → augment_normalize → batch`` stage.
    """
    total_batch = int(np.prod(batch_dims))

    if fake_data:
        yield from _fake_batches(batch_dims, image_size, transpose, bfloat16)
        return
    if tf is None:
        raise ImportError("tensorflow required for the real input pipeline")

    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    start, end = _host_shard_range(split, pi, pc)

    if source is not None:
        ds = _memory_source(source[0], source[1], start, end)
    elif data_dir is None:
        raise ValueError("need data_dir (TFDS/TFRecord) or source=(images, labels)")
    else:
        try:
            ds = _tfds_source(split, data_dir, start, end, is_training)
        except ImportError:
            ds = _tfrecord_source(split, data_dir, start, end)

    options = tf.data.Options()
    options.threading.private_threadpool_size = 48
    options.threading.max_intra_op_parallelism = 1
    options.experimental_optimization.map_parallelization = True
    if is_training:
        options.deterministic = False
    ds = ds.with_options(options)

    spec = None
    if is_training:
        from sav_tpu.data.augment_spec import parse_augment_spec

        spec = parse_augment_spec(augment_name)
        if pc > 1:
            # Multi-host training: cache the decoded-source shard on this
            # host before repeat/shuffle (input_pipeline.py:143-145) — each
            # host re-reads only memory after epoch 1.
            ds = ds.cache()
        ds = ds.repeat()
        ds = ds.shuffle(
            shuffle_buffer if shuffle_buffer is not None else 10 * total_batch,
            seed=seed,
        )
    # Eval: no repeat; partial final batches are kept for flat batch_dims
    # (the trainer pads + masks them, so any mesh shape works) and dropped
    # for nested batch_dims (a partial batch can't fill the device grid).
    # The reference instead hard-errored on non-divisible eval sizes
    # (input_pipeline.py:150-152), which crashed the shipped defaults.

    def _augment(image):
        """RA/AA on a single uint8 HWC image."""
        if spec.randaugment is not None:
            from sav_tpu.data.autoaugment import distort_image_with_randaugment

            layers, mag = spec.randaugment
            return distort_image_with_randaugment(image, layers, mag)
        if spec.autoaugment:
            from sav_tpu.data.autoaugment import distort_image_with_autoaugment

            return distort_image_with_autoaugment(image)
        return image

    aug_after_mix = (
        is_training
        and not augment_before_mix
        and spec.mixes
        and (spec.randaugment is not None or spec.autoaugment)
    )

    def preprocess(example):
        if is_training:
            image = _train_preprocess(example["image_bytes"], image_size)
            if not aug_after_mix:
                image = _augment(image)
        else:
            image = _eval_preprocess(example["image_bytes"], image_size, eval_preproc)
        return {"images": image, "labels": tf.cast(example["label"], tf.int32)}

    ds = ds.map(preprocess, num_parallel_calls=tf.data.AUTOTUNE)
    drop_remainder = is_training or len(batch_dims) > 1
    ds = ds.batch(total_batch, drop_remainder=drop_remainder)

    if is_training and spec is not None and spec.mixes:
        from sav_tpu.data.mix import apply_mixes

        # Mixes run on 0..255 floats before normalization (commutes with the
        # per-channel affine normalize — see sav_tpu/data/mix.py).
        ds = ds.map(
            lambda b: apply_mixes(b, spec), num_parallel_calls=tf.data.AUTOTUNE
        )
        if aug_after_mix:
            # Reference's augment-after-mix stage (input_pipeline.py:218-222):
            # re-quantize each mixed image to uint8, augment, rebatch.
            def requant_augment(example):
                image = tf.cast(
                    tf.clip_by_value(example["images"], 0.0, 255.0), tf.uint8
                )
                return dict(example, images=_augment(image))

            ds = (
                ds.unbatch()
                .map(requant_augment, num_parallel_calls=tf.data.AUTOTUNE)
                .batch(total_batch, drop_remainder=True)
            )

    def finalize(batch):
        batch = dict(batch)
        batch["images"] = _normalize(batch["images"])
        images = batch["images"]
        lead = list(batch_dims)
        if len(lead) > 1:
            # Nested batch: [d0, ..., H, W, C]; with transpose the innermost
            # batch dim moves after the image dims → [d0, H, W, C, d1]
            # (the reference's per-device HWCN layout, input_pipeline.py:226-227).
            images = tf.reshape(images, lead + images.shape.as_list()[1:])
            if transpose:
                rank = len(lead) + 3
                perm = list(range(len(lead) - 1)) + [
                    *range(len(lead), rank),
                    len(lead) - 1,
                ]
                images = tf.transpose(images, perm)
            batch["labels"] = tf.reshape(batch["labels"], lead)
            for k in ("mix_labels", "ratio"):
                if k in batch:
                    batch[k] = tf.reshape(batch[k], lead)
        elif transpose:
            images = tf.transpose(images, [1, 2, 3, 0])  # HWCN
        batch["images"] = images
        return batch

    ds = ds.map(finalize, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.prefetch(tf.data.AUTOTUNE)

    if bfloat16 and _BF16 is not None:
        # Late cast on the host halves host→device bytes (the reference's
        # bf16 view fix-up, input_pipeline.py:238-243); the native loader
        # core does it threaded with the GIL released when built.
        from sav_tpu.data.native_loader import f32_to_bf16

        def _cast(b):
            b["images"] = f32_to_bf16(b["images"])
            return b
    else:
        _cast = lambda b: b

    for batch in ds.as_numpy_iterator():
        yield _cast(dict(batch))


def _fake_batches(batch_dims, image_size, transpose, bfloat16):
    lead = list(batch_dims)
    img = [image_size, image_size, 3]
    if transpose:
        # Same layouts as the real path: flat → HWCN; nested → [d0, H, W, C, d1].
        shape = img + [lead[0]] if len(lead) == 1 else lead[:-1] + img + [lead[-1]]
    else:
        shape = lead + img
    dtype = _BF16 if (bfloat16 and _BF16 is not None) else np.float32
    images = np.zeros(shape, dtype)
    labels = np.zeros(lead, np.int32)
    while True:
        yield {"images": images, "labels": labels}
