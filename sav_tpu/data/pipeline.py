"""tf.data input pipeline.

Capability parity with the reference's DeepMind-lineage ImageNet pipeline
(/root/reference/input_pipeline.py, SURVEY.md §2.4), TPU-first:

  - ``Split`` enum with the same example-count semantics (VALID carved from
    the TFDS train split, TEST = TFDS validation).
  - per-host data sharding (``np.array_split`` over example ranges →
    TFDS ReadInstruction / per-host file sharding).
  - JPEG-bytes cropping: crops computed on raw bytes via
    ``tf.image.decode_and_crop_jpeg`` so full decode never happens
    (input_pipeline.py:126, 536-544 — a real throughput optimization).
  - Inception-style distorted-bbox random crop + flip + bicubic resize;
    ``crop_resize`` / ``resize_crop_{pct}`` eval preprocessing.
  - RandAugment / AutoAugment on uint8, CutMix/MixUp on normalized floats,
    augment-string DSL (:mod:`sav_tpu.data.augment_spec`).
  - double-transpose trick (images emitted HWCN) + late bf16 cast on the
    host (halves host→device bytes; the model transposes back on-device).

Sources: TFDS when installed, a TFRecord directory, or an in-memory
``(images, labels)`` pair (JPEG-encoded on the fly so tests exercise the
real bytes path). ``fake_data=True`` yields correctly-shaped zero batches
without any backing data (input_pipeline.py:104-113 parity).
"""

from __future__ import annotations

import enum
from typing import Generator, Optional, Sequence

import numpy as np

# TF is only needed for the real pipeline, not for fake data; the guarded
# import hides accelerators from TF (see sav_tpu/data/_tf.py).
from sav_tpu.data._tf import tf

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

# Canonical values live in the TF-free constants module (the device
# preprocessing path imports them without TF); re-exported here for the
# existing import surface.
from sav_tpu.data.constants import MEAN_RGB, STDDEV_RGB  # noqa: E402


class Split(enum.Enum):
    """ImageNet splits (input_pipeline.py:38-62 semantics)."""

    TRAIN = 1
    TRAIN_AND_VALID = 2
    VALID = 3
    TEST = 4

    @property
    def num_examples(self) -> int:
        return {
            Split.TRAIN: 1_271_167,
            Split.TRAIN_AND_VALID: 1_281_167,
            Split.VALID: 10_000,
            Split.TEST: 50_000,
        }[self]


def _host_shard_range(
    split: Split,
    process_index: int,
    process_count: int,
    split_examples: Optional[int] = None,
) -> tuple[int, int]:
    """[start, end) absolute example indices for this host
    (input_pipeline.py:369-380 behavior). ``split_examples`` overrides the
    ImageNet-sized split for custom TFRecord datasets."""
    n = split.num_examples if split_examples is None else split_examples
    arange = np.arange(n)
    shard = np.array_split(arange, process_count)[process_index]
    # VALID lives at the tail of TRAIN_AND_VALID (train[:10000] carve-out in
    # the reference is from the front of tfds train; we use offsets below).
    return int(shard[0]), int(shard[-1]) + 1


# --------------------------------------------------------------- decoding


def _distorted_bbox_crop_window(
    image_bytes: "tf.Tensor", stateless_seed=None,
    area_range: tuple = (0.08, 1.0),
) -> "tf.Tensor":
    """Inception-style random crop window on raw JPEG bytes
    (input_pipeline.py:479-497). With ``stateless_seed`` the draw is a pure
    function of the seed (``sample_distorted_bounding_box`` ignores the
    graph-level seed, so replayable pipelines must use the stateless op).
    ``area_range`` is the reference's hard-coded (0.08, 1.0); small-image
    datasets want a gentler floor (timm's configurable ``scale``)."""
    shape = tf.image.extract_jpeg_shape(image_bytes)
    bbox = tf.constant([0.0, 0.0, 1.0, 1.0], shape=[1, 1, 4])
    kwargs = dict(
        bounding_boxes=bbox,
        min_object_covered=0.1,
        aspect_ratio_range=(3.0 / 4.0, 4.0 / 3.0),
        area_range=tuple(area_range),
        use_image_if_no_bounding_boxes=True,
    )
    if stateless_seed is not None:
        begin, size, _ = tf.image.stateless_sample_distorted_bounding_box(
            shape, seed=stateless_seed, **kwargs
        )
    else:
        begin, size, _ = tf.image.sample_distorted_bounding_box(
            shape, max_attempts=10, **kwargs
        )
    y, x, _ = tf.unstack(begin)
    h, w, _ = tf.unstack(size)
    return tf.stack([y, x, h, w])


def _center_crop_window(image_bytes, image_size: int):
    """Aspect-preserving center crop padded by 32px (input_pipeline.py:500-524)."""
    shape = tf.image.extract_jpeg_shape(image_bytes)
    h, w = shape[0], shape[1]
    ratio = tf.cast(image_size, tf.float32) / (tf.cast(image_size, tf.float32) + 32.0)
    crop = tf.cast(
        ratio * tf.cast(tf.minimum(h, w), tf.float32), tf.int32
    )
    y = (h - crop + 1) // 2
    x = (w - crop + 1) // 2
    return tf.stack([y, x, crop, crop])


def _decode_crop(image_bytes, window):
    return tf.image.decode_and_crop_jpeg(image_bytes, window, channels=3)


def _resize_bicubic(image, image_size: int):
    out = tf.image.resize(
        tf.cast(image, tf.float32), [image_size, image_size], tf.image.ResizeMethod.BICUBIC
    )
    return tf.cast(tf.clip_by_value(out, 0.0, 255.0), tf.uint8)


def _train_preprocess(image_bytes, image_size: int, stateless_seed=None,
                      area_range: tuple = (0.08, 1.0), random_flip: bool = True):
    if stateless_seed is None:
        window = _distorted_bbox_crop_window(image_bytes, area_range=area_range)
        image = _decode_crop(image_bytes, window)
        if random_flip:
            image = tf.image.random_flip_left_right(image)
    else:
        window = _distorted_bbox_crop_window(
            image_bytes, stateless_seed=stateless_seed, area_range=area_range
        )
        image = _decode_crop(image_bytes, window)
        if random_flip:
            image = tf.image.stateless_random_flip_left_right(
                image, seed=stateless_seed + tf.constant([0, 1], tf.int64)
            )
    return _resize_bicubic(image, image_size)


def _eval_preprocess(image_bytes, image_size: int, eval_preproc: str):
    if eval_preproc == "crop_resize":
        image = _decode_crop(image_bytes, _center_crop_window(image_bytes, image_size))
        return _resize_bicubic(image, image_size)
    if eval_preproc.startswith("resize_crop_"):
        # Resize so that image_size/pct fits, then center-crop to image_size
        # (input_pipeline.py:547-566).
        pct = float(eval_preproc[len("resize_crop_") :])
        image = tf.io.decode_jpeg(image_bytes, channels=3)
        resize_to = tf.cast(tf.cast(image_size, tf.float32) / pct, tf.int32)
        image = tf.image.resize(
            tf.cast(image, tf.float32), [resize_to, resize_to], tf.image.ResizeMethod.BICUBIC
        )
        image = tf.image.resize_with_crop_or_pad(image, image_size, image_size)
        return tf.cast(tf.clip_by_value(image, 0.0, 255.0), tf.uint8)
    raise ValueError(f"unknown eval_preproc {eval_preproc!r}")


def _normalize(image):
    image = tf.cast(image, tf.float32)
    image = image - tf.constant(MEAN_RGB, shape=[1, 1, 3])
    return image / tf.constant(STDDEV_RGB, shape=[1, 1, 3])


# ----------------------------------------------------------------- sources


def _tfds_source(split: Split, data_dir, start: int, end: int, is_training: bool):
    import tensorflow_datasets as tfds

    if split in (Split.TRAIN, Split.TRAIN_AND_VALID, Split.VALID):
        base = "train"
        # VALID is the reference's train[:10000] carve-out; TRAIN skips it.
        offset = 0 if split is Split.VALID else (
            10_000 if split is Split.TRAIN else 0
        )
    else:
        base, offset = "validation", 0
    instruction = tfds.core.ReadInstruction(
        base, from_=start + offset, to=end + offset, unit="abs"
    )
    ds = tfds.load(
        "imagenet2012:5.*.*",
        split=instruction,
        data_dir=data_dir,
        decoders={"image": tfds.decode.SkipDecoding()},
        shuffle_files=is_training,
    )
    return ds.map(lambda d: {"image_bytes": d["image"], "label": d["label"]})


def _tfrecord_source(split: Split, data_dir: str, start: int, end: int,
                     custom_size: bool = False):
    """Deterministic record stream with the same carve-out/range semantics as
    the TFDS path: VALID = first 10k of the train stream, TRAIN skips them,
    and [start, end) is this host's shard within the split. With
    ``custom_size`` (a non-ImageNet dataset via ``split_examples``) the
    VALID carve-out is disabled — the files hold exactly the split."""
    pattern = {
        Split.TRAIN: "train-*",
        Split.TRAIN_AND_VALID: "train-*",
        Split.VALID: "train-*",
        Split.TEST: "validation-*",
    }[split]
    files = tf.io.gfile.glob(f"{data_dir.rstrip('/')}/{pattern}")
    if not files:
        raise FileNotFoundError(f"no TFRecords matching {pattern} under {data_dir}")
    # Files read in sorted order, sequentially, so absolute example indices
    # are stable across hosts (shuffling happens later, after sharding).
    ds = tf.data.TFRecordDataset(sorted(files))
    offset = 10_000 if (split is Split.TRAIN and not custom_size) else 0
    ds = ds.skip(offset + start).take(end - start)
    features = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/class/label": tf.io.FixedLenFeature([], tf.int64),
    }

    def parse(record):
        ex = tf.io.parse_single_example(record, features)
        # ImageNet TFRecords label in [1, 1000] → [0, 999]; custom datasets
        # write 0-indexed labels.
        shift = 0 if custom_size else 1
        return {
            "image_bytes": ex["image/encoded"],
            "label": tf.cast(ex["image/class/label"], tf.int32) - shift,
        }

    return ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)


def _memory_source(images: np.ndarray, labels: np.ndarray, start: int, end: int):
    """In-memory uint8 images, JPEG-encoded so the bytes path is exercised."""
    end = min(end, len(images))
    start = min(start, end)
    encoded = [
        tf.io.encode_jpeg(images[i]).numpy() for i in range(start, end)
    ]
    ds = tf.data.Dataset.from_tensor_slices(
        {
            "image_bytes": tf.constant(encoded),
            "label": tf.constant(labels[start:end], tf.int32),
        }
    )
    return ds


# -------------------------------------------------------------------- load


def load(
    split: Split,
    *,
    data_dir: Optional[str] = None,
    source: Optional[tuple[np.ndarray, np.ndarray]] = None,
    is_training: bool,
    batch_dims: Sequence[int],
    image_size: int = 224,
    augment_name: Optional[str] = None,
    eval_preproc: str = "crop_resize",
    augment_before_mix: bool = True,
    transpose: bool = False,
    bfloat16: bool = False,
    fake_data: bool = False,
    shuffle_buffer: Optional[int] = None,
    seed: Optional[int] = None,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
    epoch_mode: bool = False,
    strict_determinism: bool = False,
    split_examples: Optional[int] = None,
    crop_area_range: tuple = (0.08, 1.0),
    random_flip: bool = True,
    device_preprocess: bool = False,
) -> Generator[dict, None, None]:
    """Build the input generator. See module docstring.

    ``device_preprocess``: stop host work after the augment stage and emit
    **uint8** images — normalize and CutMix/MixUp then run inside the
    jitted train step (``TrainConfig.device_preprocess``,
    sav_tpu/ops/preprocess.py). 4x fewer host->device bytes than f32 and
    the host sheds its normalize/mix arithmetic. Mixed-image requantization
    makes it incompatible with ``augment_before_mix=False``.

    ``batch_dims``: leading batch shape, outermost first (reference
    semantics: ``[local_devices, per_device_bs]``; pjit callers typically
    pass a single global-per-host dim).

    ``augment_before_mix``: apply RandAugment/AutoAugment before (True,
    default) or after CutMix/MixUp — the reference's toggle
    (input_pipeline.py:180-182, 218-222). The after-mix path re-quantizes
    the mixed images to uint8 for the augment ops, exactly like the
    reference's ``unbatch → augment_normalize → batch`` stage.

    ``epoch_mode``: yield exactly one epoch (no ``.repeat()``) with
    deterministic example order for the given ``seed`` — the building block
    for preemption-safe resume (:func:`resumable_train_iterator`). With
    ``strict_determinism`` the preprocess map also runs serially so the
    stateful TF augmentation draws replay bit-exactly (slower; without it
    the batch *composition* is deterministic but augment draws are not —
    the same guarantee PyTorch-style loader resume gives).
    """
    total_batch = int(np.prod(batch_dims))

    if fake_data:
        yield from _fake_batches(
            batch_dims, image_size, transpose, bfloat16, device_preprocess
        )
        return
    if tf is None:
        raise ImportError("tensorflow required for the real input pipeline")

    import jax

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    start, end = _host_shard_range(split, pi, pc, split_examples)

    if source is not None:
        ds = _memory_source(source[0], source[1], start, end)
    elif data_dir is None:
        raise ValueError("need data_dir (TFDS/TFRecord) or source=(images, labels)")
    elif split_examples is not None:
        ds = _tfrecord_source(split, data_dir, start, end, custom_size=True)
    else:
        try:
            ds = _tfds_source(split, data_dir, start, end, is_training)
        except ImportError:
            ds = _tfrecord_source(split, data_dir, start, end)

    if epoch_mode and is_training:
        # Deterministic op-level seeds for this (pipeline, seed) build; the
        # map stages below draw from stateful TF RNG ops whose seeds derive
        # from this graph-level seed.
        tf.random.set_seed(seed if seed is not None else 0)

    options = tf.data.Options()
    options.threading.private_threadpool_size = 48
    options.threading.max_intra_op_parallelism = 1
    options.experimental_optimization.map_parallelization = True
    if is_training:
        options.deterministic = bool(epoch_mode)
    ds = ds.with_options(options)

    map_calls = 1 if (epoch_mode and strict_determinism) else tf.data.AUTOTUNE

    spec = None
    if is_training:
        from sav_tpu.data.augment_spec import parse_augment_spec

        spec = parse_augment_spec(augment_name)
        if epoch_mode:
            # Stable per-example ids key the stateless augmentation draws
            # below; assigned on the sharded source so an id always names
            # the same example.
            ds = ds.enumerate().map(
                lambda i, ex: dict(ex, _index=i), num_parallel_calls=tf.data.AUTOTUNE
            )
        if pc > 1 and not epoch_mode:
            # Multi-host training: cache the decoded-source shard on this
            # host before repeat/shuffle (input_pipeline.py:143-145) — each
            # host re-reads only memory after epoch 1. Skipped in epoch_mode:
            # the resumable iterator rebuilds a fresh pipeline per epoch, so
            # a cache would be filled once and thrown away.
            ds = ds.cache()
        if not epoch_mode:
            ds = ds.repeat()
        ds = ds.shuffle(
            shuffle_buffer if shuffle_buffer is not None else 10 * total_batch,
            seed=seed,
            reshuffle_each_iteration=not epoch_mode,
        )
    # Eval: no repeat; partial final batches are kept for flat batch_dims
    # (the trainer pads + masks them, so any mesh shape works) and dropped
    # for nested batch_dims (a partial batch can't fill the device grid).
    # The reference instead hard-errored on non-divisible eval sizes
    # (input_pipeline.py:150-152), which crashed the shipped defaults.

    def _augment(image):
        """RA/AA on a single uint8 HWC image."""
        if spec.randaugment is not None:
            from sav_tpu.data.autoaugment import distort_image_with_randaugment

            layers, mag = spec.randaugment
            return distort_image_with_randaugment(image, layers, mag)
        if spec.autoaugment:
            from sav_tpu.data.autoaugment import distort_image_with_autoaugment

            return distort_image_with_autoaugment(image)
        return image

    aug_after_mix = (
        is_training
        and not augment_before_mix
        and spec.mixes
        and (spec.randaugment is not None or spec.autoaugment)
    )
    if device_preprocess and aug_after_mix:
        raise ValueError(
            "device_preprocess moves CutMix/MixUp into the jitted step, so "
            "the host cannot re-augment mixed images; use "
            "augment_before_mix=True (default) with device_preprocess"
        )

    def preprocess(example):
        if is_training:
            sseed = None
            if epoch_mode:
                base = tf.cast(seed if seed is not None else 0, tf.int64)
                sseed = tf.stack(
                    [base, tf.cast(example["_index"], tf.int64) * 2]
                )
            image = _train_preprocess(
                example["image_bytes"], image_size, stateless_seed=sseed,
                area_range=crop_area_range, random_flip=random_flip,
            )
            if not aug_after_mix:
                image = _augment(image)
        else:
            image = _eval_preprocess(example["image_bytes"], image_size, eval_preproc)
        return {"images": image, "labels": tf.cast(example["label"], tf.int32)}

    ds = ds.map(preprocess, num_parallel_calls=map_calls)
    drop_remainder = is_training or len(batch_dims) > 1
    ds = ds.batch(total_batch, drop_remainder=drop_remainder)

    if is_training and spec is not None and spec.mixes and not device_preprocess:
        from sav_tpu.data.mix import apply_mixes

        # Mixes run on 0..255 floats before normalization (commutes with the
        # per-channel affine normalize — see sav_tpu/data/mix.py).
        ds = ds.map(lambda b: apply_mixes(b, spec), num_parallel_calls=map_calls)
        if aug_after_mix:
            # Reference's augment-after-mix stage (input_pipeline.py:218-222):
            # re-quantize each mixed image to uint8, augment, rebatch.
            def requant_augment(example):
                image = tf.cast(
                    tf.clip_by_value(example["images"], 0.0, 255.0), tf.uint8
                )
                return dict(example, images=_augment(image))

            ds = (
                ds.unbatch()
                .map(requant_augment, num_parallel_calls=map_calls)
                .batch(total_batch, drop_remainder=True)
            )

    def finalize(batch):
        batch = dict(batch)
        if device_preprocess:
            # Ship uint8; the jitted step normalizes (+ mixes when
            # training). Post-augment images may already be uint8 (RA/AA
            # output); float crop output is requantized round-to-nearest,
            # bounding the deviation at 0.5/255 — the same quantization
            # the augment stage applies whenever RA/AA runs. NOTE this
            # also covers EVAL batches: the bilinear-resized crop is
            # float, so eval in this mode deviates ≤0.5/255/pixel from
            # the standard path — eval top-1 between modes is equal in
            # expectation but not bit-identical (ADVICE r3).
            if batch["images"].dtype != tf.uint8:
                batch["images"] = tf.cast(
                    tf.clip_by_value(tf.round(batch["images"]), 0.0, 255.0),
                    tf.uint8,
                )
        else:
            batch["images"] = _normalize(batch["images"])
        images = batch["images"]
        lead = list(batch_dims)
        if len(lead) > 1:
            # Nested batch: [d0, ..., H, W, C]; with transpose the innermost
            # batch dim moves after the image dims → [d0, H, W, C, d1]
            # (the reference's per-device HWCN layout, input_pipeline.py:226-227).
            images = tf.reshape(images, lead + images.shape.as_list()[1:])
            if transpose:
                rank = len(lead) + 3
                perm = list(range(len(lead) - 1)) + [
                    *range(len(lead), rank),
                    len(lead) - 1,
                ]
                images = tf.transpose(images, perm)
            batch["labels"] = tf.reshape(batch["labels"], lead)
            for k in ("mix_labels", "ratio"):
                if k in batch:
                    batch[k] = tf.reshape(batch[k], lead)
        elif transpose:
            images = tf.transpose(images, [1, 2, 3, 0])  # HWCN
        batch["images"] = images
        return batch

    ds = ds.map(finalize, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.prefetch(tf.data.AUTOTUNE)

    if bfloat16 and _BF16 is not None and not device_preprocess:
        # Late cast on the host halves host→device bytes (the reference's
        # bf16 view fix-up, input_pipeline.py:238-243); the native loader
        # core does it threaded with the GIL released when built.
        from sav_tpu.data.native_loader import f32_to_bf16

        def _cast(b):
            b["images"] = f32_to_bf16(b["images"])
            return b
    else:
        _cast = lambda b: b

    for batch in ds.as_numpy_iterator():
        yield _cast(dict(batch))


def resumable_train_iterator(
    split: Split,
    *,
    start_step: int = 0,
    steps_per_epoch: Optional[int] = None,
    seed: int = 0,
    strict_determinism: bool = False,
    **load_kwargs,
) -> Generator[dict, None, None]:
    """Preemption-safe train stream over per-epoch deterministic pipelines.

    The tf.data equivalent of the SavRecord path's (seed, epoch)-replayable
    iteration (sav_tpu/data/records.py): each epoch e is produced by a fresh
    ``load(..., epoch_mode=True, seed=mix(seed, e))`` pipeline, so a run
    restored at step S rebuilds epoch ``S // steps_per_epoch`` and skips
    ``S % steps_per_epoch`` batches — every example is seen exactly the same
    number of times as the uninterrupted run. The reference's train path
    lost iterator position entirely on preemption (train.py never restored;
    SURVEY.md §5 checkpoint/resume).

    ``steps_per_epoch``: batches per epoch on this host; computed from the
    split size when omitted.

    ``strict_determinism``: also replay the random augmentation draws
    bit-exactly (serial preprocess map — see :func:`load`).
    """
    kwargs = dict(load_kwargs)
    kwargs.pop("epoch_mode", None)
    kwargs.pop("seed", None)
    if steps_per_epoch is None:
        import jax

        pi = kwargs.get("process_index")
        pc = kwargs.get("process_count")
        pi = jax.process_index() if pi is None else pi
        pc = jax.process_count() if pc is None else pc
        start, end = _host_shard_range(split, pi, pc, kwargs.get("split_examples"))
        total_batch = int(np.prod(kwargs["batch_dims"]))
        if "source" in kwargs and kwargs["source"] is not None:
            end = min(end, len(kwargs["source"][0]))
        steps_per_epoch = (end - start) // total_batch
        if steps_per_epoch < 1:
            # epoch_mode drops the remainder, so a shard smaller than one
            # batch would yield nothing and the epoch loop would spin
            # rebuilding pipelines forever.
            raise ValueError(
                f"host shard of {end - start} examples is smaller than the "
                f"per-host batch ({total_batch}); shrink the batch or use "
                "fewer hosts"
            )

    epoch = start_step // steps_per_epoch
    skip = start_step % steps_per_epoch
    while True:
        it = load(
            split,
            is_training=True,
            epoch_mode=True,
            strict_determinism=strict_determinism,
            # Golden-ratio mix keeps per-epoch seeds far apart while staying
            # deterministic in (seed, epoch).
            seed=(seed * 0x9E3779B1 + epoch) % (2**31),
            **kwargs,
        )
        produced = 0
        for batch in it:
            if produced >= steps_per_epoch:
                break  # keep epoch accounting exact even if load() yields more
            if skip > 0:
                skip -= 1
                produced += 1
                continue
            produced += 1
            yield batch
        epoch += 1
        skip = 0


def _fake_batches(batch_dims, image_size, transpose, bfloat16,
                  device_preprocess=False):
    lead = list(batch_dims)
    img = [image_size, image_size, 3]
    if transpose:
        # Same layouts as the real path: flat → HWCN; nested → [d0, H, W, C, d1].
        shape = img + [lead[0]] if len(lead) == 1 else lead[:-1] + img + [lead[-1]]
    else:
        shape = lead + img
    if device_preprocess:  # real path ships uint8 in this mode
        dtype = np.uint8
    else:
        dtype = _BF16 if (bfloat16 and _BF16 is not None) else np.float32
    images = np.zeros(shape, dtype)
    labels = np.zeros(lead, np.int32)
    while True:
        yield {"images": images, "labels": labels}
