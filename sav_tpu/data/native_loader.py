"""ctypes bindings for the native loader core + a threaded prefetcher.

The C++ library (``native/loader.cc``) accelerates the host-side hot loop of
the input pipeline — normalize, HWCN transpose, late bf16 cast, batch gather
(/root/reference/input_pipeline.py:187-196, 226-243 equivalents). Every entry
point has a numpy fallback so the framework works without the build step;
``native_available()`` reports which path is active. ctypes calls release
the GIL, so the ``PrefetchLoader`` worker threads overlap this byte work
with device compute.

Build once: ``make -C native`` (plain g++, no pybind11 dependency).
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional

import numpy as np

from sav_tpu.data.feeder import DeviceFeeder

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "libsavtpu_loader.so",
)
_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB_PATH):
        return None
    lib = ctypes.CDLL(_LIB_PATH)
    lib.sav_loader_abi_version.restype = ctypes.c_int
    if lib.sav_loader_abi_version() != 1:  # pragma: no cover
        return None
    c_f32p = ctypes.POINTER(ctypes.c_float)
    c_u8p = ctypes.POINTER(ctypes.c_uint8)
    c_u16p = ctypes.POINTER(ctypes.c_uint16)
    c_i32p = ctypes.POINTER(ctypes.c_int32)
    lib.sav_normalize_batch.argtypes = [
        c_u8p, c_f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, c_f32p, c_f32p, ctypes.c_int, ctypes.c_int,
    ]
    lib.sav_f32_to_bf16.argtypes = [c_f32p, c_u16p, ctypes.c_int64, ctypes.c_int]
    lib.sav_gather_batch.argtypes = [
        c_u8p, c_i32p, c_u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int,
    ]
    lib.sav_transpose_nhwc_to_hwcn.argtypes = [
        c_f32p, c_f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int,
    ]
    # Added after the v1 release of the ABI; same-version .so files built
    # before it simply lack the symbol (backward-compatible addition), so
    # probe instead of bumping the version and orphaning older builds.
    if hasattr(lib, "sav_u8_passthrough_batch"):
        lib.sav_u8_passthrough_batch.argtypes = [
            c_u8p, c_u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, c_u8p, ctypes.c_int,
        ]
    _lib = lib
    return lib


def native_available() -> bool:
    return _load() is not None


def _threads(n: Optional[int]) -> int:
    return n if n is not None else min(8, os.cpu_count() or 1)


def normalize_batch(
    images: np.ndarray,
    mean,
    stddev,
    *,
    transpose: bool = False,
    num_threads: Optional[int] = None,
) -> np.ndarray:
    """uint8 [N,H,W,C] → normalized float32 ([N,H,W,C] or HWCN)."""
    assert images.dtype == np.uint8 and images.ndim == 4
    n, h, w, c = images.shape
    lib = _load()
    # Broadcast scalars/short vectors up front so the C kernel always sees
    # exactly C contiguous floats (the numpy fallback would broadcast anyway).
    mean = np.ascontiguousarray(np.broadcast_to(np.asarray(mean, np.float32), (c,)))
    stddev = np.ascontiguousarray(
        np.broadcast_to(np.asarray(stddev, np.float32), (c,))
    )
    if lib is None:
        out = (images.astype(np.float32) - mean) / stddev
        return np.transpose(out, (1, 2, 3, 0)) if transpose else out
    images = np.ascontiguousarray(images)
    out_shape = (h, w, c, n) if transpose else (n, h, w, c)
    out = np.empty(out_shape, np.float32)
    lib.sav_normalize_batch(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, h, w, c,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        stddev.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(transpose), _threads(num_threads),
    )
    return out


def f32_to_bf16(x: np.ndarray, *, num_threads: Optional[int] = None) -> np.ndarray:
    """float32 → bfloat16 (round-to-nearest-even), threaded."""
    if _BF16 is None:  # pragma: no cover
        raise RuntimeError("ml_dtypes unavailable")
    lib = _load()
    x = np.ascontiguousarray(x, np.float32)
    if lib is None:
        return x.astype(_BF16)
    out = np.empty(x.shape, np.uint16)
    lib.sav_f32_to_bf16(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
        x.size, _threads(num_threads),
    )
    return out.view(_BF16)


def passthrough_batch_u8(
    images: np.ndarray,
    *,
    flip: Optional[np.ndarray] = None,
    num_threads: Optional[int] = None,
) -> np.ndarray:
    """uint8 [N,H,W,C] → uint8 [N,H,W,C]: the wire-format passthrough.

    The uint8-on-the-wire output mode (``savrec_train_iterator(
    normalize=False)`` / ``TrainConfig.device_preprocess``) ships raw
    post-augment bytes — half the bytes of late-bf16, a quarter of f32 —
    and its only remaining host transform is assembling a contiguous
    batch with the per-image horizontal flips applied. This does exactly
    that in threaded C++ (GIL released), sitting next to
    :func:`f32_to_bf16` as the uint8 counterpart of the late-cast stage.

    ``flip``: optional bool/uint8 [N] mask; True reverses the W axis of
    that image. None copies straight through.
    """
    assert images.dtype == np.uint8 and images.ndim == 4
    n, h, w, c = images.shape
    lib = _load()
    if flip is not None:
        flip = np.ascontiguousarray(
            np.asarray(flip).astype(np.uint8).reshape(n)
        )
    if lib is None or not hasattr(lib, "sav_u8_passthrough_batch"):
        if flip is None:
            # Always a fresh buffer, matching the native path — callers may
            # mutate the batch while the source is a reused pool/mmap view.
            return images.copy(order="C")
        return np.where(
            flip.astype(bool)[:, None, None, None], images[:, :, ::-1], images
        )
    images = np.ascontiguousarray(images)
    out = np.empty_like(images)
    lib.sav_u8_passthrough_batch(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, h, w, c,
        None if flip is None
        else flip.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        _threads(num_threads),
    )
    return out


def gather_batch(
    pool: np.ndarray, indices: np.ndarray, *, num_threads: Optional[int] = None
) -> np.ndarray:
    """out[i] = pool[indices[i]] for contiguous fixed-size items.

    Indices must be in ``[0, len(pool))`` — negative (numpy-wrap) indices are
    rejected so the native memcpy path and the numpy fallback agree.
    """
    lib = _load()
    indices = np.ascontiguousarray(indices, np.int32)
    if indices.size and (indices.min() < 0 or indices.max() >= len(pool)):
        raise IndexError(
            f"indices out of range [0, {len(pool)}): "
            f"[{indices.min()}, {indices.max()}]"
        )
    if lib is None:
        return pool[indices].copy()
    pool = np.ascontiguousarray(pool)
    item_bytes = pool[0].nbytes
    out = np.empty((len(indices),) + pool.shape[1:], pool.dtype)
    lib.sav_gather_batch(
        pool.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(indices), item_bytes, _threads(num_threads),
    )
    return out


def transpose_nhwc_to_hwcn(
    x: np.ndarray, *, num_threads: Optional[int] = None
) -> np.ndarray:
    lib = _load()
    x = np.ascontiguousarray(x, np.float32)
    if lib is None:
        return np.transpose(x, (1, 2, 3, 0)).copy()
    n, h, w, c = x.shape
    out = np.empty((h, w, c, n), np.float32)
    lib.sav_transpose_nhwc_to_hwcn(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, h, w, c, _threads(num_threads),
    )
    return out


class PrefetchLoader(DeviceFeeder):
    """Bounded background prefetch over any batch iterator.

    The tf.data path has its own C++ prefetch; this covers every other
    source (synthetic, native-assembled, custom) so host work overlaps
    device steps. Iteration order is preserved (single worker per iterator
    semantics; the byte-heavy transforms above run with the GIL released).

    A thin host-only view of :class:`~sav_tpu.data.feeder.DeviceFeeder`
    (``transform`` is its ``place_fn``) so the bounded-queue / drain /
    error-propagation state machine lives in exactly one place; it also
    inherits ``close()`` and a worker that stays responsive to it instead
    of wedging on a full queue.
    """

    def __init__(self, iterator: Iterator[dict], *, depth: int = 2, transform=None):
        super().__init__(
            iterator,
            transform if transform is not None else lambda item: item,
            depth=depth,
            name="prefetch-loader",
        )
