"""SavRecord: the framework's native on-disk dataset format.

The reference fed ImageNet through TF's C++ tf.data/TFRecord runtime
(SURVEY.md §2.8); SavRecord is sav_tpu's own equivalent: a mmap'd
fixed-shape image/label container read by the threaded C++ gather in
``native/records.cc`` (ctypes, GIL released), with a pure-numpy fallback so
everything works without the build step. Python owns the *policy* — epoch
shuffling, per-host sharding (the ``np.array_split`` semantics of the
reference's ``_shard``, input_pipeline.py:369-380), batch assembly — and
C++ owns the byte movement.

Format v1 (little-endian): see native/records.cc header comment. Fixed
image shape per file, int32 labels; the offsets table already supports
variable-length records for a future JPEG-bytes variant.

Usage::

    write_savrec("train.savrec", images_u8, labels)
    ds = SavRecDataset("train.savrec")
    for batch in savrec_epoch_iterator(ds, batch_size=256, seed=0,
                                       host_id=0, host_count=1):
        ...  # {'images': u8 [B,H,W,C], 'labels': i32 [B]}
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Iterator, Optional

import numpy as np

from sav_tpu.data import native_loader as _nl

_MAGIC = b"SAVREC01"
_HEADER = struct.Struct("<8sII Q IIII")  # magic, version, reserved, n, h, w, c, label_bytes


def write_savrec(path: str, images: np.ndarray, labels: np.ndarray) -> None:
    """Serialize uint8 images ``[N,H,W,C]`` + int labels ``[N]`` to ``path``."""
    images = np.ascontiguousarray(images, np.uint8)
    labels = np.ascontiguousarray(labels, np.int32)
    if images.ndim != 4 or labels.shape != (images.shape[0],):
        raise ValueError(
            f"expected images [N,H,W,C] u8 and labels [N], got "
            f"{images.shape} / {labels.shape}"
        )
    n, h, w, c = images.shape
    image_bytes = h * w * c
    rec_bytes = image_bytes + 4
    offsets = np.arange(n + 1, dtype=np.uint64) * rec_bytes
    tmp = path + ".tmp"
    # Interleave image+label bytes in fixed-size chunks so peak extra memory
    # stays O(chunk), not O(dataset) (ImageNet-scale files are 100s of GB).
    chunk = max(1, (64 << 20) // rec_bytes)
    with open(tmp, "wb") as f:
        f.write(_HEADER.pack(_MAGIC, 1, 0, n, h, w, c, 4))
        offsets.tofile(f)
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            payload = np.empty((hi - lo, rec_bytes), np.uint8)
            payload[:, :image_bytes] = images[lo:hi].reshape(hi - lo, image_bytes)
            payload[:, image_bytes:] = labels[lo:hi].view(np.uint8).reshape(
                hi - lo, 4
            )
            payload.tofile(f)
    os.replace(tmp, path)


class SavRecDataset:
    """Random-access reader; native mmap+threads when built, numpy otherwise."""

    def __init__(self, path: str, *, num_threads: Optional[int] = None):
        self.path = path
        self._threads = num_threads
        self._handle = None
        lib = _nl._load()
        if lib is not None and hasattr(lib, "sav_rec_open"):
            self._bind(lib)
            handle = lib.sav_rec_open(path.encode())
            if not handle:
                raise ValueError(f"not a readable SavRecord v1 file: {path}")
            self._handle = handle
            self._lib = lib
            meta = (ctypes.c_int64 * 4)()
            lib.sav_rec_meta(handle, meta)
            self._n = int(lib.sav_rec_count(handle))
            self.image_shape = (int(meta[0]), int(meta[1]), int(meta[2]))
        else:
            self._open_fallback(path)

    @staticmethod
    def _bind(lib) -> None:
        if getattr(lib, "_savrec_bound", False):
            return
        lib.sav_rec_open.restype = ctypes.c_void_p
        lib.sav_rec_open.argtypes = [ctypes.c_char_p]
        lib.sav_rec_count.restype = ctypes.c_int64
        lib.sav_rec_count.argtypes = [ctypes.c_void_p]
        lib.sav_rec_meta.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)]
        lib.sav_rec_read_batch.restype = ctypes.c_int
        lib.sav_rec_read_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int,
        ]
        lib.sav_rec_close.argtypes = [ctypes.c_void_p]
        lib._savrec_bound = True

    def _open_fallback(self, path: str) -> None:
        with open(path, "rb") as f:
            head = f.read(_HEADER.size)
        if len(head) < _HEADER.size:
            raise ValueError(f"not a readable SavRecord v1 file: {path}")
        magic, version, _, n, h, w, c, label_bytes = _HEADER.unpack(head)
        if magic != _MAGIC or version != 1:
            raise ValueError(f"not a readable SavRecord v1 file: {path}")
        # Same validation as the native open: overflow-safe truncation check
        # plus a full offsets-table scan (monotonic, fixed record size).
        file_len = os.path.getsize(path)
        image_bytes = h * w * c
        rec_bytes = image_bytes + label_bytes
        if (
            rec_bytes == 0
            or n > (file_len - _HEADER.size) // 8 - 1
            or file_len < _HEADER.size + (n + 1) * 8 + n * rec_bytes
        ):
            raise ValueError(f"not a readable SavRecord v1 file: {path}")
        offsets = np.memmap(
            path, np.uint64, mode="r", offset=_HEADER.size, shape=(n + 1,)
        )
        if int(offsets[0]) != 0 or not np.all(np.diff(offsets) == rec_bytes):
            raise ValueError(f"not a readable SavRecord v1 file: {path}")
        self._n = int(n)
        self.image_shape = (h, w, c)
        payload_off = _HEADER.size + (n + 1) * 8
        raw = np.memmap(path, np.uint8, mode="r", offset=payload_off)
        self._fallback_records = raw[: n * rec_bytes].reshape(n, rec_bytes)
        self._image_bytes = image_bytes

    def __len__(self) -> int:
        return self._n

    @property
    def native(self) -> bool:
        return self._handle is not None

    def read_batch(self, indices: np.ndarray) -> dict:
        """Gather records by index → ``{'images': u8 [B,H,W,C], 'labels': i32 [B]}``."""
        indices = np.ascontiguousarray(indices, np.int64)
        b = indices.shape[0]
        h, w, c = self.image_shape
        if self._handle is not None:
            images = np.empty((b, h, w, c), np.uint8)
            labels = np.empty((b,), np.int32)
            rc = self._lib.sav_rec_read_batch(
                self._handle,
                indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                b,
                images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                _nl._threads(self._threads),
            )
            if rc != 0:
                raise IndexError(f"record index out of range (0..{self._n - 1})")
        else:
            if indices.min(initial=0) < 0 or indices.max(initial=-1) >= self._n:
                raise IndexError(f"record index out of range (0..{self._n - 1})")
            recs = self._fallback_records[indices]
            images = recs[:, : self._image_bytes].reshape(b, h, w, c).copy()
            labels = recs[:, self._image_bytes :].copy().view(np.int32).reshape(b)
        return {"images": images, "labels": labels}

    def close(self) -> None:
        if self._handle is not None:
            self._lib.sav_rec_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def host_shard_indices(n: int, host_id: int, host_count: int) -> np.ndarray:
    """This host's example indices — ``np.array_split`` semantics, matching
    the reference's per-host TFDS ReadInstruction sharding
    (input_pipeline.py:369-380)."""
    if not 0 <= host_id < host_count:
        raise ValueError(f"host_id {host_id} not in [0, {host_count})")
    return np.array_split(np.arange(n, dtype=np.int64), host_count)[host_id]


def savrec_epoch_iterator(
    dataset: SavRecDataset,
    *,
    batch_size: int,
    shuffle: bool = True,
    seed: int = 0,
    host_id: int = 0,
    host_count: int = 1,
    drop_remainder: bool = True,
    num_epochs: Optional[int] = None,
    start_epoch: int = 0,
) -> Iterator[dict]:
    """Host-sharded, per-epoch-reshuffled batch iterator.

    The shuffle is seeded by ``(seed, epoch)`` so a restored run resumed at
    ``start_epoch`` replays the exact same data order — the data-iterator
    half of preemption-safe resume (the trainer checkpoints the step, which
    determines the epoch).
    """
    shard = host_shard_indices(len(dataset), host_id, host_count)
    if drop_remainder and len(shard) < batch_size:
        raise ValueError(
            f"host shard has {len(shard)} records < batch_size {batch_size} "
            f"with drop_remainder=True — no batch would ever be yielded"
        )
    epoch = start_epoch
    while num_epochs is None or epoch < start_epoch + num_epochs:
        order = shard
        if shuffle:
            rng = np.random.default_rng([seed, epoch])
            order = rng.permutation(shard)
        limit = (len(order) // batch_size) * batch_size if drop_remainder else len(order)
        for lo in range(0, limit, batch_size):
            yield dataset.read_batch(order[lo : lo + batch_size])
        epoch += 1


def savrec_train_iterator(
    dataset: SavRecDataset,
    *,
    batch_size: int,
    normalize: bool = True,
    mean=None,
    stddev=None,
    transpose: bool = False,
    bfloat16: bool = False,
    flip: bool = True,
    **epoch_kwargs,
) -> Iterator[dict]:
    """Trainer-ready batches, end-to-end through the native path.

    C++ record gather → random horizontal flip → C++ normalize (optionally
    fused with the HWCN double-transpose) → C++ late bf16 cast: the full
    reference host hot loop (input_pipeline.py:187-196, 226-243) with zero
    TF dependency. Wrap in :class:`~sav_tpu.data.native_loader.PrefetchLoader`
    to overlap with device compute.
    """
    if transpose and not normalize:
        # The HWCN transpose is fused into the C++ normalize; the raw
        # (device-preprocess) path has no host transpose, and yielding
        # NHWC while the trainer expects HWCN would shard/permute wrongly.
        raise ValueError(
            "transpose=True requires normalize=True (the transpose is fused "
            "into the C++ normalize); the raw uint8 path ships NHWC — use "
            "transpose_images=False with device_preprocess"
        )
    if mean is None or stddev is None:
        from sav_tpu.data.pipeline import MEAN_RGB, STDDEV_RGB

        mean = MEAN_RGB if mean is None else mean
        stddev = STDDEV_RGB if stddev is None else stddev
    seed = epoch_kwargs.pop("seed", 0)
    start_epoch = epoch_kwargs.pop("start_epoch", 0)
    num_epochs = epoch_kwargs.pop("num_epochs", None)
    epoch = start_epoch
    # One epoch at a time so the flip RNG (like the shuffle) is seeded by
    # (seed, epoch) — a run resumed at start_epoch=e replays epoch e exactly.
    while num_epochs is None or epoch < start_epoch + num_epochs:
        flip_rng = np.random.default_rng([seed + 1, epoch])
        for batch in savrec_epoch_iterator(
            dataset, batch_size=batch_size, seed=seed, start_epoch=epoch,
            num_epochs=1, **epoch_kwargs,
        ):
            images = batch["images"]
            if flip:
                # Threaded C++ flip+assemble (GIL released) — on the raw
                # uint8 wire path (normalize=False, the
                # device_preprocess pairing) this is the only host byte
                # transform left, so it must not bounce through
                # numpy/float.
                do = flip_rng.random(images.shape[0]) < 0.5
                images = _nl.passthrough_batch_u8(images, flip=do)
            if normalize:
                images = _nl.normalize_batch(images, mean, stddev, transpose=transpose)
                if bfloat16:
                    images = _nl.f32_to_bf16(images)
            yield {"images": images, "labels": batch["labels"]}
        epoch += 1
