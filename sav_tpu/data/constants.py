"""Normalization constants shared by the host (TF/C++) and device (JAX)
preprocessing paths. Values are the ImageNet channel statistics on the
0..255 scale (reference input_pipeline.py MEAN_RGB/STDDEV_RGB).

TF-free on purpose: the device path (sav_tpu.ops.preprocess) must be
importable without TensorFlow.
"""

MEAN_RGB = (0.485 * 255, 0.456 * 255, 0.406 * 255)
STDDEV_RGB = (0.229 * 255, 0.224 * 255, 0.225 * 255)
