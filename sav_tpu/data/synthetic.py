"""Synthetic / fake data iterators.

Parity with the reference's ``fake_data`` branch (/root/reference/
input_pipeline.py:104-113 — correctly-shaped zero batches used as the
built-in fake backend for driver testing), plus a random-data variant for
train-step smoke tests (loss must decrease on a learnable signal).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def fake_data_iterator(
    *,
    batch_size: int,
    image_size: int = 224,
    num_classes: int = 1000,
    transpose: bool = False,
    dtype=np.float32,
) -> Iterator[dict]:
    """Infinite zero batches with the pipeline's exact output shapes."""
    img_shape = (
        (image_size, image_size, 3, batch_size)
        if transpose
        else (batch_size, image_size, image_size, 3)
    )
    images = np.zeros(img_shape, dtype)
    labels = np.zeros((batch_size,), np.int32)
    while True:
        yield {"images": images, "labels": labels}


def synth_batch(
    *,
    seed: int,
    position: int,
    batch_size: int,
    image_size: int = 32,
    num_classes: int = 10,
    dtype=np.float32,
) -> dict:
    """The deterministic synthetic batch at schedule ``position``.

    Counter-based (Philox keyed on ``(seed, position)``): the batch is a
    pure function of its schedule position, independent of iteration
    history — which makes the stream *resumable by construction* (restart
    at any step and the batches match the uninterrupted run bit-for-bit)
    and lets an external verifier (tools/chaos_soak.py) recompute any
    position's batch, fingerprint it with the flight recorder's blake2b
    machinery, and prove a resumed child picked up step-exact. The class
    id is embedded as a brightness offset (the learnable signal the
    train-step tests rely on), so loss curves carry information.

    Positions are 1-indexed completed-step numbers, matching the
    recorder's ring entries and ``--skip-steps`` semantics.
    """
    key = np.array([seed & 0xFFFFFFFFFFFFFFFF, position], np.uint64)
    rng = np.random.Generator(np.random.Philox(key=key))
    labels = rng.integers(0, num_classes, (batch_size,), dtype=np.int32)
    images = rng.standard_normal(
        (batch_size, image_size, image_size, 3)
    ).astype(np.float32)
    images += (labels[:, None, None, None] / num_classes - 0.5) * 4.0
    return {"images": images.astype(dtype), "labels": labels}


def synth_resumable_iterator(
    *,
    seed: int,
    start_step: int = 0,
    batch_size: int,
    image_size: int = 32,
    num_classes: int = 10,
    num_batches: Optional[int] = None,
    dtype=np.float32,
) -> Iterator[dict]:
    """Infinite (or bounded) stream of :func:`synth_batch` batches from
    position ``start_step + 1`` on — the ``train.py --synth-data`` feed:
    a TF-free, preemption-exact data path for elasticity soaks and
    kill-resume tests (docs/elasticity.md)."""
    position = start_step
    produced = 0
    while num_batches is None or produced < num_batches:
        position += 1
        produced += 1
        yield synth_batch(
            seed=seed,
            position=position,
            batch_size=batch_size,
            image_size=image_size,
            num_classes=num_classes,
            dtype=dtype,
        )


def synthetic_data_iterator(
    *,
    batch_size: int,
    image_size: int = 32,
    num_classes: int = 10,
    transpose: bool = False,
    seed: int = 0,
    num_batches: Optional[int] = None,
    learnable: bool = True,
    dtype=np.float32,
) -> Iterator[dict]:
    """Random images with (optionally) label-correlated signal.

    With ``learnable=True`` the class id is embedded as a constant brightness
    offset, so a model trained on this stream must show decreasing loss —
    the train-step integration test the reference lacked (SURVEY.md §4).
    """
    rng = np.random.default_rng(seed)
    count = 0
    while num_batches is None or count < num_batches:
        images = rng.standard_normal(
            (batch_size, image_size, image_size, 3)
        ).astype(dtype)
        labels = rng.integers(0, num_classes, (batch_size,), dtype=np.int32)
        if learnable:
            images += (labels[:, None, None, None] / num_classes - 0.5) * 4.0
        if transpose:
            images = np.transpose(images, (1, 2, 3, 0))
        yield {"images": images.astype(dtype), "labels": labels}
        count += 1
