"""Guarded TensorFlow import: TF serves host-side data only.

Every sav_tpu module that needs TF imports it from here, so device hiding
runs no matter which entry point loads first. JAX owns the accelerator; a
TF claim on a single-tenant TPU lease can deadlock JAX's device init
outright (the reference fought the milder version of this battle,
/root/reference/input_pipeline.py:228-231).
"""

from __future__ import annotations

import logging

try:
    import tensorflow as tf
except ImportError:  # pragma: no cover
    tf = None

def require_tf():
    """Return the tf module or raise a clear ImportError when TF is absent
    (the guarded import above exports ``tf = None`` instead of raising, so
    downstream modules would otherwise die with a confusing
    ``NoneType has no attribute ...``)."""
    if tf is None:
        raise ImportError(
            "tensorflow is required for sav_tpu's host-side data pipeline "
            "(images ops / mixes / TFRecord reading) but is not installed"
        )
    return tf


if tf is not None:
    for _kind in ("TPU", "GPU"):
        try:
            tf.config.set_visible_devices([], _kind)
        except Exception as e:  # pragma: no cover - env-dependent
            # Most likely "visible devices cannot be modified after being
            # initialized" — the hazard window is real, so say so instead
            # of failing silently.
            logging.getLogger(__name__).warning(
                "could not hide %s devices from TensorFlow (%s); if JAX "
                "device init hangs, import sav_tpu.data before running any "
                "TF op",
                _kind,
                e,
            )
