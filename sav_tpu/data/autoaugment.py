"""RandAugment and AutoAugment-v0 policies over :mod:`sav_tpu.data.image_ops`.

The reference shipped RandAugment only — its pipeline referenced
``distort_image_with_autoaugment`` that was never defined
(/root/reference/input_pipeline.py:428, SURVEY.md §2.9 #10). Both paths work
here. Op selection uses ``tf.switch_case`` (one branch table) instead of the
reference's nested ``tf.cond`` ladder (autoaugment.py:543-564).
"""

from __future__ import annotations

from typing import Callable

from sav_tpu.data._tf import require_tf

tf = require_tf()

from sav_tpu.data import image_ops as ops

_MAX_LEVEL = 10.0


def _mag(level: float, maxval: float) -> float:
    return level / _MAX_LEVEL * maxval


def _signed(value):
    sign = tf.cast(tf.random.uniform([], 0, 2, tf.int32) * 2 - 1, tf.float32)
    return tf.cast(value, tf.float32) * sign


# name -> callable(image, level) applying the op at that magnitude.
def _op_table(cutout_const: int, translate_const: int) -> dict[str, Callable]:
    return {
        "AutoContrast": lambda im, lv: ops.autocontrast(im),
        "Equalize": lambda im, lv: ops.equalize(im),
        "Invert": lambda im, lv: ops.invert(im),
        "Rotate": lambda im, lv: ops.rotate(im, _signed(_mag(lv, 30.0))),
        # Posterize/Solarize keep the published AA magnitude mapping the
        # policies were tuned against (bits = lv/10*4 kept; threshold =
        # lv/10*256 — higher level is *weaker* solarize), matching
        # /root/reference/autoaugment.py:455-467.
        "Posterize": lambda im, lv: ops.posterize(im, int(_mag(lv, 4.0))),
        "Solarize": lambda im, lv: ops.solarize(im, int(_mag(lv, 256.0))),
        "SolarizeAdd": lambda im, lv: ops.solarize_add(im, int(_mag(lv, 110.0))),
        "Color": lambda im, lv: ops.color(im, 1.0 + _signed(_mag(lv, 0.9))),
        "Contrast": lambda im, lv: ops.contrast(im, 1.0 + _signed(_mag(lv, 0.9))),
        "Brightness": lambda im, lv: ops.brightness(im, 1.0 + _signed(_mag(lv, 0.9))),
        "Sharpness": lambda im, lv: ops.sharpness(im, 1.0 + _signed(_mag(lv, 0.9))),
        "ShearX": lambda im, lv: ops.shear_x(im, _signed(_mag(lv, 0.3))),
        "ShearY": lambda im, lv: ops.shear_y(im, _signed(_mag(lv, 0.3))),
        "TranslateX": lambda im, lv: ops.translate_x(
            im, _signed(_mag(lv, float(translate_const)))
        ),
        "TranslateY": lambda im, lv: ops.translate_y(
            im, _signed(_mag(lv, float(translate_const)))
        ),
        "Cutout": lambda im, lv: ops.cutout(im, int(_mag(lv, float(cutout_const)))),
    }


_RANDAUG_OPS = [
    "AutoContrast", "Equalize", "Invert", "Rotate", "Posterize", "Solarize",
    "Color", "Contrast", "Brightness", "Sharpness", "ShearX", "ShearY",
    "TranslateX", "TranslateY", "Cutout", "SolarizeAdd",
]


def distort_image_with_randaugment(
    image: tf.Tensor,
    num_layers: int,
    magnitude: int,
    *,
    cutout_const: int = 40,
    translate_const: int = 100,
) -> tf.Tensor:
    """RandAugment: ``num_layers`` uniformly-chosen ops at fixed magnitude,
    each applied with probability ~U[0.2, 0.8] (reference semantics,
    autoaugment.py:519-565)."""
    table = _op_table(cutout_const, translate_const)
    branches = [
        (lambda name: (lambda im: table[name](im, float(magnitude))))(n)
        for n in _RANDAUG_OPS
    ]
    for _ in range(num_layers):
        op_idx = tf.random.uniform([], 0, len(branches), tf.int32)
        prob = tf.random.uniform([], 0.2, 0.8)
        should = tf.random.uniform([]) < prob
        image = tf.cond(
            should,
            lambda: tf.switch_case(op_idx, [
                (lambda b: (lambda: b(image)))(branch) for branch in branches
            ]),
            lambda: image,
        )
    return image


# AutoAugment ImageNet policy v0 (25 sub-policies of two (op, prob, level)
# steps — the policy published with the AutoAugment paper).
_POLICY_V0 = [
    [("Equalize", 0.8, 1), ("ShearY", 0.8, 4)],
    [("Color", 0.4, 9), ("Equalize", 0.6, 3)],
    [("Color", 0.4, 1), ("Rotate", 0.6, 8)],
    [("Solarize", 0.8, 3), ("Equalize", 0.4, 7)],
    [("Solarize", 0.4, 2), ("Solarize", 0.6, 2)],
    [("Color", 0.2, 0), ("Equalize", 0.8, 8)],
    [("Equalize", 0.4, 8), ("SolarizeAdd", 0.8, 3)],
    [("ShearX", 0.2, 9), ("Rotate", 0.6, 8)],
    [("Color", 0.6, 1), ("Equalize", 1.0, 2)],
    [("Invert", 0.4, 9), ("Rotate", 0.6, 0)],
    [("Equalize", 1.0, 9), ("ShearY", 0.6, 3)],
    [("Color", 0.4, 7), ("Equalize", 0.6, 0)],
    [("Posterize", 0.4, 6), ("AutoContrast", 0.4, 7)],
    [("Solarize", 0.6, 8), ("Color", 0.6, 9)],
    [("Solarize", 0.2, 4), ("Rotate", 0.8, 9)],
    [("Rotate", 1.0, 7), ("TranslateY", 0.8, 9)],
    [("ShearX", 0.0, 0), ("Solarize", 0.8, 4)],
    [("ShearY", 0.8, 0), ("Color", 0.6, 4)],
    [("Color", 1.0, 0), ("Rotate", 0.6, 2)],
    [("Equalize", 0.8, 4), ("Equalize", 0.0, 8)],
    [("Equalize", 1.0, 4), ("AutoContrast", 0.6, 2)],
    [("ShearY", 0.4, 7), ("SolarizeAdd", 0.6, 7)],
    [("Posterize", 0.8, 2), ("Solarize", 0.6, 10)],
    [("Solarize", 0.6, 8), ("Equalize", 0.6, 1)],
    [("Color", 0.8, 6), ("Rotate", 0.4, 5)],
]


def distort_image_with_autoaugment(
    image: tf.Tensor,
    *,
    cutout_const: int = 100,
    translate_const: int = 250,
) -> tf.Tensor:
    """Apply one random AutoAugment-v0 sub-policy (the working version of the
    path the reference declared but never shipped)."""
    table = _op_table(cutout_const, translate_const)

    def apply_subpolicy(sub):
        def fn():
            im = image
            for name, prob, level in sub:
                should = tf.random.uniform([]) < prob
                im = tf.cond(
                    should,
                    (lambda im=im, name=name, level=level: table[name](im, float(level))),
                    (lambda im=im: im),
                )
            return im

        return fn

    idx = tf.random.uniform([], 0, len(_POLICY_V0), tf.int32)
    return tf.switch_case(idx, [apply_subpolicy(sub) for sub in _POLICY_V0])
