"""Input pipeline package.

Re-exports are fully lazy (PEP 562 via :mod:`sav_tpu._lazy`, like
:mod:`sav_tpu.obs` / :mod:`sav_tpu.train`): the pipeline's TF import and
the feeder/records jax imports load on first use, so jax-free consumers
— the elasticity supervisor's chaos harness recomputing
:func:`synth_batch` fingerprints in the *parent* process of an on-chip
job, laptop report tooling — can import :mod:`sav_tpu.data.synthetic`
(numpy-only) without dragging a backend in.
"""

from __future__ import annotations

from sav_tpu._lazy import install_lazy_exports

_EXPORTS = {
    "AugmentSpec": "sav_tpu.data.augment_spec",
    "parse_augment_spec": "sav_tpu.data.augment_spec",
    "DeviceFeeder": "sav_tpu.data.feeder",
    "PrefetchLoader": "sav_tpu.data.native_loader",
    "native_available": "sav_tpu.data.native_loader",
    "SavRecDataset": "sav_tpu.data.records",
    "write_savrec": "sav_tpu.data.records",
    "savrec_epoch_iterator": "sav_tpu.data.records",
    "host_shard_indices": "sav_tpu.data.records",
    "fake_data_iterator": "sav_tpu.data.synthetic",
    "synthetic_data_iterator": "sav_tpu.data.synthetic",
    "synth_batch": "sav_tpu.data.synthetic",
    "synth_resumable_iterator": "sav_tpu.data.synthetic",
    "load": "sav_tpu.data.pipeline",
    "Split": "sav_tpu.data.pipeline",
    "resumable_train_iterator": "sav_tpu.data.pipeline",
}

__all__ = list(_EXPORTS)

__getattr__, __dir__ = install_lazy_exports(
    globals(),
    _EXPORTS,
    {"augment_spec", "constants", "feeder", "native_loader", "pipeline",
     "records", "synthetic"},
)
