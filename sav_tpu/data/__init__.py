from sav_tpu.data.synthetic import fake_data_iterator, synthetic_data_iterator

__all__ = ["fake_data_iterator", "synthetic_data_iterator"]
