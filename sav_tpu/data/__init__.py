from sav_tpu.data.augment_spec import AugmentSpec, parse_augment_spec
from sav_tpu.data.feeder import DeviceFeeder
from sav_tpu.data.native_loader import (
    PrefetchLoader,
    native_available,
)
from sav_tpu.data.records import (
    SavRecDataset,
    host_shard_indices,
    savrec_epoch_iterator,
    write_savrec,
)
from sav_tpu.data.synthetic import fake_data_iterator, synthetic_data_iterator

__all__ = [
    "AugmentSpec",
    "parse_augment_spec",
    "DeviceFeeder",
    "PrefetchLoader",
    "native_available",
    "SavRecDataset",
    "write_savrec",
    "savrec_epoch_iterator",
    "host_shard_indices",
    "fake_data_iterator",
    "synthetic_data_iterator",
    "load",
    "Split",
    "resumable_train_iterator",
]


def __getattr__(name):
    # pipeline (and its TF import) loads lazily so fake/synthetic paths work
    # in TF-free contexts.
    if name in ("load", "Split", "resumable_train_iterator"):
        from sav_tpu.data import pipeline

        return getattr(pipeline, name)
    raise AttributeError(name)
