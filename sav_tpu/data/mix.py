"""Batch-level CutMix / MixUp (TF graph ops).

Capability parity with the reference's mix family
(/root/reference/input_pipeline.py:248-350): CutMix rectangles with
area-ratio labels, MixUp with per-example Beta-sampled ratios, and the
combined policy that applies MixUp to half the batch and CutMix to the
other half (reference ``my_mixup_cutmix``:328-350). Implementation differs
deliberately in one way: instead of consuming a 2× batch and mixing its
halves (``my_cutmix``:285-299), each example mixes with its ``roll``-by-1
partner — every sample stays in the batch, which keeps the effective batch
size / epoch accounting and is the timm-standard formulation. Ratios are
per-example exactly as the reference attaches them per-example
(:169-182), so the regularization statistics match.

Mixing operates on 0..255 float images *before* normalization (masking and
convex combinations commute with the per-channel affine normalize, so this
is numerically identical to the reference's normalize-then-mix order) —
which is what lets the ``augment_before_mix=False`` path re-augment the
mixed images as uint8 afterwards (input_pipeline.py:218-222).

Emits ``labels``, ``mix_labels`` and per-example ``ratio``; the trainer
mixes one-hot targets accordingly (/root/reference/train.py:84-87 behavior).
"""

from __future__ import annotations

from sav_tpu.data._tf import require_tf

tf = require_tf()


def _sample_beta(shape, alpha: float) -> tf.Tensor:
    """Beta(alpha, alpha) via two Gammas (TF has no direct Beta sampler)."""
    g1 = tf.random.gamma(shape, alpha)
    g2 = tf.random.gamma(shape, alpha)
    return g1 / (g1 + g2)


def mixup(batch: dict, alpha: float = 0.2) -> dict:
    """images ← r·x + (1-r)·roll(x); ratio r ~ Beta(alpha, alpha) per example
    (reference attaches ``mixup_ratio`` per example, input_pipeline.py:169-178)."""
    images = tf.cast(batch["images"], tf.float32)
    n = tf.shape(images)[0]
    ratio = _sample_beta([n], alpha)
    mixed = ratio[:, None, None, None] * images + (
        1.0 - ratio[:, None, None, None]
    ) * tf.roll(images, 1, axis=0)
    return dict(
        batch,
        images=mixed,
        mix_labels=tf.roll(batch["labels"], 1, axis=0),
        ratio=ratio,
    )


def _cutmix_mask(n, height, width):
    """Per-example binary keep-mask ``[n, h, w, 1]`` and kept-area ratio
    ``[n]``. Box area fraction ≈ (1 − λ) with λ ~ Beta(1, 1) = U(0, 1), the
    reference's ``cutmix_padding`` distribution (input_pipeline.py:248-282)."""
    lam = tf.random.uniform([n])
    cut = tf.sqrt(1.0 - lam)
    hf = tf.cast(height, tf.float32)
    wf = tf.cast(width, tf.float32)
    cut_h = tf.cast(cut * hf, tf.int32)
    cut_w = tf.cast(cut * wf, tf.int32)
    cy = tf.random.uniform([n], 0, height, tf.int32)
    cx = tf.random.uniform([n], 0, width, tf.int32)
    y0 = tf.clip_by_value(cy - cut_h // 2, 0, height)[:, None, None, None]
    y1 = tf.clip_by_value(cy + cut_h // 2, 0, height)[:, None, None, None]
    x0 = tf.clip_by_value(cx - cut_w // 2, 0, width)[:, None, None, None]
    x1 = tf.clip_by_value(cx + cut_w // 2, 0, width)[:, None, None, None]
    rows = tf.range(height)[None, :, None, None]
    cols = tf.range(width)[None, None, :, None]
    inside = (rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1)
    keep = 1.0 - tf.cast(inside, tf.float32)
    ratio = tf.reduce_mean(keep, axis=[1, 2, 3])
    return keep, ratio


def cutmix(batch: dict, alpha: float = 1.0) -> dict:
    """Paste a random box from the rolled partner; label ratio = kept area.

    Boxes and ratios are per-example (the reference computes one mask per
    example, input_pipeline.py:166-168). ``alpha`` is accepted for the
    augment-DSL surface but the box distribution is Beta(1,1) like the
    reference's ``cutmix_padding``.
    """
    del alpha  # reference uses Beta(1, 1) == uniform regardless
    images = tf.cast(batch["images"], tf.float32)
    shape = tf.shape(images)
    n, h, w = shape[0], shape[1], shape[2]
    keep, ratio = _cutmix_mask(n, h, w)
    mixed = keep * images + (1.0 - keep) * tf.roll(images, 1, axis=0)
    return dict(
        batch,
        images=mixed,
        mix_labels=tf.roll(batch["labels"], 1, axis=0),
        ratio=ratio,
    )


def mixup_and_cutmix(
    batch: dict, *, mixup_alpha: float = 0.2, cutmix_alpha: float = 1.0
) -> dict:
    """MixUp on the first half of the batch, CutMix on the second half —
    the reference's combined policy (``my_mixup_cutmix``,
    input_pipeline.py:328-350), with roll-partners inside each half so the
    batch size is preserved."""
    images = tf.cast(batch["images"], tf.float32)
    labels = batch["labels"]
    half = tf.shape(images)[0] // 2
    mu = mixup({"images": images[:half], "labels": labels[:half]}, mixup_alpha)
    cm = cutmix({"images": images[half:], "labels": labels[half:]}, cutmix_alpha)
    return dict(
        batch,
        images=tf.concat([mu["images"], cm["images"]], axis=0),
        mix_labels=tf.concat([mu["mix_labels"], cm["mix_labels"]], axis=0),
        ratio=tf.concat([mu["ratio"], cm["ratio"]], axis=0),
    )


def apply_mixes(batch: dict, spec) -> dict:
    """Apply the mix ops selected by an AugmentSpec."""
    if spec.cutmix and spec.mixup:
        return mixup_and_cutmix(
            batch, mixup_alpha=spec.mixup_alpha, cutmix_alpha=spec.cutmix_alpha
        )
    if spec.mixup:
        return mixup(batch, spec.mixup_alpha)
    if spec.cutmix:
        return cutmix(batch, spec.cutmix_alpha)
    return batch
