"""Batch-level CutMix / MixUp (TF graph ops).

Capability parity with the reference's mix family
(/root/reference/input_pipeline.py:248-350): CutMix rectangles with
area-ratio labels, MixUp with Beta-sampled ratios, and the combined
mixup-or-cutmix batch policy. Implementation differs deliberately: instead
of splitting the batch in halves (reference ``my_cutmix``:285-299), each
example mixes with its ``roll``-by-1 partner — every sample stays in the
batch, which keeps the effective batch size and is the timm-standard
formulation. Emits ``labels``, ``mix_labels`` and per-example ``ratio``;
the trainer mixes one-hot targets accordingly
(/root/reference/train.py:84-87 behavior).
"""

from __future__ import annotations

import tensorflow as tf


def _sample_beta(shape, alpha: float) -> tf.Tensor:
    """Beta(alpha, alpha) via two Gammas (TF has no direct Beta sampler)."""
    g1 = tf.random.gamma(shape, alpha)
    g2 = tf.random.gamma(shape, alpha)
    return g1 / (g1 + g2)


def mixup(batch: dict, alpha: float = 0.2) -> dict:
    """images ← r·x + (1-r)·roll(x); ratio r ~ Beta(alpha, alpha) per batch."""
    images = tf.cast(batch["images"], tf.float32)
    n = tf.shape(images)[0]
    ratio = _sample_beta([], alpha)
    mixed = ratio * images + (1.0 - ratio) * tf.roll(images, 1, axis=0)
    return dict(
        batch,
        images=mixed,
        mix_labels=tf.roll(batch["labels"], 1, axis=0),
        ratio=tf.fill([n], tf.cast(ratio, tf.float32)),
    )


def _cutmix_box(height: int, width: int, alpha: float):
    """Random box whose area fraction ≈ (1-λ), λ ~ Beta(alpha, alpha)."""
    lam = _sample_beta([], alpha)
    cut = tf.sqrt(1.0 - lam)
    cut_h = tf.cast(cut * tf.cast(height, tf.float32), tf.int32)
    cut_w = tf.cast(cut * tf.cast(width, tf.float32), tf.int32)
    cy = tf.random.uniform([], 0, height, tf.int32)
    cx = tf.random.uniform([], 0, width, tf.int32)
    y0 = tf.clip_by_value(cy - cut_h // 2, 0, height)
    y1 = tf.clip_by_value(cy + cut_h // 2, 0, height)
    x0 = tf.clip_by_value(cx - cut_w // 2, 0, width)
    x1 = tf.clip_by_value(cx + cut_w // 2, 0, width)
    return y0, y1, x0, x1


def cutmix(batch: dict, alpha: float = 1.0) -> dict:
    """Paste a random box from the rolled partner; label ratio = kept area."""
    images = tf.cast(batch["images"], tf.float32)
    shape = tf.shape(images)
    n, h, w = shape[0], shape[1], shape[2]
    y0, y1, x0, x1 = _cutmix_box(h, w, alpha)
    rows = tf.range(h)[None, :, None, None]
    cols = tf.range(w)[None, None, :, None]
    inside = (rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1)
    mixed = tf.where(inside, tf.roll(images, 1, axis=0), images)
    box_area = tf.cast((y1 - y0) * (x1 - x0), tf.float32)
    ratio = 1.0 - box_area / tf.cast(h * w, tf.float32)
    return dict(
        batch,
        images=mixed,
        mix_labels=tf.roll(batch["labels"], 1, axis=0),
        ratio=tf.fill([n], ratio),
    )


def mixup_or_cutmix(
    batch: dict, *, mixup_alpha: float = 0.2, cutmix_alpha: float = 1.0
) -> dict:
    """Randomly apply MixUp or CutMix to the batch (reference
    ``my_mixup_cutmix`` split the batch four ways; choosing per-batch keeps
    whole-batch vectorization — input_pipeline.py:320-350)."""
    return tf.cond(
        tf.random.uniform([]) < 0.5,
        lambda: mixup(batch, mixup_alpha),
        lambda: cutmix(batch, cutmix_alpha),
    )


def apply_mixes(batch: dict, spec) -> dict:
    """Apply the mix ops selected by an AugmentSpec."""
    if spec.cutmix and spec.mixup:
        return mixup_or_cutmix(
            batch, mixup_alpha=spec.mixup_alpha, cutmix_alpha=spec.cutmix_alpha
        )
    if spec.mixup:
        return mixup(batch, spec.mixup_alpha)
    if spec.cutmix:
        return cutmix(batch, spec.cutmix_alpha)
    return batch
