"""TF uint8 image augmentation ops.

Capability parity with the reference's 16-op zoo
(/root/reference/autoaugment.py:36-392) rebuilt on modern TF primitives:
geometric ops go through one affine helper on
``tf.raw_ops.ImageProjectiveTransformV3`` (native ``fill_value`` — no
wrap/unwrap alpha-channel trick needed), photometric ops are small uint8
kernels. All ops take/return ``[H, W, 3] uint8`` tensors.
"""

from __future__ import annotations

import math

from sav_tpu.data._tf import require_tf

tf = require_tf()

_GRAY = tf.constant([128] * 3, tf.float32)


def blend(image_a: tf.Tensor, image_b: tf.Tensor, factor) -> tf.Tensor:
    """``a + factor * (b - a)``, clipped to uint8 range. factor may exceed 1."""
    a = tf.cast(image_a, tf.float32)
    b = tf.cast(image_b, tf.float32)
    out = a + tf.cast(factor, tf.float32) * (b - a)
    return tf.cast(tf.clip_by_value(out, 0.0, 255.0), tf.uint8)


# ---------------------------------------------------------------- geometric


def _affine(image: tf.Tensor, transform, fill: int = 128) -> tf.Tensor:
    """Apply a single projective transform (8-vector) with constant fill."""
    out = tf.raw_ops.ImageProjectiveTransformV3(
        images=tf.cast(image, tf.float32)[None],
        transforms=tf.convert_to_tensor([transform], tf.float32),
        output_shape=tf.shape(image)[:2],
        fill_value=float(fill),
        fill_mode="CONSTANT",
        interpolation="NEAREST",
    )[0]
    return tf.cast(tf.clip_by_value(out, 0.0, 255.0), tf.uint8)


def rotate(image: tf.Tensor, degrees: float, fill: int = 128) -> tf.Tensor:
    radians = degrees * math.pi / 180.0
    c, s = tf.cos(radians), tf.sin(radians)
    h = tf.cast(tf.shape(image)[0], tf.float32)
    w = tf.cast(tf.shape(image)[1], tf.float32)
    cx, cy = (w - 1.0) / 2.0, (h - 1.0) / 2.0
    # Rotation about the image center (output→input mapping).
    tx = cx - c * cx + s * cy
    ty = cy - s * cx - c * cy
    return _affine(image, [c, -s, tx, s, c, ty, 0.0, 0.0], fill)


def shear_x(image: tf.Tensor, level: float, fill: int = 128) -> tf.Tensor:
    return _affine(image, [1.0, level, 0.0, 0.0, 1.0, 0.0, 0.0, 0.0], fill)


def shear_y(image: tf.Tensor, level: float, fill: int = 128) -> tf.Tensor:
    return _affine(image, [1.0, 0.0, 0.0, level, 1.0, 0.0, 0.0, 0.0], fill)


def translate_x(image: tf.Tensor, pixels: float, fill: int = 128) -> tf.Tensor:
    return _affine(image, [1.0, 0.0, -pixels, 0.0, 1.0, 0.0, 0.0, 0.0], fill)


def translate_y(image: tf.Tensor, pixels: float, fill: int = 128) -> tf.Tensor:
    return _affine(image, [1.0, 0.0, 0.0, 0.0, 1.0, -pixels, 0.0, 0.0], fill)


# -------------------------------------------------------------- photometric


def invert(image: tf.Tensor) -> tf.Tensor:
    return 255 - image


def posterize(image: tf.Tensor, bits: int) -> tf.Tensor:
    shift = tf.cast(8 - bits, image.dtype)
    return tf.bitwise.left_shift(tf.bitwise.right_shift(image, shift), shift)


def solarize(image: tf.Tensor, threshold: int = 128) -> tf.Tensor:
    return tf.where(image < tf.cast(threshold, image.dtype), image, 255 - image)


def solarize_add(image: tf.Tensor, addition: int, threshold: int = 128) -> tf.Tensor:
    added = tf.cast(
        tf.clip_by_value(tf.cast(image, tf.int32) + addition, 0, 255), image.dtype
    )
    return tf.where(image < tf.cast(threshold, image.dtype), added, image)


def color(image: tf.Tensor, factor: float) -> tf.Tensor:
    gray = tf.image.grayscale_to_rgb(tf.image.rgb_to_grayscale(image))
    return blend(gray, image, factor)


def contrast(image: tf.Tensor, factor: float) -> tf.Tensor:
    mean = tf.reduce_mean(tf.cast(tf.image.rgb_to_grayscale(image), tf.float32))
    flat = tf.cast(tf.fill(tf.shape(image), 0), tf.float32) + mean
    return blend(tf.cast(flat, tf.uint8), image, factor)


def brightness(image: tf.Tensor, factor: float) -> tf.Tensor:
    return blend(tf.zeros_like(image), image, factor)


def autocontrast(image: tf.Tensor) -> tf.Tensor:
    def per_channel(ch):
        ch_f = tf.cast(ch, tf.float32)
        lo = tf.reduce_min(ch_f)
        hi = tf.reduce_max(ch_f)

        def stretch():
            scale = 255.0 / (hi - lo)
            return tf.clip_by_value((ch_f - lo) * scale, 0.0, 255.0)

        return tf.cast(tf.cond(hi > lo, stretch, lambda: ch_f), tf.uint8)

    return tf.stack(
        [per_channel(image[..., c]) for c in range(3)], axis=-1
    )


def equalize(image: tf.Tensor) -> tf.Tensor:
    def per_channel(ch):
        hist = tf.histogram_fixed_width(tf.cast(ch, tf.int32), [0, 255], nbins=256)
        nonzero = tf.boolean_mask(hist, hist != 0)
        step = (tf.reduce_sum(nonzero) - nonzero[-1]) // 255

        def eq():
            lut = (tf.cumsum(hist) + (step // 2)) // step
            lut = tf.concat([[step // 2 // step], lut[:-1]], 0)
            lut = tf.clip_by_value(lut, 0, 255)
            return tf.gather(lut, tf.cast(ch, tf.int32))

        return tf.cast(
            tf.cond(step == 0, lambda: tf.cast(ch, tf.int32), eq), tf.uint8
        )

    return tf.stack([per_channel(image[..., c]) for c in range(3)], axis=-1)


def sharpness(image: tf.Tensor, factor: float) -> tf.Tensor:
    img = tf.cast(image, tf.float32)[None]
    kernel = (
        tf.constant([[1, 1, 1], [1, 5, 1], [1, 1, 1]], tf.float32, shape=[3, 3, 1, 1])
        / 13.0
    )
    kernel = tf.tile(kernel, [1, 1, 3, 1])
    smoothed = tf.nn.depthwise_conv2d(
        img, kernel, strides=[1, 1, 1, 1], padding="VALID"
    )
    smoothed = tf.clip_by_value(smoothed, 0.0, 255.0)
    # Keep original border (conv is VALID), smooth interior only.
    smoothed = tf.pad(smoothed, [[0, 0], [1, 1], [1, 1], [0, 0]])
    mask = tf.pad(
        tf.ones_like(smoothed[:, 1:-1, 1:-1, :]), [[0, 0], [1, 1], [1, 1], [0, 0]]
    )
    smoothed = tf.where(mask == 1.0, smoothed, img)
    return blend(tf.cast(smoothed[0], tf.uint8), image, factor)


def cutout(image: tf.Tensor, pad_size: int, fill: int = 128) -> tf.Tensor:
    """Zero out (to ``fill``) a random ``2*pad_size`` square."""
    h = tf.shape(image)[0]
    w = tf.shape(image)[1]
    cy = tf.random.uniform([], 0, h, tf.int32)
    cx = tf.random.uniform([], 0, w, tf.int32)
    y0 = tf.maximum(cy - pad_size, 0)
    y1 = tf.minimum(cy + pad_size, h)
    x0 = tf.maximum(cx - pad_size, 0)
    x1 = tf.minimum(cx + pad_size, w)
    rows = tf.range(h)[:, None, None]
    cols = tf.range(w)[None, :, None]
    inside = (rows >= y0) & (rows < y1) & (cols >= x0) & (cols < x1)
    return tf.where(inside, tf.cast(fill, image.dtype), image)
