"""Async double-buffered device feeder.

The trainer's fit() loop was structurally serial: every step blocked on
``next(data_iter)`` and then on ``shard_batch`` (a synchronous
``device_put``) before the device step could even dispatch, so host fetch
and host→device transfer were pure addends on top of the ~100 ms device
step (PERF.md §7 — 2,388 img/s device-resident vs 93–169 img/s fed).
:class:`DeviceFeeder` pipelines the three stages instead:

    host fetch (batch N+2)  ──┐  background thread
    device_put (batch N+1)  ──┤  (bounded queue, depth knob)
    device step (batch N)   ──┘  training thread

A single background thread pulls host batches, immediately places them on
the mesh via the caller's ``place_fn`` (typically ``Trainer.shard_batch``
— per-leaf NamedShardings, multi-process assembly included), and pushes
the *placed* batches into a bounded queue. ``depth=2`` is classic double
buffering: at most ``depth`` placed batches wait on device beyond the one
in flight, so HBM exposure is bounded while transfer of batch N+1 hides
behind compute of step N. The queue's ``maxsize`` is the backpressure —
a slow consumer stalls the worker, never the other way around.

Semantics the trainer relies on (unit-tested in tests/test_feeder.py):

- **Drain**: the source iterator's ``StopIteration`` is delivered to the
  consumer exactly once, after every already-placed batch has been
  consumed; subsequent ``next()`` calls keep raising ``StopIteration``.
- **Exception propagation**: an exception in the source iterator or in
  ``place_fn`` is re-raised in the consumer thread (after the batches
  placed before it), not swallowed on the worker.
- **Shutdown**: ``close()`` (also via context manager) stops the worker
  promptly even when it is blocked on a full queue; it never joins a
  thread that is blocked inside the source iterator forever (the worker
  is a daemon and checks the stop flag between stages).

Telemetry: the feeder keeps worker-side counters (host fetch seconds,
device_put seconds, queue-depth high-water/occupancy) exposed by
:meth:`stats`; the trainer publishes them as ``feeder/*`` gauges on the
goodput ledger so a run's report shows the overlap working — in feeder
mode the ledger's ``input_wait`` is the consumer's residual queue wait
and ``h2d`` on the training thread is ~0, while ``feeder/h2d_s`` shows
where the placement time actually went (overlapped).

Stdlib + the injected ``place_fn`` only — no jax import at module level,
so the data layer stays importable in TF-free/device-free contexts.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterator, Optional


class DeviceFeeder:
    """Bounded async pipeline: host iterator → place_fn → placed-batch queue.

    Args:
      iterator: host batch source (dicts of numpy arrays, typically).
      place_fn: called on the worker thread with each host batch; returns
        the placed (device) batch the consumer receives. Pass
        ``Trainer.shard_batch`` for SPMD-correct per-leaf placement.
      depth: max placed batches queued beyond the one the consumer holds
        (2 = double buffering). Also the backpressure bound.
      name: thread-name suffix for stack dumps (the obs watchdog prints
        every thread; a recognizable name keeps its reports readable).
    """

    _POLL_S = 0.1  # stop-flag responsiveness for blocking queue ops

    def __init__(
        self,
        iterator: Iterator[dict],
        place_fn: Callable[[dict], Any],
        *,
        depth: int = 2,
        name: str = "device-feeder",
    ):
        if depth < 1:
            raise ValueError(f"feeder depth must be >= 1, got {depth}")
        self.depth = depth
        self._iterator = iterator
        self._place_fn = place_fn
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._done = object()
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._finished = False
        # Worker-side counters. Python attribute writes are atomic under
        # the GIL; the consumer only ever reads them for telemetry.
        self._fetch_s = 0.0
        self._put_s = 0.0
        self._batches = 0
        self._depth_max = 0
        self._depth_sum = 0
        self._wait_s = 0.0  # consumer-side blocked time
        self._thread = threading.Thread(
            target=self._worker, name=name, daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- worker

    def _enqueue(self, item) -> bool:
        """Bounded put that stays responsive to close(); True if queued."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=self._POLL_S)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    batch = next(self._iterator)
                except StopIteration:
                    break
                self._fetch_s += time.perf_counter() - t0
                t0 = time.perf_counter()
                placed = self._place_fn(batch)
                self._put_s += time.perf_counter() - t0
                self._batches += 1
                if not self._enqueue(placed):
                    return  # closed while blocked on a full queue
                d = self._queue.qsize()
                self._depth_sum += d
                self._depth_max = max(self._depth_max, d)
        except BaseException as e:  # re-raised on the consumer thread
            self._err = e
        finally:
            self._enqueue(self._done)

    # ----------------------------------------------------------- consumer

    def __iter__(self):
        return self

    def __next__(self):
        # Terminal states persist: the sentinel is consumed exactly once,
        # so later next() calls must not block on an empty queue.
        if self._finished:
            if self._err is not None:
                raise self._err
            raise StopIteration
        # Timed get re-checking the stop flag (mirror of _enqueue): after
        # close() the worker drops everything including the sentinel, so
        # an untimed get from a consumer on another thread would block
        # forever instead of seeing the closed state.
        t0 = time.perf_counter()
        while True:
            if self._stop.is_set():
                self._wait_s += time.perf_counter() - t0
                raise RuntimeError("DeviceFeeder is closed")
            try:
                item = self._queue.get(timeout=self._POLL_S)
                break
            except queue.Empty:
                continue
        self._wait_s += time.perf_counter() - t0
        if item is self._done:
            self._finished = True
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop the worker and release the queue. Idempotent.

        Safe to call with the worker in any state (blocked on a full
        queue, mid-place, already drained). Does not wait on the source
        iterator: a worker blocked inside ``next(iterator)`` is a daemon
        thread and dies with the process; everything it might still
        enqueue after close() is dropped by the poisoned stop flag.
        """
        self._stop.set()
        # Unblock a worker stuck in queue.put by draining; bounded loop —
        # the worker checks the stop flag at least every _POLL_S.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5 * self._POLL_S)
        # The drain races the worker's in-flight put: the slot it freed can
        # be re-filled just after get_nowait saw Empty. The worker never
        # *starts* a put once the flag is set, so after the join one more
        # drain releases anything that slipped in — without it a placed
        # device batch could stay referenced by the dead queue.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---------------------------------------------------------- telemetry

    def stats(self) -> dict:
        """Worker/consumer counters for the goodput ledger's gauges.

        ``h2d_s``/``fetch_s`` are background-thread seconds (overlapped
        with device compute, NOT training-thread wall time); ``wait_s``
        is the consumer's blocked time (what the trainer also books as
        ``input_wait``); ``depth_avg``/``depth_max`` show whether the
        buffer actually stayed full (a starved feeder sits at 0).
        """
        batches = self._batches
        return {
            "batches": float(batches),
            "fetch_s": round(self._fetch_s, 6),
            "h2d_s": round(self._put_s, 6),
            "wait_s": round(self._wait_s, 6),
            "depth": float(self.depth),
            "depth_max": float(self._depth_max),
            "depth_avg": round(self._depth_sum / batches, 4) if batches else 0.0,
        }
