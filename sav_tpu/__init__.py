"""sav_tpu — a TPU-native vision self-attention framework.

A ground-up JAX / XLA / pjit / Pallas re-design with the capabilities of
``cfoster0/self-attention-experiments-vision`` (see SURVEY.md): a vision
attention layer zoo, a model zoo (ViT, CaiT, CvT, CeiT, TNT, BoTNet,
MLP-Mixer), a sharded ImageNet input pipeline, and an SPMD training stack
over a ``jax.sharding.Mesh`` with fused Pallas TPU flash-attention kernels
behind a ``backend='pallas'`` seam.

Subpackages
-----------
- ``sav_tpu.ops``      — functional compute ops (attention cores, Pallas kernels)
- ``sav_tpu.models``   — layer zoo + model zoo + registry
- ``sav_tpu.utils``    — metrics, logging
- ``sav_tpu.parallel`` — mesh, sharding rules, ring attention (sequence parallel)
- ``sav_tpu.data``     — input pipeline (fake data, tf.data, augmentations)
- ``sav_tpu.train``    — pjit trainer, schedules, checkpointing
"""

__version__ = "0.1.0"
