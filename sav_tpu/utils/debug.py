"""Numerics debugging — the framework's 'sanitizer' layer.

Functional JAX makes in-model data races a non-issue (SURVEY.md §5 'Race
detection'), so the debugging surface that matters on TPU is *numerics*:
NaN/Inf escapes in bf16 training. Two mechanisms:

- :func:`find_nonfinite` / :func:`assert_all_finite` — host-side tree
  checks that name the offending leaves, used by the trainer's
  ``debug_nans`` mode on logged metrics/gradients (zero cost when off).
- :func:`checkify_step` — wraps a jitted step with ``jax.experimental
  .checkify`` NaN checks for in-graph detection when hunting an
  intermittent blow-up.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np


from sav_tpu.utils.param_overview import _path_str


def find_nonfinite(tree: Any) -> list[str]:
    """Paths of leaves containing NaN/Inf (host-side; device_gets the tree)."""
    host = jax.device_get(tree)
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(host)[0]:
        # jnp.issubdtype, not numpy dtype.kind: bfloat16 (ml_dtypes) has
        # kind 'V' and would silently pass a kind=='f' check.
        arr = np.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if arr.dtype.kind != "f":  # ml_dtypes (bfloat16, float8_*) → upcast
            arr = arr.astype(np.float32)
        if not np.isfinite(arr).all():
            bad.append(_path_str(path))
    return bad


def assert_all_finite(tree: Any, name: str = "tree") -> None:
    """Raise ``FloatingPointError`` naming non-finite leaves."""
    bad = find_nonfinite(tree)
    if bad:
        raise FloatingPointError(f"non-finite values in {name}: {bad}")


def global_norm_nonfinite(tree: Any) -> jax.Array:
    """In-graph scalar: 1.0 if any float leaf contains NaN/Inf, else 0.0.

    Cheap enough to compute every step (one reduction per leaf, fused by
    XLA); log it and alert host-side instead of device_getting full trees.
    """
    flags = [
        jnp.any(~jnp.isfinite(x))
        for x in jax.tree.leaves(tree)
        if jnp.issubdtype(x.dtype, jnp.floating)
    ]
    if not flags:
        return jnp.zeros((), jnp.float32)
    return jnp.max(jnp.stack([f.astype(jnp.float32) for f in flags]))


def checkify_step(step_fn: Callable) -> Callable:
    """Wrap a step function with in-graph NaN/div checks.

    Returns a function with the same signature whose errors are raised
    host-side after the step (``err.throw()``).
    """
    from jax.experimental import checkify

    checked = checkify.checkify(step_fn, errors=checkify.nan_checks)

    def wrapper(*args, **kwargs):
        err, out = checked(*args, **kwargs)
        err.throw()
        return out

    return wrapper
