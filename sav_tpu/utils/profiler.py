"""Profiling & tracing.

The reference had no profiling at all (SURVEY.md §5 'Tracing / profiling').
This module provides the TPU-native equivalents:

- :func:`trace` — context manager around ``jax.profiler`` that writes an
  XPlane trace viewable in TensorBoard/Perfetto; the standard tool for
  finding input-bound vs compute-bound steps on TPU.
- :class:`StepTimer` — host-side throughput/latency tracker with jitter
  percentiles, for the images/sec counters the training loop logs.
- :func:`benchmark_fn` — microbenchmark harness for jitted functions and
  Pallas kernels (compile excluded, device-synced timing), used by the
  kernel cross-check/benchmark tests and ``bench.py``-style tooling.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Optional

import jax
import numpy as np


def start_trace(log_dir: str, *, host_tracer_level: int = 2) -> None:
    """``jax.profiler.start_trace`` with host-tracer options when the
    running jax supports them (single implementation for the context
    manager and the trainer's step-window profiling)."""
    options = None
    try:  # ProfileOptions is a recent jax addition; fall back silently.
        options = jax.profiler.ProfileOptions()
        options.host_tracer_level = host_tracer_level
    except Exception:
        options = None
    kwargs = {"profiler_options": options} if options is not None else {}
    try:
        jax.profiler.start_trace(log_dir, **kwargs)
    except TypeError:  # older signature without profiler_options
        jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: Optional[str], *, host_tracer_level: int = 2):
    """Capture a ``jax.profiler`` trace into ``log_dir``.

    No-op when ``log_dir`` is None so call sites can leave the hook wired
    unconditionally (``with trace(cfg.profile_dir): step()``).
    """
    if log_dir is None:
        yield
        return
    start_trace(log_dir, host_tracer_level=host_tracer_level)
    try:
        yield
    finally:
        stop_trace()


def annotate(name: str):
    """Named trace span (shows up in the profiler timeline)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Rolling step-latency / throughput tracker.

    Host-side: call :meth:`tick` once per (logical) step after the device
    work for that step has been dispatched. Throughput uses wall time
    between ticks, which on a steady pipeline equals device step time.
    """

    def __init__(self, items_per_step: int = 0, window: int = 100):
        self.items_per_step = items_per_step
        self.window = window
        self._durations: list[float] = []
        self._last: Optional[float] = None

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._durations.append(now - self._last)
            if len(self._durations) > self.window:
                self._durations.pop(0)
        self._last = now

    def reset(self) -> None:
        """Forget the last tick (call after eval/checkpoint pauses so the
        gap doesn't pollute the next interval)."""
        self._last = None

    @property
    def num_ticks(self) -> int:
        return len(self._durations)

    def summary(self) -> dict[str, float]:
        if not self._durations:
            return {}
        d = np.asarray(self._durations)
        out = {
            "step_time_mean_s": float(d.mean()),
            "step_time_p50_s": float(np.percentile(d, 50)),
            "step_time_p95_s": float(np.percentile(d, 95)),
        }
        if self.items_per_step:
            out["items_per_sec"] = self.items_per_step / float(d.mean())
        return out


def benchmark_fn(
    fn: Callable[..., Any],
    *args: Any,
    iters: int = 10,
    warmup: int = 2,
    **kwargs: Any,
) -> dict[str, float]:
    """Time a device computation: compile/warmup excluded, output-synced.

    Returns mean/min seconds per call. ``fn`` should return a jax array or
    pytree of arrays; synchronization is via ``block_until_ready`` on every
    leaf plus a final ``device_get`` (some relayed platforms complete
    ``block_until_ready`` before execution finishes).
    """

    def sync(out):
        for leaf in jax.tree.leaves(out):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
        leaves = jax.tree.leaves(out)
        if leaves and hasattr(leaves[0], "addressable_shards"):
            jax.device_get(jax.tree.map(lambda x: x.ravel()[0], leaves[0]))
        return out

    for _ in range(max(warmup, 1)):
        sync(fn(*args, **kwargs))

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    t = np.asarray(times)
    return {
        "mean_s": float(t.mean()),
        "min_s": float(t.min()),
        "p50_s": float(np.percentile(t, 50)),
        "iters": float(iters),
    }
