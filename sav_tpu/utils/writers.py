"""Metric writers — host-side observability sinks.

The reference logged wandb scalars from *inside* the pmapped train step (a
tracer leak, /root/reference/train.py:102-107; SURVEY.md §2.9 #11). Here
metric emission is strictly host-side: the trainer hands a plain
``dict[str, float]`` to a writer after ``device_get``. Writers compose via
:class:`MultiWriter`; wandb and TensorBoard sinks import lazily and degrade
to no-ops when the library isn't installed (neither is a framework
dependency).
"""

from __future__ import annotations

import json
import os
from typing import Mapping, Optional, Protocol, Sequence


class MetricWriter(Protocol):
    def write(self, step: int, metrics: Mapping[str, float]) -> None: ...
    def close(self) -> None: ...


class JsonlWriter:
    """One JSON object per write, appended to ``<dir>/metrics.jsonl``."""

    def __init__(self, log_dir: str, filename: str = "metrics.jsonl"):
        os.makedirs(log_dir, exist_ok=True)
        self._path = os.path.join(log_dir, filename)
        self._f = open(self._path, "a", buffering=1)

    @property
    def path(self) -> str:
        return self._path

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        rec = {"step": int(step)}
        for k, v in metrics.items():
            if k == "step":
                continue  # the positional step wins; don't float-cast it
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                # Non-scalar payloads (e.g. a goodput summary dict) pass
                # through as-is — jsonl is the one sink that can hold them.
                rec[k] = v
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


class LoggingWriter:
    """Writes through a callable (default ``print``) — the CLI sink."""

    def __init__(self, log_fn=print):
        self._log_fn = log_fn

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        parts = ", ".join(
            f"{k}={v:.6g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in metrics.items()
        )
        self._log_fn(f"step {step}: {parts}")

    def close(self) -> None:
        pass


class WandbWriter:
    """Weights & Biases sink (the reference's logger, train.py:195-201).

    Lazily imports ``wandb``; becomes a no-op if unavailable.
    """

    def __init__(self, project: str, *, config: Optional[dict] = None, **kwargs):
        self._run = None
        self._wandb = None
        try:
            import wandb  # type: ignore
        except ImportError:
            return  # library absent → silent no-op (documented behavior)
        try:
            self._run = wandb.init(project=project, config=config, **kwargs)
            self._wandb = wandb
        except Exception as e:  # installed but init failed (auth, network…)
            import warnings

            warnings.warn(f"wandb.init failed, metrics will not be logged: {e}")

    @property
    def active(self) -> bool:
        return self._run is not None

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        if self._run is not None:
            self._wandb.log(dict(metrics), step=int(step))

    def close(self) -> None:
        if self._run is not None:
            self._run.finish()


class TensorBoardWriter:
    """TensorBoard scalar sink via ``tf.summary`` (TF ships with the data
    pipeline); no-op when TF is unavailable."""

    def __init__(self, log_dir: str):
        self._tf = None
        self._writer = None
        try:
            from sav_tpu.data._tf import tf  # type: ignore
        except ImportError:
            return  # library absent → silent no-op (documented behavior)
        if tf is None:  # guarded import exports None instead of raising
            return
        try:
            self._writer = tf.summary.create_file_writer(log_dir)
            self._tf = tf
        except Exception as e:
            import warnings

            warnings.warn(f"TensorBoard writer init failed: {e}")

    @property
    def active(self) -> bool:
        return self._writer is not None

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        if self._writer is None:
            return
        with self._writer.as_default():
            for k, v in metrics.items():
                self._tf.summary.scalar(k, float(v), step=int(step))
            self._writer.flush()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()


class MultiWriter:
    """Fan-out to several writers."""

    def __init__(self, writers: Sequence[MetricWriter]):
        self._writers = list(writers)

    def write(self, step: int, metrics: Mapping[str, float]) -> None:
        for w in self._writers:
            w.write(step, metrics)

    def close(self) -> None:
        # Close every sink even if one raises (a wandb network error must
        # not leave the jsonl file unflushed); re-raise the first failure.
        errors: list[Exception] = []
        for w in self._writers:
            try:
                w.close()
            except Exception as e:
                errors.append(e)
        if errors:
            raise errors[0]
