"""Metrics.

Capability parity with the reference's ``utils.py`` (``topk_correct``,
/root/reference/utils.py:20-37), rebuilt without the vmapped ``any_in``
gather — a single ``top_k``/comparison pattern XLA fuses cleanly on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def topk_correct(logits: jax.Array, labels: jax.Array, topk: tuple[int, ...] = (1, 5)):
    """Per-example top-k correctness masks.

    Args:
      logits: ``[batch, num_classes]`` float array.
      labels: ``[batch]`` int class ids.
      topk: tuple of k values.

    Returns:
      dict ``{f'top_{k}_acc': [batch] float mask}`` — 1.0 where the true label
      is within the top-k predictions.
    """
    max_k = max(topk)
    _, top_ids = jax.lax.top_k(logits, max_k)  # [batch, max_k]
    hit = top_ids == labels[:, None]  # [batch, max_k]
    out = {}
    for k in topk:
        out[f"top_{k}_acc"] = jnp.any(hit[:, :k], axis=-1).astype(jnp.float32)
    return out


def accuracy_topk(logits: jax.Array, labels: jax.Array, topk: tuple[int, ...] = (1, 5)):
    """Mean top-k accuracies over the batch."""
    masks = topk_correct(logits, labels, topk)
    return {k: jnp.mean(v) for k, v in masks.items()}


def cross_entropy(logits: jax.Array, label_probs: jax.Array) -> jax.Array:
    """Mean softmax cross entropy against (possibly soft/mixed) label distributions.

    Loss math runs in float32 regardless of logits dtype (the reference casts
    logits to fp32 before the loss, train.py:89-90).
    """
    logits = logits.astype(jnp.float32)
    label_probs = label_probs.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.sum(label_probs * logp, axis=-1))
