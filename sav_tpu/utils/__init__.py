"""Utility surface: metrics, parameter overviews, profiling, debug, writers.

Re-exports are lazy (PEP 562): importing a stdlib-only submodule such as
``sav_tpu.utils.backend_probe`` must not drag ``jax`` into the process —
the probe runs on the exact path (down/wedged relay) where every heavy
import delays the abort decision.
"""

from __future__ import annotations

from sav_tpu._lazy import install_lazy_exports

_EXPORTS = {
    "topk_correct": "sav_tpu.utils.metrics",
    "accuracy_topk": "sav_tpu.utils.metrics",
    "cross_entropy": "sav_tpu.utils.metrics",
    "count_parameters": "sav_tpu.utils.param_overview",
    "parameter_overview": "sav_tpu.utils.param_overview",
    "log_parameter_overview": "sav_tpu.utils.param_overview",
    "StepTimer": "sav_tpu.utils.profiler",
    "annotate": "sav_tpu.utils.profiler",
    "benchmark_fn": "sav_tpu.utils.profiler",
    "trace": "sav_tpu.utils.profiler",
    "assert_all_finite": "sav_tpu.utils.debug",
    "checkify_step": "sav_tpu.utils.debug",
    "find_nonfinite": "sav_tpu.utils.debug",
    "global_norm_nonfinite": "sav_tpu.utils.debug",
    "JsonlWriter": "sav_tpu.utils.writers",
    "LoggingWriter": "sav_tpu.utils.writers",
    "MetricWriter": "sav_tpu.utils.writers",
    "MultiWriter": "sav_tpu.utils.writers",
    "TensorBoardWriter": "sav_tpu.utils.writers",
    "WandbWriter": "sav_tpu.utils.writers",
}

__all__ = list(_EXPORTS)


__getattr__, __dir__ = install_lazy_exports(
    globals(),
    _EXPORTS,
    {"backend_probe", "debug", "metrics", "param_overview", "profiler",
     "writers"},
)
