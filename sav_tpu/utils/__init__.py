from sav_tpu.utils.metrics import topk_correct, accuracy_topk, cross_entropy
from sav_tpu.utils.param_overview import (
    count_parameters,
    log_parameter_overview,
    parameter_overview,
)
from sav_tpu.utils.profiler import StepTimer, annotate, benchmark_fn, trace
from sav_tpu.utils.debug import (
    assert_all_finite,
    checkify_step,
    find_nonfinite,
    global_norm_nonfinite,
)
from sav_tpu.utils.writers import (
    JsonlWriter,
    LoggingWriter,
    MetricWriter,
    MultiWriter,
    TensorBoardWriter,
    WandbWriter,
)

__all__ = [
    "topk_correct",
    "accuracy_topk",
    "cross_entropy",
    "count_parameters",
    "parameter_overview",
    "log_parameter_overview",
    "StepTimer",
    "annotate",
    "benchmark_fn",
    "trace",
    "assert_all_finite",
    "checkify_step",
    "find_nonfinite",
    "global_norm_nonfinite",
    "JsonlWriter",
    "LoggingWriter",
    "MetricWriter",
    "MultiWriter",
    "TensorBoardWriter",
    "WandbWriter",
]
