from sav_tpu.utils.metrics import topk_correct, accuracy_topk, cross_entropy

__all__ = ["topk_correct", "accuracy_topk", "cross_entropy"]
