"""FLOPs / MFU helpers shared by the trainer and bench.py.

XLA's ``compiled.cost_analysis()`` reports the **per-device** FLOPs of the
SPMD-partitioned executable (verified on an 8-way sharded program: exactly
1/8 of the single-device count). MFU is therefore computed per chip:

    mfu = per_device_flops / step_time / per_chip_peak

which is correct for any mesh size without knowing the global batch.
"""

from __future__ import annotations

from typing import Optional

import jax

# Peak dense bf16 FLOP/s per chip, matched on substrings of
# ``jax.Device.device_kind``.
PEAK_FLOPS_PER_CHIP = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6": 918e12,
}


def per_chip_peak_flops(devices=None) -> Optional[float]:
    """Peak bf16 FLOP/s of one chip (None if the device kind is unknown)."""
    devices = jax.devices() if devices is None else devices
    kind = getattr(devices[0], "device_kind", "").lower()
    for key, peak in PEAK_FLOPS_PER_CHIP.items():
        if key in kind:
            return peak
    return None


def xla_cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one dict ({} if absent).

    Backends disagree on shape: TPU returns a dict, CPU a one-element
    list of dicts — normalize so callers (``compiled_flops``,
    ``obs/costs.py``) read ``'flops'``/``'bytes accessed'`` uniformly.
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend-dependent
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


def compiled_flops(compiled) -> float:
    """Per-device FLOPs from a compiled executable (0.0 if unavailable)."""
    try:
        return float(xla_cost_analysis(compiled).get("flops", 0.0) or 0.0)
    except Exception:  # pragma: no cover - backend-dependent
        return 0.0
