"""Parameter overview — counts and a human-readable table.

TPU-native stand-in for the reference's use of
``clu.parameter_overview.count_parameters`` (the only observability it had,
/root/reference/experiments/base.py:79-80): module-path param counts,
shapes, dtypes, and sharding info for mesh-sharded trees.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def _path_str(path) -> str:
    return "/".join(
        k.key if hasattr(k, "key") else str(getattr(k, "idx", k)) for k in path
    )


def count_parameters(params: Any) -> int:
    """Total number of scalar parameters in the tree."""
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def parameter_overview(params: Any, *, include_stats: bool = False) -> str:
    """Formatted table: path, shape, dtype, #params (and sharding if any)."""
    rows = []
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in flat:
        n = int(np.prod(leaf.shape))
        sharding = ""
        spec = getattr(getattr(leaf, "sharding", None), "spec", None)
        if spec is not None and any(s is not None for s in spec):
            sharding = str(spec)
        rows.append((_path_str(path), str(leaf.shape), str(leaf.dtype), n, sharding))
    total = sum(r[3] for r in rows)
    width = max([len(r[0]) for r in rows] + [10])
    lines = [f"{'Name':<{width}}  {'Shape':<18} {'Dtype':<9} {'Count':>12}  Sharding"]
    lines += [
        f"{name:<{width}}  {shape:<18} {dtype:<9} {n:>12,}  {sh}"
        for name, shape, dtype, n, sh in rows
    ]
    lines.append(f"{'Total':<{width}}  {'':<18} {'':<9} {total:>12,}")
    return "\n".join(lines)


def log_parameter_overview(params: Any, *, log_fn=print) -> int:
    """Print/log the overview; returns the total count."""
    log_fn(parameter_overview(params))
    return count_parameters(params)
