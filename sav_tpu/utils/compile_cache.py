"""Persistent XLA compilation cache.

The TNT two-stream graph takes 493 s to XLA-compile on the relayed chip
(PERF.md §12), and every relay reconnection — plus every bench/train
process restart — pays the full recompile again. JAX's persistent
compilation cache keyed on (HLO, compile options, backend version) turns
those repeats into a disk read. This module is the single switch point:
``train.py --compilation-cache-dir`` / ``bench.py --compilation-cache-dir``
/ ``TrainConfig.compilation_cache_dir`` all land here.

Must be enabled *before* the first compilation of the program to cover it
(Trainer applies it in ``__init__``, before any jit dispatch). Config
names are probed defensively so older/newer jax versions degrade to a
no-op warning instead of crashing the run.
"""

from __future__ import annotations

import logging
import os
from typing import Optional


def enable_persistent_cache(
    cache_dir: str,
    *,
    min_compile_time_secs: Optional[float] = None,
) -> bool:
    """Point XLA's persistent compilation cache at ``cache_dir``.

    Args:
      cache_dir: directory for cache entries (created if missing). Shared
        safely between concurrent processes — entries are content-keyed
        and written atomically by jax.
      min_compile_time_secs: only persist compilations slower than this
        (None keeps jax's default, ~1 s — tests pass 0.0 so tiny programs
        produce entries).

    Returns True if the cache was enabled, False if this jax build does
    not expose the config (logged, never raised — a missing cache is a
    slower run, not a broken one).
    """
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (AttributeError, ValueError) as e:  # pragma: no cover - old jax
        logging.warning("persistent compilation cache unavailable: %s", e)
        return False
    if min_compile_time_secs is not None:
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(min_compile_time_secs),
            )
        except (AttributeError, ValueError):  # pragma: no cover - old jax
            pass
    try:
        # Entry-size floor off: a cached 50 ms CPU step is still a win in
        # tests, and real TPU programs dwarf any floor anyway.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except (AttributeError, ValueError):  # pragma: no cover - old jax
        pass
    try:
        # jax freezes the enable/disable decision at the process's FIRST
        # compilation (compilation_cache._cache_initialized): a trainer
        # built after any prior jit dispatch — a warmup, another trainer,
        # an earlier test — would silently get no cache. Reset the
        # singleton so the new directory takes effect from here on.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # pragma: no cover - private API moved
        logging.warning(
            "could not reset jax's compilation-cache singleton; the "
            "persistent cache only applies if nothing compiled yet"
        )
    return True


def disable_persistent_cache() -> None:
    """Turn the persistent cache fully off — the symmetric inverse of
    :func:`enable_persistent_cache`.

    Clearing ``jax_compilation_cache_dir`` alone is NOT enough: jax's
    cache singleton froze its enable decision at the first compilation
    after :func:`enable_persistent_cache`'s reset, so the live cache
    object keeps serving the old directory — later identical programs
    come back as *deserialized* executables from a path the caller
    believes is disabled (and on the CPU backend that deserialized-hit
    path has crashed outright: the flight-recorder replay of a
    just-recorded step is exactly a same-process identical recompile).
    Callers that enable the cache temporarily (tests, notebooks) must
    tear down through here.
    """
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", None)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except (AttributeError, ValueError):  # pragma: no cover - old jax
        pass
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()  # drop the frozen, still-live cache object
    except Exception:  # pragma: no cover - private API moved
        logging.warning(
            "could not reset jax's compilation-cache singleton; the old "
            "cache directory may keep serving this process's compiles"
        )
