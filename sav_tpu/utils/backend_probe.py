"""Bounded accelerator-backend probing (failure detection for on-chip runs).

On this stack the failure mode of a down or wedged TPU relay is a *hang*
inside PJRT plugin init — not an error (observed rounds 3-5: a dial-retry
sleep loop inside the plugin, and a wedged chip grant after a client died
holding it). Any process that initializes the backend in-process therefore
hangs uninterruptibly. These helpers probe from a **subprocess** with a
timeout, so callers can degrade a transient outage into a late start or a
prompt, clearly-labeled abort instead of a silently hung job.

This is new behavior, not reference parity: the reference assumes a
healthy single-host device and has no startup failure-detection at all —
if backend init hung it would simply hang. The tunneled-relay failure
mode observed here (ADVICE.md r5) forces the guard, and it has to be an
external subprocess probe because the in-process path cannot time out.

Used by ``bench.py --backend-wait`` and ``train.py --backend-wait``; the
steady-state counterpart (a run that hangs *after* starting) is
``sav_tpu.obs.watchdog``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

# device_get of a computed value, not block_until_ready — the relay can ack
# transfers early (see docs/benchmarking.md). The platform is printed
# behind a sentinel prefix so banners/warnings a plugin emits on stdout
# can never be misread as a reachable platform.
_PROBE_SENTINEL = "PROBE_PLATFORM="
_PROBE_SRC = """
import jax, jax.numpy as jnp
print("PROBE_PLATFORM=" + jax.devices()[0].platform)
print(jax.device_get((jnp.ones((128, 128), jnp.bfloat16)
                      @ jnp.ones((128, 128), jnp.bfloat16)).sum()))
"""


def accelerator_expected() -> bool:
    """True when the environment is configured for a non-CPU backend."""
    platforms = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if platforms and set(platforms.split(",")) - {"cpu", ""}:
        return True
    # The axon relay plugin registers itself (and resets jax_platforms to
    # prefer itself) whenever this var is set, regardless of JAX_PLATFORMS.
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS"))


def probe_backend(timeout_s: float):
    """Platform string of device 0, or None if unreachable.

    'cpu' from an accelerator-configured environment counts as unreachable
    (a down relay can degrade to a silent CPU fallback).
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=max(timeout_s, 1.0),
        )
    except subprocess.TimeoutExpired:
        return None
    if proc.returncode != 0:
        return None
    platform = None
    for line in proc.stdout.splitlines():
        if line.startswith(_PROBE_SENTINEL):
            platform = line[len(_PROBE_SENTINEL):].strip() or None
            break
    if platform is None:  # sentinel absent: stdout was banners, not a probe
        return None
    if platform == "cpu" and accelerator_expected():
        return None
    return platform


def unreachable_message(tag: str, deadline_s: float) -> str:
    """The one abort line wrapper scripts grep for — single definition so
    bench.py (which layers a parseable stdout JSON record on top) and
    train.py cannot drift from each other."""
    return (
        f"{tag}: accelerator backend unreachable within "
        f"--backend-wait={deadline_s:.0f}s; aborting"
    )


def wait_for_backend(deadline_s: float = 600.0, poll_s: float = 30.0,
                     probe_s: float = 90.0, tag: str = "backend-probe",
                     probe_log: Optional[list] = None):
    """Poll the accelerator relay until it answers or the deadline passes.

    Returns the platform string, or None when the deadline expired (the
    caller decides whether to proceed or abort — proceeding will hang if
    the relay is truly wedged). CPU-only environments skip the probe and
    return 'cpu'; healthy accelerator environments pay one subprocess JAX
    init (~10-30 s — noise next to the multi-minute relay compile).
    Per-probe timeouts are clamped to the remaining deadline, and the wait
    only gives up once ~1 s of budget remains — the last probe runs with
    whatever is left rather than abandoning up to ``poll_s`` unused
    (ADVICE.md r5). Logs to stderr under ``tag``.

    ``probe_log``: optional list the wait appends one dict per probe to
    (``attempt``/``elapsed_s``/``platform``) — the machine-readable probe
    timeline run manifests and bench.py's backend-unreachable JSON record
    carry instead of re-parsing the stderr prose.
    """
    if not accelerator_expected():
        if probe_log is not None:
            probe_log.append(
                {"attempt": 0, "elapsed_s": 0.0, "platform": "cpu"}
            )
        return "cpu"
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline_s - (time.monotonic() - t0)
        platform = probe_backend(timeout_s=min(probe_s, max(remaining, 1.0)))
        if probe_log is not None:
            probe_log.append({
                "attempt": attempt,
                "elapsed_s": round(time.monotonic() - t0, 1),
                "platform": platform,
            })
        if platform is not None:
            if attempt > 1:
                print(
                    f"{tag}: backend '{platform}' reachable after "
                    f"{time.monotonic() - t0:.0f}s ({attempt} probes)",
                    file=sys.stderr,
                )
            return platform
        remaining = deadline_s - (time.monotonic() - t0)
        if remaining <= 1.0:
            print(
                f"{tag}: backend unreachable after "
                f"{time.monotonic() - t0:.0f}s ({attempt} probes); "
                "giving up",
                file=sys.stderr,
            )
            return None
        # Sleep at most poll_s, but never past the point where only the
        # final clamped probe's budget remains.
        sleep_s = min(poll_s, max(remaining - 1.0, 0.0))
        print(
            f"{tag}: backend probe {attempt} failed at "
            f"{time.monotonic() - t0:.0f}s; retrying in {sleep_s:.0f}s",
            file=sys.stderr,
        )
        time.sleep(sleep_s)


def require_backend_or_exit(deadline_s: float, tag: str, exit_code: int = 3,
                            manifest=None):
    """``wait_for_backend`` or abort the process with ``exit_code``.

    Single definition of the abort contract (message format + exit 3) that
    wrapper scripts key on; used by ``train.py`` directly and mirrored by
    ``bench.py`` (which adds a parseable stdout JSON record on top of the
    same :func:`unreachable_message`). Returns the platform string on
    success.

    ``manifest``: optional :class:`~sav_tpu.obs.manifest.RunManifest`
    finalized with ``outcome: "backend_unreachable"`` + the probe timeline
    before the abort, so the run record never degrades to prose-only
    (the BENCH_r05 failure mode).
    """
    probe_log: list = []
    platform = wait_for_backend(
        deadline_s=deadline_s, tag=tag, probe_log=probe_log
    )
    if platform is None:
        # Proceeding would hang in in-process backend init (the wedged
        # relay fails by hanging, not erroring); a prompt labeled exit
        # beats a job that stalls forever holding its slot.
        message = unreachable_message(tag, deadline_s)
        if manifest is not None:
            manifest.finalize(
                "backend_unreachable",
                error=message,
                exit_code=exit_code,
                notes={"backend_probe": {
                    "deadline_s": deadline_s,
                    "attempts": len(probe_log),
                    "probes": probe_log,
                }},
            )
        print(message, file=sys.stderr)
        raise SystemExit(exit_code)  # savlint: disable=SAV114 -- THE documented exit-3 abort contract wrapper scripts and the supervisor key on; the manifest was finalized above
    return platform
