"""Runtime lock sanitizer: the observed half of the lock-order proof.

SAV122 proves the *static* acquisition graph cycle-free; this module
checks the claim against reality, in the StepSanitizer tradition of
"instrument the real run, fail loudly on contract breach". A
:class:`LockWatch` patches ``threading`` inside chosen sav_tpu modules
so every ``threading.Lock()`` / ``threading.RLock()`` they construct
comes back wrapped: each acquire records the per-thread held stack
(every held lock gains an edge to the newly-acquired one — the
*observed* acquisition-order graph), each release records the hold
time. After the run:

- :meth:`LockWatch.cycles` — any cycle in the observed graph is a
  deadlock that merely hasn't scheduled yet; :meth:`check` raises.
- :meth:`LockWatch.unexplained_edges` — observed edges missing from the
  static graph (``build_lock_graph`` must over-approximate the runtime;
  an unexplained edge means the linter has a blind spot worth filing).
- :meth:`LockWatch.summary` / :meth:`write` — JSON for post-mortems and
  the battery's on-chip assertions; ``tools/lockgraph.py`` renders it.

Lock naming matches the static side's identities (``Router._lock``,
``sav_tpu.ops.attn_tuning._lock``) by inspecting the construction site:
the enclosing ``self``'s class plus the ``self._x = threading.Lock()``
source line, or the defining module for bare globals. Locks must be
constructed INSIDE the patch window — ``with watch.patch(mod): obj =
mod.Thing()`` — existing locks stay untracked real locks.

Condition/Event/Semaphore pass through untracked: ``Condition`` reaches
around ``acquire``/``release`` via ``_release_save``/``_acquire_restore``
and would silently corrupt the held stacks if wrapped naively. The
repo's modules use bare Lock/RLock, which is exactly what SAV122 models.

Overhead is one dict-free method call and a few list ops per acquire —
bounded by the lockwatch unit tests so arming chaos runs stays cheap.
"""

from __future__ import annotations

import contextlib
import json
import linecache
import re
import sys
import threading as _threading
import time
from typing import Any, Iterable, Optional


class LockWatchError(AssertionError):
    """The observed locking violated the concurrency contract."""


_ASSIGN_RE = re.compile(r"(?:self\.(?P<attr>\w+)|(?P<name>\w+))\s*=[^=]")


def _name_from_site(frame, default: str) -> str:
    """Static-graph identity for the lock constructed at ``frame``."""
    line = linecache.getline(
        frame.f_code.co_filename, frame.f_lineno
    ).strip()
    m = _ASSIGN_RE.match(line)
    attr = m.group("attr") if m else None
    bare = m.group("name") if m else None
    owner = frame.f_locals.get("self")
    if attr is not None and owner is not None:
        return f"{type(owner).__name__}.{attr}"
    if frame.f_code.co_name == "<module>" and bare is not None:
        return f"{frame.f_globals.get('__name__', 'module')}.{bare}"
    if bare is not None:
        return f"{frame.f_globals.get('__name__', 'module')}.{bare}"
    return default


class _TrackedLock:
    """A Lock/RLock that reports acquire/release to its LockWatch."""

    def __init__(self, watch: "LockWatch", name: str, inner, reentrant: bool):
        self._watch = watch
        self.name = name
        self._inner = inner
        self._reentrant = reentrant
        self._depth = _threading.local()  # reentrant depth, per thread

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            depth = getattr(self._depth, "n", 0)
            self._depth.n = depth + 1
            if depth == 0:  # RLock re-entry is not a new acquisition
                self._watch._note_acquire(self)
        return got

    def release(self):
        depth = getattr(self._depth, "n", 1)
        self._depth.n = depth - 1
        if depth - 1 == 0:
            self._watch._note_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<TrackedLock {self.name!r} wrapping {self._inner!r}>"


class _ThreadingProxy:
    """Stand-in for the ``threading`` module inside a patched module.

    ``Lock``/``RLock`` construct tracked wrappers; everything else —
    ``Thread``, ``Event``, ``Condition``, ``local``, constants — falls
    through to the real module untouched.
    """

    def __init__(self, watch: "LockWatch"):
        self._watch = watch

    def Lock(self):  # noqa: N802 — mirrors the stdlib name
        return self._watch._make(sys._getframe(1), reentrant=False)

    def RLock(self):  # noqa: N802
        return self._watch._make(sys._getframe(1), reentrant=True)

    def __getattr__(self, name: str) -> Any:
        return getattr(_threading, name)


class LockWatch:
    """Collects the observed acquisition graph across tracked locks."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._mu = _threading.Lock()  # guards the aggregates below
        self._held = _threading.local()  # per-thread stack of (lock, t0)
        self._locks: dict[str, int] = {}  # name -> times acquired
        self._edges: dict[tuple, dict] = {}  # (src, dst) -> {count, threads}
        self._hold_s: dict[str, float] = {}  # name -> max hold seconds
        self._serial = 0

    # ------------------------------------------------------ construction

    def _make(self, frame, reentrant: bool) -> _TrackedLock:
        with self._mu:
            self._serial += 1
            default = f"lock#{self._serial}"
        name = _name_from_site(frame, default)
        inner = _threading.RLock() if reentrant else _threading.Lock()
        return _TrackedLock(self, name, inner, reentrant)

    @contextlib.contextmanager
    def patch(self, *modules):
        """Swap a tracking ``threading`` into each module's globals.

        Locks the modules construct inside the window are tracked;
        the originals are restored on exit no matter what raised.
        """
        proxy = _ThreadingProxy(self)
        saved: list = []
        for mod in modules:
            if "threading" in mod.__dict__:
                saved.append((mod, mod.__dict__["threading"]))
                mod.__dict__["threading"] = proxy
        try:
            yield self
        finally:
            for mod, real in saved:
                mod.__dict__["threading"] = real

    # -------------------------------------------------------- recording

    def _stack(self) -> list:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _note_acquire(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        tname = _threading.current_thread().name
        with self._mu:
            self._locks[lock.name] = self._locks.get(lock.name, 0) + 1
            for held, _t0 in stack:
                key = (held.name, lock.name)
                e = self._edges.setdefault(
                    key, {"count": 0, "threads": []}
                )
                e["count"] += 1
                if tname not in e["threads"] and len(e["threads"]) < 8:
                    e["threads"].append(tname)
        stack.append((lock, self._clock()))

    def _note_release(self, lock: _TrackedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                _l, t0 = stack.pop(i)
                held_s = self._clock() - t0
                with self._mu:
                    if held_s > self._hold_s.get(lock.name, 0.0):
                        self._hold_s[lock.name] = held_s
                return

    # -------------------------------------------------------- reporting

    def edges(self) -> list:
        with self._mu:
            return [
                {"src": s, "dst": d, **v}
                for (s, d), v in sorted(self._edges.items())
            ]

    def cycles(self) -> list:
        from sav_tpu.analysis.concurrency import find_cycles

        return find_cycles(self.edges())

    def unexplained_edges(self, static_graph: dict) -> list:
        """Observed edges the static graph does not predict.

        The static pass must over-approximate the runtime; an observed
        edge it missed is a linter blind spot (an acquisition through
        getattr indirection, a callback it could not resolve). Only
        edges between locks the static side KNOWS about count — helper
        locks private to a test harness are not a mismatch.
        """
        known = {n["id"] for n in static_graph["nodes"]}
        predicted = {(e["src"], e["dst"]) for e in static_graph["edges"]}
        return [
            e
            for e in self.edges()
            if e["src"] in known
            and e["dst"] in known
            and (e["src"], e["dst"]) not in predicted
        ]

    def check(self, static_graph: Optional[dict] = None) -> None:
        """Raise :class:`LockWatchError` on any observed cycle, or any
        observed edge a provided static graph failed to predict."""
        cycles = self.cycles()
        if cycles:
            loops = "; ".join(" -> ".join(c) for c in cycles)
            raise LockWatchError(
                f"observed lock-order cycle(s): {loops} — this schedule "
                "deadlocks when the interleaving lands the other way"
            )
        if static_graph is not None:
            missing = self.unexplained_edges(static_graph)
            if missing:
                listed = "; ".join(
                    f"{e['src']} -> {e['dst']} (x{e['count']})"
                    for e in missing
                )
                raise LockWatchError(
                    f"observed acquisition edges the static graph does "
                    f"not predict: {listed} — SAV122 has a blind spot "
                    "here; extend the analysis or re-rank the locks"
                )

    def summary(self) -> dict:
        with self._mu:
            hold_ms = {
                k: round(v * 1e3, 3) for k, v in sorted(self._hold_s.items())
            }
            locks = dict(sorted(self._locks.items()))
        return {
            "locks": locks,
            "edges": self.edges(),
            "cycles": [list(c) for c in self.cycles()],
            "max_hold_ms": hold_ms,
        }

    def write(self, path: str) -> dict:
        doc = self.summary()
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        return doc


def watch_modules(module_names: Iterable[str], clock=time.perf_counter):
    """Import-and-patch convenience for drivers (serve_bench/chaos_soak):
    returns ``(watch, context)`` where entering ``context`` arms tracking
    in every named module that is importable."""
    import importlib

    mods = []
    for name in module_names:
        try:
            mods.append(importlib.import_module(name))
        except ImportError:
            continue
    watch = LockWatch(clock=clock)
    return watch, watch.patch(*mods)
