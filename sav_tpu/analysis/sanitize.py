"""Runtime sanitizers: hard-fail the invariants savlint cannot prove.

Static analysis (savlint) catches the *lexical* shapes of the classic
TPU hot-loop regressions; this module catches the *dynamic* ones, on an
opt-in flag (``TrainConfig.sanitize`` / ``train.py --sanitize``), in the
spirit of ASan/TSan: cheap enough to leave on for smoke runs, loud the
instant the discipline breaks instead of hours later in a goodput
report.

Two sanitizers, both scoped to the steady-state hot loop (armed after
the first completed step, so compilation and one-time setup transfers
are exempt):

- **Transfer sanitizer** — ``jax.transfer_guard_host_to_device
  ("disallow")``: implicit host→device transfers (a numpy batch leaking
  into the jitted step, a Python scalar silently uploaded per step)
  raise immediately. Explicit transfers stay legal, which is exactly
  the repo's contract: the feeder's ``device_put`` (on its own thread —
  the guard is thread-local and never sees it) and the serial
  fallback's explicit placement both pass. The device→host direction is
  deliberately unguarded: the loop's intentional syncs (log window,
  checkpoint serialization) are statically audited instead — each
  carries a savlint SAV101 pragma with its justification.
- **Retrace sanitizer** — a :class:`~sav_tpu.obs.memory.RetraceCounter`
  on the jitted step that raises :class:`RetraceSanitizerError` the
  moment the compile cache grows after warmup. PR 1's ``retraces``
  metric *reports* silent recompilation at the next log window; the
  sanitizer turns it into a step-attributed hard failure (on the relay
  each silent retrace is minutes of compile, so "fail at the step that
  caused it" beats "notice it in telemetry later").
"""

from __future__ import annotations

import contextlib
from typing import Optional

from sav_tpu.obs.memory import RetraceCounter


class RetraceSanitizerError(RuntimeError):
    """The jitted step re-traced after the sanitizer was armed."""


class StepSanitizer:
    """Arms both hot-loop sanitizers around a jitted step function.

    Lifecycle (mirrors fit()'s loop):

    - construct before the loop (counts any pre-loop traces as warmup);
    - :meth:`arm` after the FIRST completed step — swallows the warmup
      trace(s) and enters the transfer guard;
    - :meth:`check` after every subsequent dispatch — raises on a fresh
      trace (tracing is synchronous at call time, so a retrace is
      visible the moment the dispatch returns);
    - :meth:`close` in the loop's ``finally`` — exits the transfer
      guard (it is a thread-local config context and must unwind on the
      thread that entered it).

    ``transfer_guard=None`` disables the transfer arm (retrace checking
    only) for callers embedded in code that legitimately relies on
    implicit transfers.
    """

    def __init__(
        self,
        jit_fn,
        *,
        transfer_guard: Optional[str] = "disallow",
        tag: str = "sanitize",
    ):
        self._retraces = RetraceCounter(jit_fn)
        self._transfer_guard = transfer_guard
        self._tag = tag
        self._stack = contextlib.ExitStack()
        self.armed = False

    def arm(self) -> None:
        """Enter steady state: warmup traces forgiven, guards live."""
        if self.armed:
            return
        if self._transfer_guard is not None:
            import jax

            self._stack.enter_context(
                jax.transfer_guard_host_to_device(self._transfer_guard)
            )
        self._retraces.delta()  # the first compile is expected, not a retrace
        self.armed = True

    def check(self, step: int) -> None:
        """Raise if the step function traced again since the last check."""
        if not self.armed:
            return
        new = self._retraces.delta()
        if new:
            raise RetraceSanitizerError(
                f"{self._tag}: jitted step re-traced {new}x at step {step} — "
                "steady-state dispatch must hit the compile cache. Usual "
                "causes: a batch whose shape/dtype drifted, a Python scalar "
                "argument that changed value, or a leaked weak type. "
                "Reproduce the trigger with savlint (SAV104) or "
                "TrainConfig.diagnostics retrace telemetry, then pin the "
                "offending argument."
            )

    def close(self) -> None:
        """Unwind the transfer guard; idempotent, safe before arm()."""
        self._stack.close()
        self.armed = False

    def __enter__(self) -> "StepSanitizer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def active(self) -> bool:
        """False when the running jax cannot count traces (the counter
        degrades to zero — the retrace arm is then a no-op)."""
        return self._retraces.active
