"""Whole-program concurrency analysis: locksets, lock order, liveness.

The serving arc made sav_tpu genuinely concurrent — feeder, batcher,
engine device loop, router dispatch workers, replica supervisors,
heartbeat writers, watchdog, autoprof, recorder all share state across
stdlib threads — and every concurrency bug shipped so far (the batcher
and router submit/close TOCTOU strandings, ``Router.admit``'s two-lock
max_inflight overshoot, the HeartbeatWriter deadlock on a hung FS) was
caught by hand in review. This module is the static half of the fix:
the first :class:`~sav_tpu.analysis.lint.ProjectRule` pass, seeing all
linted modules at once, in the classic pairing of lockset analysis
(Eraser — Savage et al. 1997) and acquisition-order cycle detection
(GoodLock — Havelund 2000):

- **SAV121 unguarded-shared-state** — per class, every
  ``threading.Lock/RLock/Condition`` attribute is inventoried and the
  *guarded set* inferred (attributes accessed under ``with self._lock``
  in any method). A guarded attribute read or written WITHOUT the lock
  in a method reachable from a ``Thread`` target or registered callback
  is the Eraser lockset violation. Methods whose every intra-class call
  site holds the lock (the ``_window_snapshot`` "owner must hold the
  lock" idiom) inherit that guard, so the flow-insensitive pass does
  not flag lock-held helpers.
- **SAV122 lock-order-cycle** — every nested ``with``-acquisition (and
  every call made WHILE holding a lock into a method/function that
  acquires more, across classes and files via ``self.attr``-type
  inference) contributes a directed edge to ONE repo-wide graph; any
  cycle is a deadlock-in-waiting and an error. ``tools/lockgraph.py``
  renders this graph; :mod:`sav_tpu.analysis.lockwatch` checks the
  *observed* graph against it at runtime.
- **SAV123 unbounded-blocking-call** — a zero-argument ``acquire()`` /
  ``join()`` / ``get()`` / ``wait()`` (or an explicit ``timeout=None``)
  in the modules bound by the watchdog exit-4 and heartbeat
  bounded-lock contracts (``serve/``, ``obs/``, ``data/``, ``train/``).
  The zero-argument spellings are exactly the block-forever forms
  (``dict.get`` needs a key, ``str.join`` an iterable — no false
  positives from those), and the contracts require every block to be
  bounded: the HeartbeatWriter's ``acquire(timeout=...)`` discipline
  and the watchdog's bounded dumper joins are the in-repo exemplars.
- **SAV124 thread-leak** — a ``threading.Thread(...)`` started with
  ``daemon`` unset and never ``join``ed (by its bound name) anywhere in
  the module: on interpreter exit a non-daemon thread blocks process
  teardown forever — the quiet cousin of the hang the watchdog exists
  to abort.

Known limits, by design (heuristics, not proofs — the savlint charter):
bounded ``lock.acquire(timeout=...)`` guards are not credited to the
guarded set (the HeartbeatWriter deliberately drops rather than blocks,
so its attributes are protected by that discipline, not by ``with``);
``threading.Thread`` *subclasses* are not traced to their constructor
kwargs (SAV124) though a ``run()`` method on one IS a thread target
(SAV121); and attribute types resolve by bare class name across the
linted set. The pragma system covers the residue, with justifications.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from sav_tpu.analysis.lint import Finding, ModuleInfo, ProjectRule

LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}

# Primitives that synchronize internally: reading/calling them without a
# lock is their entire point, so they never enter the guarded set.
SELF_SYNCHRONIZED_FACTORIES = frozenset(
    {
        "threading.Event",
        "queue.Queue",
        "queue.SimpleQueue",
        "queue.LifoQueue",
        "queue.PriorityQueue",
        "collections.deque",
    }
)

# Modules bound by a bounded-blocking contract: the watchdog's exit-4
# guarantee (docs/elasticity.md) presumes no thread blocks forever, and
# the heartbeat writers promise drop-never-block (docs/fleet.md).
BOUNDED_CONTRACT_PATHS = (
    "sav_tpu/serve/",
    "sav_tpu/obs/",
    "sav_tpu/data/",
    "sav_tpu/train/",
)

_BLOCKING_VERBS = frozenset({"acquire", "join", "get", "wait"})

# Method names that mutate their receiver in place: calling one on a
# self-attribute IS a write to that attribute for lockset purposes
# (``self._window.clear()`` races ``self._window.append()`` exactly as
# an assignment would), even though the AST context is a Load.
_MUTATOR_METHODS = frozenset(
    {
        "append", "appendleft", "extend", "insert", "remove", "clear",
        "pop", "popitem", "popleft", "add", "discard", "update",
        "setdefault", "sort", "reverse",
    }
)


def _module_dotted(module: ModuleInfo) -> str:
    rel = module.relpath
    if rel.endswith(".py"):
        rel = rel[:-3]
    return rel.replace("/", ".")


def _is_self_attr(node) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _site(module: ModuleInfo, node) -> dict:
    return {
        "path": module.relpath,
        "line": getattr(node, "lineno", 1),
        "code": module.function_source_line(getattr(node, "lineno", 1)),
    }


# ------------------------------------------------------------- inventory


class _ClassFacts:
    """Everything the four rules need to know about one class."""

    def __init__(self, module: ModuleInfo, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.methods = {
            n.name: n
            for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: dict[str, dict] = {}  # attr -> {kind, line}
        self.sync_attrs: set = set()
        self.attr_types: dict[str, str] = {}  # attr -> bare class name
        self.thread_targets: set = set()
        self.callback_refs: set = set()
        # filled by _analyze_method, keyed by method name:
        self.accesses: dict[str, list] = {}
        self.acquires: dict[str, list] = {}
        self.calls: dict[str, list] = {}
        self.call_sites: dict[str, list] = {}  # callee -> [held-set, ...]

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{attr}"


class _ModuleFacts:
    def __init__(self, module: ModuleInfo):
        self.module = module
        self.dotted = _module_dotted(module)
        self.classes: dict[str, _ClassFacts] = {}
        self.global_locks: dict[str, dict] = {}  # bare name -> {id, kind}
        # module-level function name -> FunctionDef (for cross-module
        # acquire closures, e.g. attn_tuning.lookup's ``with _lock:``)
        self.functions: dict[str, ast.FunctionDef] = {}
        self.fn_acquires: dict[str, list] = {}
        self.fn_calls: dict[str, list] = {}


def _inventory_class(module: ModuleInfo, cls: ast.ClassDef) -> _ClassFacts:
    facts = _ClassFacts(module, cls)
    for base in cls.bases:
        resolved = module.resolve(base)
        bare = base.attr if isinstance(base, ast.Attribute) else getattr(
            base, "id", None
        )
        if resolved == "threading.Thread" or bare == "Thread":
            facts.thread_targets.add("run")
    for node in ast.walk(cls):
        if isinstance(node, ast.Call):
            resolved = module.resolve_call(node)
            if resolved == "threading.Thread":
                for k in node.keywords:
                    if k.arg == "target" and _is_self_attr(k.value):
                        facts.thread_targets.add(k.value.attr)
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if _is_self_attr(arg):
                    facts.callback_refs.add(arg.attr)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            resolved = module.resolve_call(node.value)
            for t in node.targets:
                if not _is_self_attr(t):
                    continue
                if resolved in LOCK_FACTORIES:
                    facts.lock_attrs[t.attr] = {
                        "kind": LOCK_FACTORIES[resolved],
                        "line": node.lineno,
                    }
                elif resolved in SELF_SYNCHRONIZED_FACTORIES:
                    facts.sync_attrs.add(t.attr)
                else:
                    # ``self._ring = SpanRing(...)`` — remember the bare
                    # constructor name so a call on the attribute can be
                    # resolved to that class's lock acquisitions.
                    fn = node.value.func
                    bare = (
                        fn.attr if isinstance(fn, ast.Attribute)
                        else getattr(fn, "id", None)
                    )
                    if bare and bare[:1].isupper():
                        facts.attr_types[t.attr] = bare
    facts.callback_refs &= set(facts.methods)
    return facts


def _inventory_module(module: ModuleInfo) -> _ModuleFacts:
    mf = _ModuleFacts(module)
    for stmt in module.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            resolved = module.resolve_call(stmt.value)
            if resolved in LOCK_FACTORIES:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        mf.global_locks[t.id] = {
                            "id": f"{mf.dotted}.{t.id}",
                            "kind": LOCK_FACTORIES[resolved],
                            "line": stmt.lineno,
                        }
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mf.functions[stmt.name] = stmt
    for cls in module.classes:
        facts = _inventory_class(module, cls)
        mf.classes[facts.name] = facts
    return mf


# ------------------------------------------------- per-function analysis


def _analyze_body(
    mf: _ModuleFacts,
    facts: Optional[_ClassFacts],
    fn,
):
    """(accesses, acquires, calls) for one function body.

    Tracks the lexically-held lock set through ``with`` statements —
    the SAV107 protection-tracking visitor, extended with acquisition
    ORDER (``held_before`` per acquire, the GoodLock edge source) and a
    call ledger (who is invoked while which locks are held).
    """
    module = mf.module
    accesses: list = []
    acquires: list = []
    calls: list = []

    def lock_of(expr) -> Optional[str]:
        if (
            facts is not None
            and _is_self_attr(expr)
            and expr.attr in facts.lock_attrs
        ):
            return facts.lock_id(expr.attr)
        if isinstance(expr, ast.Name) and expr.id in mf.global_locks:
            return mf.global_locks[expr.id]["id"]
        return None

    def visit(node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # closures run in their own thread context (SAV107)
        if isinstance(node, ast.With):
            inner = list(held)
            for item in node.items:
                lid = lock_of(item.context_expr)
                if lid is not None:
                    acquires.append((lid, item.context_expr, tuple(inner)))
                    inner.append(lid)
                else:
                    visit(item.context_expr, held)
            for child in node.body:
                visit(child, inner)
            return
        if (
            facts is not None
            and _is_self_attr(node)
            and node.attr not in facts.methods
        ):
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            accesses.append((node.attr, node, is_write, frozenset(held)))
            return
        if (
            facts is not None
            and isinstance(node, ast.Subscript)
            and isinstance(node.ctx, (ast.Store, ast.Del))
            and _is_self_attr(node.value)
        ):
            # self.x[k] = v / del self.x[k]: a WRITE to x's contents.
            accesses.append(
                (node.value.attr, node.value, True, frozenset(held))
            )
        if isinstance(node, ast.Call):
            f = node.func
            if facts is not None and _is_self_attr(f):
                calls.append(("self", f.attr, node, frozenset(held)))
            elif (
                facts is not None
                and isinstance(f, ast.Attribute)
                and _is_self_attr(f.value)
            ):
                calls.append(
                    ("attr", (f.value.attr, f.attr), node, frozenset(held))
                )
                if f.attr in _MUTATOR_METHODS:
                    # self.x.append(...) writes x as surely as = does.
                    accesses.append(
                        (f.value.attr, f.value, True, frozenset(held))
                    )
            else:
                resolved = module.resolve_call(node)
                if resolved is not None:
                    calls.append(("global", resolved, node, frozenset(held)))
                elif isinstance(f, ast.Name) and f.id in mf.functions:
                    calls.append(
                        ("global", f"{mf.dotted}.{f.id}", node,
                         frozenset(held))
                    )
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in fn.body:
        visit(stmt, [])
    return accesses, acquires, calls


def _analyze(modules: list) -> dict:
    """The shared whole-program pass, memoized per lint run."""
    mfs = [_inventory_module(m) for m in modules]
    classes_by_name: dict[str, _ClassFacts] = {}
    for mf in mfs:
        for name, facts in mf.classes.items():
            classes_by_name.setdefault(name, facts)
    for mf in mfs:
        for facts in mf.classes.values():
            for mname, method in facts.methods.items():
                acc, acq, cal = _analyze_body(mf, facts, method)
                facts.accesses[mname] = acc
                facts.acquires[mname] = acq
                facts.calls[mname] = cal
                for kind, name, _node, held in cal:
                    if kind == "self":
                        facts.call_sites.setdefault(name, []).append(held)
        for fname, fn in mf.functions.items():
            _acc, acq, cal = _analyze_body(mf, None, fn)
            mf.fn_acquires[fname] = acq
            mf.fn_calls[fname] = cal
    return {"mfs": mfs, "classes": classes_by_name}


_CACHE: dict = {"modules": None, "value": None}


def _analysis_for(modules: list) -> dict:
    """Memoize on the identity of the module list: the four rules run
    back-to-back over the same ``lint_paths`` parse, and the whole-
    program pass must not run four times (the wall-time budget). The
    cache holds strong references, so identity comparison is sound —
    a cached module's id cannot be recycled while it is cached."""
    cached = _CACHE["modules"]
    if (
        cached is None
        or len(cached) != len(modules)
        or any(a is not b for a, b in zip(cached, modules))
    ):
        _CACHE["modules"] = list(modules)
        _CACHE["value"] = _analyze(modules)
    return _CACHE["value"]


# --------------------------------------------------- acquire closures


def _acquire_closure(analysis: dict) -> dict:
    """callable-key -> set of lock ids it may acquire (transitively).

    Keys: ``("m", ClassName, method)`` and ``("f", module.dotted, fn)``.
    Cross-class edges resolve ``self.attr.method()`` through the
    inventory's attr types; cross-module function calls resolve through
    each file's import aliases to the defining module's dotted name.
    """
    classes = analysis["classes"]
    fns: dict[str, tuple] = {}
    for mf in analysis["mfs"]:
        for fname in mf.functions:
            fns[f"{mf.dotted}.{fname}"] = (mf, fname)

    memo: dict = {}

    def closure(key, stack) -> set:
        if key in memo:
            return memo[key]
        if key in stack:
            return set()  # recursion: the partial set is enough
        stack = stack | {key}
        out: set = set()
        if key[0] == "m":
            facts = classes.get(key[1])
            if facts is None or key[2] not in facts.methods:
                return set()
            acquires = facts.acquires.get(key[2], [])
            calls = facts.calls.get(key[2], [])
        else:
            mf, fname = fns.get(f"{key[1]}.{key[2]}", (None, None))
            if mf is None:
                return set()
            acquires = mf.fn_acquires.get(fname, [])
            calls = mf.fn_calls.get(fname, [])
        for lid, _node, _held in acquires:
            out.add(lid)
        for kind, name, _node, _held in calls:
            for sub in _resolve_callee(analysis, key, kind, name):
                out |= closure(sub, stack)
        memo[key] = out
        return out

    keys = [("m", c, m) for c, f in classes.items() for m in f.methods]
    keys += [("f", mf.dotted, fname) for mf, fname in fns.values()]
    for key in keys:
        closure(key, frozenset())
    return memo


def _resolve_callee(analysis, caller_key, kind, name) -> list:
    """Callable keys a recorded call might land on (possibly empty)."""
    classes = analysis["classes"]
    if kind == "self":
        return [("m", caller_key[1], name)]
    if kind == "attr":
        attr, meth = name
        owner = classes.get(caller_key[1])
        if owner is None:
            return []
        cls_name = owner.attr_types.get(attr)
        if cls_name and cls_name in classes:
            return [("m", cls_name, meth)]
        return []
    # kind == "global": dotted name -> module function (never a class —
    # constructing an object acquires nothing in this repo's idiom)
    if "." in name:
        mod, fname = name.rsplit(".", 1)
        return [("f", mod, fname)]
    return []


# ------------------------------------------------------ the lock graph


def build_lock_graph(modules: list) -> dict:
    """The repo-wide static acquisition-order graph.

    Nodes are lock identities (``Class.attr`` / ``module.GLOBAL``);
    a directed edge A→B means somewhere, B is acquired while A is held —
    either lexically nested ``with`` blocks or a call made under A into
    code whose transitive acquire set contains B. Returned shape is
    JSON-ready for tools/lockgraph.py.
    """
    analysis = _analysis_for(modules)
    closures = _acquire_closure(analysis)
    nodes: dict[str, dict] = {}
    edges: dict[tuple, dict] = {}

    def note_edge(src, dst, module, node, via):
        if src == dst:
            kind = nodes.get(src, {}).get("kind")
            if kind == "RLock":
                return  # re-entry is an RLock's contract, not a cycle
        e = edges.setdefault(
            (src, dst), {"src": src, "dst": dst, "sites": []}
        )
        if len(e["sites"]) < 8:
            site = _site(module, node)
            if via:
                site["via"] = via
            e["sites"].append(site)

    for mf in analysis["mfs"]:
        for name, info in mf.global_locks.items():
            nodes[info["id"]] = {
                "id": info["id"],
                "kind": info["kind"],
                "path": mf.module.relpath,
                "line": info["line"],
            }
        for facts in mf.classes.values():
            for attr, info in facts.lock_attrs.items():
                lid = facts.lock_id(attr)
                nodes[lid] = {
                    "id": lid,
                    "kind": info["kind"],
                    "path": mf.module.relpath,
                    "line": info["line"],
                }
    for mf in analysis["mfs"]:
        for facts in mf.classes.values():
            for mname in facts.methods:
                for lid, node, held in facts.acquires.get(mname, []):
                    for h in held:
                        note_edge(h, lid, mf.module, node, None)
                for kind, name, node, held in facts.calls.get(mname, []):
                    if not held:
                        continue
                    for key in _resolve_callee(
                        analysis, ("m", facts.name, mname), kind, name
                    ):
                        for lid in closures.get(key, set()):
                            for h in held:
                                note_edge(
                                    h, lid, mf.module, node,
                                    f"{key[1]}.{key[2]}",
                                )
        for fname in mf.functions:
            for lid, node, held in mf.fn_acquires.get(fname, []):
                for h in held:
                    note_edge(h, lid, mf.module, node, None)
            for kind, name, node, held in mf.fn_calls.get(fname, []):
                if not held:
                    continue
                for key in _resolve_callee(
                    analysis, ("f", mf.dotted, fname), kind, name
                ):
                    for lid in closures.get(key, set()):
                        for h in held:
                            note_edge(
                                h, lid, mf.module, node,
                                f"{key[1]}.{key[2]}",
                            )
    for src, dst in edges:
        for lid in (src, dst):
            nodes.setdefault(
                lid, {"id": lid, "kind": "Lock", "path": "", "line": 0}
            )
    return {
        "nodes": [nodes[k] for k in sorted(nodes)],
        "edges": [edges[k] for k in sorted(edges)],
    }


def find_cycles(edges: list) -> list:
    """Elementary cycles in the acquisition graph (each as a node list,
    ``[A, B, A]``). Tarjan SCCs first, then one representative cycle
    per non-trivial SCC plus every self-edge — enough for an error
    message a human can act on, without path explosion."""
    adj: dict[str, list] = {}
    for e in edges:
        adj.setdefault(e["src"], []).append(e["dst"])
        adj.setdefault(e["dst"], [])
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        # iterative Tarjan: (node, iterator) frames
        work = [(v, iter(adj[v]))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    cycles = []
    edge_set = {(e["src"], e["dst"]) for e in edges}
    for scc in sccs:
        if len(scc) == 1:
            v = scc[0]
            if (v, v) in edge_set:
                cycles.append([v, v])
            continue
        # One representative cycle: walk within the SCC from its
        # smallest node until it closes.
        members = set(scc)
        start = min(scc)
        path = [start]
        seen = {start}
        node = start
        while True:
            nxt = min(
                (w for w in adj[node] if w in members), default=None
            )
            if nxt is None:
                break
            path.append(nxt)
            if nxt == start:
                cycles.append(path)
                break
            if nxt in seen:
                cycles.append(path[path.index(nxt):])
                break
            seen.add(nxt)
            node = nxt
    return cycles


# ---------------------------------------------------------------- SAV121


class UnguardedSharedState(ProjectRule):
    """A lock-guarded attribute touched without its lock on a thread path.

    The Eraser lockset discipline: if ANY method accesses ``self.x``
    under ``with self._lock``, then ``x`` is shared mutable state and
    every access from code another thread can execute (a ``Thread``
    target, a registered callback, or anything they call) must hold
    that lock too. A lockless read is a torn snapshot; a lockless write
    is a lost update — the ``Router._last_refresh`` check-then-act race
    (two dispatch workers both deciding to refresh) was exactly this
    shape. ``__init__`` runs before the thread exists and is exempt;
    ``Event``/``Queue``/``deque`` attributes synchronize internally and
    are exempt; underscore methods whose every intra-class call site
    holds the lock inherit the guard (the documented "caller must hold
    the lock" helpers).
    """

    id = "SAV121"
    name = "unguarded-shared-state"
    severity = "error"
    hint = (
        "take the guarding lock (with self._lock: ...) around this "
        "access, or move it into an existing critical section"
    )

    def check_project(self, modules: list) -> Iterator[Finding]:
        analysis = _analysis_for(modules)
        for mf in analysis["mfs"]:
            for facts in mf.classes.values():
                yield from self._check_class(mf, facts)

    def _check_class(self, mf, facts) -> Iterator[Finding]:
        if not facts.lock_attrs:
            return
        entries = facts.thread_targets | facts.callback_refs
        entries &= set(facts.methods)
        entries.discard("__init__")
        if not entries:
            return
        # Guarded set: attr -> the locks it has been seen held under.
        # Attributes never WRITTEN outside __init__ are immutable-after-
        # init (Eraser's read-shared state: clocks, config, callables
        # wired at construction) — reading one inside a critical section
        # does not make it shared mutable state, so they never enter the
        # guarded set at all.
        guards: dict[str, set] = {}
        mutable: set = set()
        for mname, accs in facts.accesses.items():
            for attr, _node, is_write, _held in accs:
                if is_write and mname != "__init__":
                    mutable.add(attr)
        for mname, accs in facts.accesses.items():
            for attr, _node, _w, held in accs:
                if held and attr in mutable and attr not in facts.lock_attrs:
                    guards.setdefault(attr, set()).update(held)
        if not guards:
            return
        # Inherited guard: private helpers invoked ONLY under the lock.
        inherited: dict[str, frozenset] = {}
        for mname in facts.methods:
            sites = facts.call_sites.get(mname, [])
            if (
                mname.startswith("_")
                and not mname.startswith("__")
                and mname not in entries
                and sites
            ):
                common = frozenset.intersection(*map(frozenset, sites))
                if common:
                    inherited[mname] = common
        # Reachability: thread targets/callbacks plus everything they
        # call on self — the code another thread can be inside.
        reachable = set()
        frontier = list(entries)
        while frontier:
            mname = frontier.pop()
            if mname in reachable or mname not in facts.methods:
                continue
            reachable.add(mname)
            for kind, name, _node, _held in facts.calls.get(mname, []):
                if kind == "self" and name not in reachable:
                    frontier.append(name)
        reachable.discard("__init__")
        seen: set = set()
        for mname in sorted(reachable):
            base = inherited.get(mname, frozenset())
            for attr, node, is_write, held in facts.accesses.get(mname, []):
                if attr in facts.sync_attrs or attr in facts.lock_attrs:
                    continue
                locks = guards.get(attr)
                if not locks:
                    continue
                if (held | base) & locks:
                    continue
                key = (mname, attr, node.lineno)
                if key in seen:
                    continue
                seen.add(key)
                lock_names = ", ".join(sorted(locks))
                verb = "written" if is_write else "read"
                yield Finding(
                    rule=self.id,
                    severity=self.severity,
                    path=mf.module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"self.{attr} is guarded by {lock_names} elsewhere "
                        f"but {verb} lock-free here, in {facts.name}."
                        f"{mname}() — reachable from thread entry point(s) "
                        f"{sorted(entries & (reachable | entries))[:3]}"
                    ),
                    hint=self.hint,
                    code="",
                    end_line=getattr(node, "end_lineno", 0) or node.lineno,
                )


# ---------------------------------------------------------------- SAV122


class LockOrderCycle(ProjectRule):
    """A cycle in the repo-wide lock acquisition-order graph.

    GoodLock's insight: you do not need to OBSERVE the deadlock, only
    the inconsistent order. Thread 1 holding A while taking B and
    thread 2 holding B while taking A deadlock the first time the
    schedule interleaves them — possibly months in, under load, on the
    serve fleet. Every nested acquisition in the linted set (including
    ones reached through calls made while holding a lock, across files)
    is an edge; a cycle is an error naming the full loop and every
    contributing site. The finding anchors at the cycle's first edge;
    the fix is to rank the locks (docs/concurrency.md's hierarchy) and
    release before calling down. A self-edge on a plain ``Lock`` (a
    method re-entering its own critical section via a call) is the
    one-lock special case and just as fatal; ``RLock`` re-entry is
    exempt.
    """

    id = "SAV122"
    name = "lock-order-cycle"
    severity = "error"
    hint = (
        "impose one acquisition order (docs/concurrency.md) — release "
        "the outer lock before calling into code that takes the other, "
        "or merge the two critical sections under one lock"
    )

    def check_project(self, modules: list) -> Iterator[Finding]:
        graph = build_lock_graph(modules)
        cycles = find_cycles(graph["edges"])
        if not cycles:
            return
        edges = {(e["src"], e["dst"]): e for e in graph["edges"]}
        for cycle in cycles:
            pairs = list(zip(cycle, cycle[1:]))
            sites = []
            for pair in pairs:
                e = edges.get(pair)
                if e and e["sites"]:
                    sites.append((pair, e["sites"][0]))
            if not sites:
                continue
            sites.sort(key=lambda s: (s[1]["path"], s[1]["line"]))
            (src, dst), anchor = sites[0]
            loop = " -> ".join(cycle)
            others = "; ".join(
                f"{a} -> {b} at {s['path']}:{s['line']}"
                for (a, b), s in sites[1:]
            )
            yield Finding(
                rule=self.id,
                severity=self.severity,
                path=anchor["path"],
                line=anchor["line"],
                col=0,
                message=(
                    f"lock-order cycle {loop}: this site acquires {dst} "
                    f"while holding {src}"
                    + (f"; closing edge(s): {others}" if others else "")
                ),
                hint=self.hint,
                code=anchor.get("code", ""),
                end_line=anchor["line"],
            )


# ---------------------------------------------------------------- SAV123


class UnboundedBlockingCall(ProjectRule):
    """A block-forever call in a module that promised it never blocks.

    ``serve/``, ``obs/``, ``data/`` and ``train/`` operate under two
    explicit liveness contracts: the watchdog guarantees exit-4 within
    its deadline even when the main thread is wedged (every dump/join
    on that path is bounded, docs/elasticity.md), and the heartbeat
    writers drop-never-block (``acquire(timeout=LOCK_TIMEOUT_S)``,
    docs/fleet.md). A bare ``acquire()`` / ``join()`` / ``get()`` /
    ``wait()`` — the zero-argument spellings ARE the unbounded forms;
    ``dict.get``/``str.join`` always take arguments, so this does not
    misfire on them — re-introduces exactly the unbounded wait those
    contracts exist to exclude: the ``Router._worker`` queue get was
    the live example (a worker blocked forever if ``close()`` died
    before posting its sentinel).
    """

    id = "SAV123"
    name = "unbounded-blocking-call"
    severity = "error"
    hint = (
        "pass a timeout (and handle expiry) — e.g. get(timeout=POLL_S) "
        "re-checking the stop flag, join(timeout=...), "
        "acquire(timeout=...) with a drop/degrade path"
    )

    def check_project(self, modules: list) -> Iterator[Finding]:
        for module in modules:
            if not module.relpath.startswith(BOUNDED_CONTRACT_PATHS):
                continue
            for node in module.nodes:
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_VERBS
                ):
                    continue
                unbounded = not node.args and not node.keywords
                if not unbounded:
                    unbounded = any(
                        k.arg == "timeout"
                        and isinstance(k.value, ast.Constant)
                        and k.value.value is None
                        for k in node.keywords
                    )
                if not unbounded:
                    continue
                yield Finding(
                    rule=self.id,
                    severity=self.severity,
                    path=module.relpath,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"unbounded .{node.func.attr}() in a module bound "
                        "by the watchdog exit-4 / heartbeat bounded-"
                        "blocking contracts — this call can block forever"
                    ),
                    hint=self.hint,
                    code="",
                    end_line=getattr(node, "end_lineno", 0) or node.lineno,
                )


# ---------------------------------------------------------------- SAV124


class ThreadLeak(ProjectRule):
    """A started thread nothing will ever reap.

    A ``threading.Thread`` with ``daemon`` unset is non-daemon: process
    exit blocks until it returns, so a worker looping on a queue keeps
    the interpreter alive forever — the silent cousin of the hang the
    watchdog aborts, except the watchdog has already exited. Every
    thread must either be a daemon (and then its OWNER must bound any
    join on it — SAV123) or be joined on all exit paths. The rule
    checks the binding: a construction with ``daemon=True``, a
    ``<name>.daemon = True`` assignment, or a ``<name>.join(...)``
    anywhere in the module clears it. (``Thread`` subclasses that set
    ``daemon`` in ``__init__`` are out of scope — their *instantiation*
    does not resolve to ``threading.Thread``.)
    """

    id = "SAV124"
    name = "thread-leak"
    severity = "warning"
    hint = (
        "pass daemon=True (plus a bounded join/close for orderly "
        "shutdown), or join the thread with a timeout on every exit "
        "path"
    )

    def check_project(self, modules: list) -> Iterator[Finding]:
        for module in modules:
            yield from self._check_module(module)

    def _check_module(self, module: ModuleInfo) -> Iterator[Finding]:
        thread_calls: list = []
        bound: dict[int, Optional[str]] = {}
        joined: set = set()
        daemoned: set = set()
        from sav_tpu.analysis.lint import _bare_name

        for node in module.nodes:
            if (
                isinstance(node, ast.Call)
                and module.resolve_call(node) == "threading.Thread"
            ):
                thread_calls.append(node)
            if isinstance(node, ast.Assign):
                if isinstance(node.value, ast.Call) and (
                    module.resolve_call(node.value) == "threading.Thread"
                ):
                    for t in node.targets:
                        bound[id(node.value)] = _bare_name(t)
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "daemon"
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True
                    ):
                        name = _bare_name(t.value)
                        if name:
                            daemoned.add(name)
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                name = _bare_name(node.func.value)
                if name:
                    joined.add(name)
        for call in thread_calls:
            daemon_kw = next(
                (k for k in call.keywords if k.arg == "daemon"), None
            )
            if (
                daemon_kw is not None
                and isinstance(daemon_kw.value, ast.Constant)
                and daemon_kw.value.value is True
            ):
                continue
            name = bound.get(id(call))
            if name and (name in joined or name in daemoned):
                continue
            where = f"bound to {name!r}" if name else "unbound"
            yield Finding(
                rule=self.id,
                severity=self.severity,
                path=module.relpath,
                line=call.lineno,
                col=call.col_offset,
                message=(
                    f"Thread created with daemon unset and never joined "
                    f"({where} in this module) — a leaked non-daemon "
                    "thread blocks interpreter exit forever"
                ),
                hint=self.hint,
                code="",
                end_line=getattr(call, "end_lineno", 0) or call.lineno,
            )


CONCURRENCY_RULES = [
    UnguardedSharedState(),
    LockOrderCycle(),
    UnboundedBlockingCall(),
    ThreadLeak(),
]
