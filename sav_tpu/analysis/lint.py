"""savlint core: file walking, AST facts, pragmas, baseline, reporting.

The linter is deliberately stdlib-only (``ast`` + ``re``): it must run in
CI frontends and pre-commit hooks that have no jax, no TPU relay, and no
interest in importing the training stack. Rules live in
:mod:`sav_tpu.analysis.rules`; this module owns everything rule-agnostic:

- **ModuleInfo** — one parsed file plus the shared facts every rule
  needs: an import-alias resolver (``jnp.zeros`` → ``jax.numpy.zeros``
  whatever the file called it), the set of functions that end up inside
  ``jax.jit`` (decorated, wrapped, or assigned), and the function table.
- **Pragmas** — ``# savlint: disable=SAV101 -- why`` suppresses the
  named rules on that statement; ``# savlint: disable-file=SAV108 --
  why`` suppresses for the whole file. The justification after ``--`` is
  mandatory: an allowlisted violation with no recorded reason is itself
  a finding (SAV100), so suppressions stay auditable instead of rotting
  into invisible exemptions.
- **Baseline** — ``sav_tpu/analysis/baseline.json`` carries bulk
  grandfathered findings keyed by (rule, path, source-line text) so they
  survive line-number drift; new occurrences of the same rule elsewhere
  still fail. Prefer pragmas for in-repo code (the justification lives
  next to the violation); the baseline exists for third-party-shaped
  bulk and for bootstrapping.

Exit-code contract (tools/savlint.py): 0 = clean, 1 = unsuppressed
findings, 2 = usage or internal error. ``--json`` emits the full finding
list for external CI.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator, Optional

# ---------------------------------------------------------------- findings


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    severity: str  # 'error' | 'warning'
    path: str  # root-relative posix path
    line: int
    col: int
    message: str
    hint: str
    code: str  # stripped source line the finding points at
    end_line: int = 0
    suppressed_by: Optional[str] = None  # None | 'pragma' | 'baseline'

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return d

    def format(self) -> str:
        tag = f" [suppressed: {self.suppressed_by}]" if self.suppressed_by else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.severity}: {self.message}{tag}\n"
            f"    {self.code}\n"
            f"    fix: {self.hint}"
        )


# ----------------------------------------------------------------- pragmas

_PRAGMA_RE = re.compile(
    r"#\s*savlint:\s*(disable|disable-file)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)"
    r"(?:\s+--\s*(?P<why>\S.*?))?\s*$"
)


@dataclasses.dataclass
class Pragma:
    line: int
    scope: str  # 'line' | 'file'
    rules: frozenset  # rule ids, upper-cased
    justification: Optional[str]


def parse_pragmas(source: str) -> list[Pragma]:
    """Pragmas from the file's *comment tokens* only.

    Tokenizing (rather than regex-scanning raw lines) means pragma text
    quoted inside a docstring — this repo documents the syntax in
    several module docstrings — is inert; only a real ``#`` comment
    arms a suppression.
    """
    import io
    import tokenize

    pragmas = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []  # the ast.parse in ModuleInfo reports the real error
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _PRAGMA_RE.search(tok.string)
        if not m:
            continue
        pragmas.append(
            Pragma(
                line=tok.start[0],
                scope="file" if m.group(1) == "disable-file" else "line",
                rules=frozenset(
                    r.strip().upper() for r in m.group("rules").split(",")
                ),
                justification=m.group("why"),
            )
        )
    return pragmas


# ------------------------------------------------------------- module facts


class ModuleInfo:
    """A parsed file plus the shared facts rules match against.

    ``resolve(node)`` canonicalizes Name/Attribute chains through the
    file's imports: ``import jax.numpy as jnp`` makes ``jnp.zeros``
    resolve to ``"jax.numpy.zeros"``; ``from jax import random`` makes
    ``random.split`` resolve to ``"jax.random.split"``. Unimported bare
    names resolve to None — a local variable named ``time`` never
    matches ``time.time``.
    """

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.pragmas = parse_pragmas(source)
        # ONE walk, shared by every rule: ``module.nodes`` replaces the
        # per-rule ``ast.walk(module.tree)`` re-walks (21 rules × N nodes
        # became 1 × N + 21 cheap list iterations — the wall-time budget
        # in test_savlint_self.py holds the line).
        self.nodes: list = list(ast.walk(self.tree))
        self.classes = [n for n in self.nodes if isinstance(n, ast.ClassDef)]
        self._aliases = self._collect_aliases()
        self.functions = [
            n
            for n in self.nodes
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        self.jitted_names, self.jitted_defs = self._collect_jitted()

    # -- imports

    def _collect_aliases(self) -> dict:
        aliases: dict[str, str] = {}
        for node in self.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node) -> Optional[str]:
        """Dotted canonical name for a Name/Attribute chain, or None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self._aliases.get(node.id)
        if base is None:
            return None
        return ".".join([base] + list(reversed(parts)))

    def resolve_call(self, call: ast.Call) -> Optional[str]:
        return self.resolve(call.func)

    # -- jit registry

    def _collect_jitted(self):
        """Names + FunctionDefs that end up inside ``jax.jit``.

        Covers the three idioms in this repo: ``self._step =
        jax.jit(self._step_impl, ...)`` (registers ``_step_impl`` as
        jit-traced and ``_step`` as a jitted callable), ``@jax.jit`` /
        ``@partial(jax.jit, ...)`` decorators, and bare ``jax.jit(f)``
        call expressions.
        """
        names: set[str] = set()
        for node in self.nodes:
            if isinstance(node, ast.Call) and self.resolve_call(node) == "jax.jit":
                if node.args:
                    target = node.args[0]
                    bare = _bare_name(target)
                    if bare is not None:
                        names.add(bare)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if self.resolve_call(node.value) == "jax.jit":
                    for t in node.targets:
                        bare = _bare_name(t)
                        if bare is not None:
                            names.add(bare)
        defs = set()
        for fn in self.functions:
            for dec in fn.decorator_list:
                resolved = self.resolve(dec)
                if resolved == "jax.jit":
                    defs.add(fn)
                    names.add(fn.name)
                elif isinstance(dec, ast.Call):
                    dec_fn = self.resolve_call(dec)
                    if dec_fn == "jax.jit" or (
                        dec_fn in ("functools.partial", "partial")
                        and dec.args
                        and self.resolve(dec.args[0]) == "jax.jit"
                    ):
                        defs.add(fn)
                        names.add(fn.name)
        defs |= {fn for fn in self.functions if fn.name in names}
        return names, defs

    def function_source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def _bare_name(node) -> Optional[str]:
    """Trailing identifier of a Name/Attribute (``self._f`` → ``_f``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


# ------------------------------------------------------------ project rules


class ProjectRule:
    """A rule that sees EVERY linted module at once (whole-program).

    Per-file rules (:class:`~sav_tpu.analysis.rules.Rule`) are blind to
    anything outside their module — fine for host-sync and dtype
    hygiene, structurally insufficient for concurrency: a lock-order
    cycle is two files each locally innocent. ``check_project`` receives
    the full list of parsed :class:`ModuleInfo` objects; findings carry
    ``path`` set to the owning module's relpath so pragma/baseline
    suppression applies exactly as for per-file findings. Subclasses
    live in :mod:`sav_tpu.analysis.concurrency`.
    """

    id: str = ""
    name: str = ""
    severity: str = "error"
    hint: str = ""

    def check_project(self, modules: list) -> Iterator[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------- baseline


def load_baseline(path: str) -> list[dict]:
    """Baseline entries: {rule, path, code, count?, justification}."""
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", [])
    for e in entries:
        e.setdefault("count", 1)
    return entries


def write_baseline(path: str, findings: Iterable[Finding]) -> int:
    """Grandfather ``findings`` into the baseline file; returns count.

    ``findings`` must come from a lint run WITHOUT the baseline applied
    (the CLI does this) so existing grandfathered violations re-match
    and survive the rewrite; entries whose violation is gone fall out.
    Hand-edited justifications are carried over by (rule, path, code)
    key; new entries start as TODO — the point of the baseline is to
    make every exemption visible, not to make it silent.
    """
    previous: dict[tuple, str] = {}
    if os.path.exists(path):
        previous = {
            (e["rule"], e["path"], e["code"]): e.get("justification", "")
            for e in load_baseline(path)
        }
    collapsed: dict[tuple, int] = {}
    for f in findings:
        key = (f.rule, f.path, f.code)
        collapsed[key] = collapsed.get(key, 0) + 1
    entries = [
        {
            "rule": rule,
            "path": relpath,
            "code": code,
            "count": count,
            "justification": previous.get(
                (rule, relpath, code), "TODO: justify or fix"
            )
            or "TODO: justify or fix",
        }
        for (rule, relpath, code), count in sorted(collapsed.items())
    ]
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2)
        f.write("\n")
    return len(entries)


def _apply_baseline(findings: list[Finding], entries: list[dict]) -> None:
    budget = {
        (e["rule"], e["path"], e["code"]): int(e.get("count", 1)) for e in entries
    }
    for f in findings:
        if f.suppressed_by is not None:
            continue
        key = (f.rule, f.path, f.code)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            f.suppressed_by = "baseline"


# ------------------------------------------------------------------ runner


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # unsuppressed — what should fail CI
    suppressed: list[Finding]  # pragma'd or baselined, for --json audits
    files: int

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def to_json(self) -> str:
        return json.dumps(
            {
                "files": self.files,
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": [f.to_dict() for f in self.suppressed],
            },
            indent=2,
        )


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__"
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)
        else:
            yield p


def _load_module(path: str, root: str):
    """Parse one file ONCE: ``(ModuleInfo, None)`` or ``(None, SAV001)``."""
    relpath = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    relpath = relpath.replace(os.sep, "/")
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        return ModuleInfo(path, relpath, source), None
    except SyntaxError as e:
        return None, Finding(
            rule="SAV001",
            severity="error",
            path=relpath,
            line=e.lineno or 1,
            col=e.offset or 0,
            message=f"file does not parse: {e.msg}",
            hint="fix the syntax error; savlint checks every file it is pointed at",
            code="",
            end_line=e.lineno or 1,
        )


def _fill_defaults(f: Finding, rule, module: ModuleInfo) -> Finding:
    f.path = module.relpath
    f.severity = rule.severity
    f.hint = f.hint or rule.hint
    if not f.code:
        f.code = module.function_source_line(f.line)
    if not f.end_line:
        f.end_line = f.line
    return f


def _check_modules(modules: list, rules: list) -> dict:
    """relpath → findings for per-file AND project rules, unsuppressed.

    Every rule runs against the SAME parsed ``ModuleInfo`` objects (one
    parse + one ``ast.walk`` per file, shared); project rules see the
    whole list at once and anchor each finding in its owning module so
    that module's pragmas apply to it.
    """
    from sav_tpu.analysis.rules import check_pragma_hygiene

    by_rel = {m.relpath: m for m in modules}
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    findings: dict[str, list[Finding]] = {m.relpath: [] for m in modules}
    for module in modules:
        for rule in file_rules:
            for f in rule.check(module):
                findings[module.relpath].append(
                    _fill_defaults(f, rule, module)
                )
        for f in check_pragma_hygiene(module):
            f.path = module.relpath
            findings[module.relpath].append(f)
    for rule in project_rules:
        for f in rule.check_project(modules):
            owner = by_rel.get(f.path)
            if owner is None:  # a rule anchored outside the linted set
                continue
            findings[owner.relpath].append(_fill_defaults(f, rule, owner))
    return findings


def lint_file(
    path: str,
    root: Optional[str] = None,
    rules: Optional[list] = None,
) -> list[Finding]:
    """All findings for one file, pragma suppression already marked.

    Project rules run with this file as the entire "project" — exactly
    what the single-file fixtures under tests/analysis_fixtures/ need.
    """
    from sav_tpu.analysis.rules import ALL_RULES

    rules = ALL_RULES if rules is None else rules
    root = root if root is not None else os.getcwd()
    module, err = _load_module(path, root)
    if err is not None:
        return [err]
    findings = _check_modules([module], rules)[module.relpath]
    _apply_pragmas(findings, module)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def _apply_pragmas(findings: list[Finding], module: ModuleInfo) -> None:
    file_pragmas = [p for p in module.pragmas if p.scope == "file"]
    line_pragmas = [p for p in module.pragmas if p.scope == "line"]
    for f in findings:
        if f.rule == "SAV100":
            continue  # pragma hygiene findings cannot pragma themselves away
        for p in file_pragmas:
            if f.rule in p.rules:
                f.suppressed_by = "pragma"
                break
        if f.suppressed_by:
            continue
        for p in line_pragmas:
            # A pragma suppresses a finding anywhere on the flagged
            # statement (multi-line calls report at the expression start
            # but may carry the pragma on any of their lines).
            if f.line <= p.line <= max(f.end_line, f.line) and f.rule in p.rules:
                f.suppressed_by = "pragma"
                break


def lint_paths(
    paths: Iterable[str],
    *,
    root: Optional[str] = None,
    select: Optional[Iterable[str]] = None,
    ignore: Optional[Iterable[str]] = None,
    baseline: Optional[str] = None,
) -> LintResult:
    """Lint files/directories; the importable equivalent of the CLI.

    ``select``/``ignore`` filter by rule id. ``baseline`` is a path to a
    baseline JSON (see :func:`load_baseline`); matched findings move to
    ``suppressed``. A missing baseline file is treated as empty here
    (library callers lint fresh trees); the CLI rejects an explicitly
    named baseline that does not exist.
    """
    from sav_tpu.analysis.rules import ALL_RULES

    select = {r.upper() for r in select} if select else None
    ignore = {r.upper() for r in ignore} if ignore else set()
    rules = [
        r
        for r in ALL_RULES
        if (select is None or r.id in select) and r.id not in ignore
    ]
    root = root if root is not None else os.getcwd()
    all_findings: list[Finding] = []
    modules: list[ModuleInfo] = []
    files = 0
    for path in iter_python_files(paths):
        files += 1
        module, err = _load_module(path, root)
        if err is not None:
            all_findings.append(err)
            continue
        modules.append(module)
    per_module = _check_modules(modules, rules)
    for module in modules:
        found = per_module[module.relpath]
        _apply_pragmas(found, module)
        found.sort(key=lambda f: (f.line, f.col, f.rule))
        all_findings.extend(found)
    if select is not None:
        all_findings = [
            f for f in all_findings if f.rule in select or f.rule == "SAV001"
        ]
    if ignore:
        all_findings = [f for f in all_findings if f.rule not in ignore]
    if baseline is not None and os.path.exists(baseline):
        _apply_baseline(all_findings, load_baseline(baseline))
    return LintResult(
        findings=[f for f in all_findings if f.suppressed_by is None],
        suppressed=[f for f in all_findings if f.suppressed_by is not None],
        files=files,
    )


def repo_root() -> str:
    """The repo checkout root (two levels above this package)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)
