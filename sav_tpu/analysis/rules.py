"""savlint rules: the TPU/JAX failure modes worth failing CI over.

Every rule carries an ID (stable — pragmas and the baseline key on it),
a severity, a one-line fix-it hint, and a docstring that is the
catalogue entry rendered into docs/static_analysis.md. The common theme:
each rule encodes a discipline the runtime already depends on (PR 1's
retrace counter, PR 2's feeder threading contract) but that nothing
enforced statically — so a future edit could silently regress a
multi-hour TPU run. Rules are heuristics, not proofs: the pragma and
baseline escapes exist precisely because ``evaluate()``'s one
end-of-pass ``device_get`` is correct and ``bench.py``'s sync-per-step
is the point. The bar for a rule is "a finding is worth a human reading
the line", not zero false positives.

Adding a rule (docs/static_analysis.md has the full recipe): subclass
:class:`Rule`, pick the next SAV1xx id, implement ``check(module)``
yielding :class:`~sav_tpu.analysis.lint.Finding`, append to
``ALL_RULES``, add a known-bad + known-clean fixture pair under
tests/analysis_fixtures/ and an entry in tests/test_savlint_rules.py.
"""

from __future__ import annotations

import ast
from typing import Iterator

from sav_tpu.analysis.lint import Finding, ModuleInfo, _bare_name

# Functions forming the training hot path: syncs here serialize the
# device pipeline every step (or every eval batch). The names are the
# trainer's public + jitted-impl surface; a repo-specific harness can
# mark extra ones hot with a matching name.
HOT_FUNCTIONS = frozenset(
    {
        "fit",
        "evaluate",
        "train_step",
        "eval_step",
        "train_step_placed",
        "train_many_steps",
        "_train_step_impl",
        "_train_many_impl",
        "_eval_step_impl",
    }
)

# jax.random derivation fns — NOT consumers; everything else under
# jax.random that takes a key as its first argument consumes it.
_KEY_DERIVERS = frozenset(
    {"split", "fold_in", "PRNGKey", "key", "key_data", "wrap_key_data", "clone"}
)

_TIME_CALLS = frozenset(
    {
        "time.time",
        "time.time_ns",
        "time.perf_counter",
        "time.perf_counter_ns",
        "time.monotonic",
        "time.process_time",
        "datetime.datetime.now",
        "datetime.datetime.utcnow",
    }
)

# Paths whose code runs under bf16 compute by default (the model zoo and
# the device-side ops): an f32-defaulting constructor here silently
# promotes every downstream op (docs/static_analysis.md, SAV108).
BF16_PATHS = ("sav_tpu/models/", "sav_tpu/ops/")


def _finding(rule, node, message, hint="", code=""):
    return Finding(
        rule=rule.id,
        severity=rule.severity,
        path="",
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
        hint=hint or rule.hint,
        code=code,
        end_line=getattr(node, "end_lineno", 0) or getattr(node, "lineno", 1),
    )


def _walk_excluding_nested(fn) -> Iterator[ast.AST]:
    """Nodes of ``fn``'s body, not descending into nested function/lambda.

    For thread- and hot-loop-scoped rules: a closure handed to a feeder
    runs on another thread (or inside a trace) and must be judged in its
    own scope, not its parent's.
    """
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class Rule:
    id: str = ""
    name: str = ""
    severity: str = "error"
    hint: str = ""

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------- SAV101


class HostSyncInHotLoop(Rule):
    """Host synchronization reachable from the training hot path.

    ``jax.device_get`` / ``block_until_ready`` / ``.item()`` /
    ``np.asarray`` inside ``fit()``, ``evaluate()``, or a jitted step
    implementation forces the dispatch pipeline to drain: the host
    blocks until the device catches up, the device then idles until the
    host dispatches again — the serialization PR 2's feeder exists to
    remove. ``float(x[...])``/``int(x.attr)`` are the same sync in
    disguise (implicit ``__float__`` on a device scalar). Legitimate
    sites exist — the per-log-window metrics sync, eval's single
    end-of-pass ``device_get``, the run-ahead cap — and each must be
    allowlisted with a pragma stating why, so the next reader knows the
    sync is priced in rather than accidental.
    """

    id = "SAV101"
    name = "host-sync-in-hot-loop"
    severity = "error"
    hint = (
        "keep values on device (stack/sum device-side, one device_get at a "
        "boundary); if this sync is intentional, pragma it with a "
        "justification"
    )

    SYNC_CALLS = {
        "jax.device_get": "jax.device_get blocks on the device",
        "jax.block_until_ready": "jax.block_until_ready drains the pipeline",
        "numpy.asarray": "np.asarray on a device array is a blocking D2H copy",
        "numpy.array": "np.array on a device array is a blocking D2H copy",
    }
    SYNC_METHODS = {
        "item": ".item() pulls a device scalar to host",
        "block_until_ready": ".block_until_ready() drains the pipeline",
    }

    def check(self, module):
        seen: set[int] = set()
        for fn in module.functions:
            if fn.name not in HOT_FUNCTIONS:
                continue
            for node in ast.walk(fn):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                resolved = module.resolve_call(node)
                where = f"in hot function {fn.name}()"
                if resolved in self.SYNC_CALLS:
                    yield _finding(
                        self, node, f"{self.SYNC_CALLS[resolved]} {where}"
                    )
                    continue
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.SYNC_METHODS
                    and not node.args
                    and not node.keywords
                ):
                    yield _finding(
                        self,
                        node,
                        f"{self.SYNC_METHODS[node.func.attr]} {where}",
                    )
                    continue
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in ("float", "int")
                    and len(node.args) == 1
                    and isinstance(node.args[0], (ast.Subscript, ast.Attribute))
                ):
                    yield _finding(
                        self,
                        node,
                        f"{node.func.id}() on a subscript/attribute {where} "
                        "implicitly syncs a device scalar to host",
                    )


# ---------------------------------------------------------------- SAV102


class JitWithoutDonation(Rule):
    """State-carrying step function jitted without buffer donation.

    A train step that takes the parameter/optimizer state and returns
    the next state must donate it (``donate_argnums``): without donation
    XLA keeps both generations of every buffer live across the update —
    on a memory-bound model that is the difference between fitting and
    OOM, and it costs an extra copy either way. Functions with ``eval``
    or ``init`` in their name are exempt: eval reuses the state across
    batches (donating it would be a use-after-donate crash) and init has
    nothing to donate.
    """

    id = "SAV102"
    name = "jit-without-donation"
    severity = "warning"
    hint = (
        "jax.jit(step, donate_argnums=(0,)) so the old state's buffers are "
        "reused in place"
    )

    STATE_PARAMS = frozenset({"state", "train_state", "opt_state"})

    def _first_param(self, fn):
        args = list(fn.args.posonlyargs) + list(fn.args.args)
        names = [a.arg for a in args]
        if names and names[0] == "self":
            names = names[1:]
        return names[0] if names else None

    def _exempt(self, name: str) -> bool:
        return "eval" in name or "init" in name

    def check(self, module):
        by_name = {}
        for fn in module.functions:
            by_name.setdefault(fn.name, fn)
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            if module.resolve_call(node) != "jax.jit" or not node.args:
                continue
            kwargs = {k.arg for k in node.keywords}
            if kwargs & {"donate_argnums", "donate_argnames"}:
                continue
            target = _bare_name(node.args[0])
            fn = by_name.get(target) if target else None
            if fn is None or self._exempt(fn.name):
                continue
            if self._first_param(fn) in self.STATE_PARAMS:
                yield _finding(
                    self,
                    node,
                    f"jax.jit({target}) carries state (first parameter "
                    f"{self._first_param(fn)!r}) but donates nothing — both "
                    "state generations stay live across every step",
                )
        # Decorator forms: bare @jax.jit cannot pass donate_argnums at
        # all; @partial(jax.jit, ...) can but may have forgotten to.
        for fn in module.jitted_defs:
            if self._exempt(fn.name) or (
                self._first_param(fn) not in self.STATE_PARAMS
            ):
                continue
            for dec in fn.decorator_list:
                if module.resolve(dec) == "jax.jit":
                    yield _finding(
                        self,
                        dec,
                        f"@jax.jit on {fn.name}() carries state but a bare "
                        "decorator cannot donate",
                        hint="use @partial(jax.jit, donate_argnums=(0,))",
                    )
                elif (
                    isinstance(dec, ast.Call)
                    and module.resolve_call(dec)
                    in ("functools.partial", "partial")
                    and dec.args
                    and module.resolve(dec.args[0]) == "jax.jit"
                    and not (
                        {k.arg for k in dec.keywords}
                        & {"donate_argnums", "donate_argnames"}
                    )
                ):
                    yield _finding(
                        self,
                        dec,
                        f"@partial(jax.jit) on {fn.name}() carries state "
                        "but donates nothing — both state generations stay "
                        "live across every step",
                    )


# ---------------------------------------------------------------- SAV103


class PrngKeyReuse(Rule):
    """The same PRNG key consumed by more than one random op.

    Two samplers fed the same key draw *correlated* values — dropout
    masks equal to stochastic-depth draws, augmentation mixes mirroring
    initialization noise. The failure is silent: shapes check out,
    training even converges, just worse. Keys must be split
    (``jax.random.split``) or derived (``fold_in``) per consumer;
    deriving does not count as consumption. The check is per-scope and
    flow-insensitive (an if/else consuming the same key once per branch
    is a false positive worth a pragma).
    """

    id = "SAV103"
    name = "prng-key-reuse"
    severity = "error"
    hint = (
        "split the key per consumer (k1, k2 = jax.random.split(key)) or "
        "derive with jax.random.fold_in(key, tag)"
    )

    def check(self, module):
        for fn in module.functions:
            events = []  # (line, col, kind, name, node)
            for node in _walk_excluding_nested(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                events.append(
                                    (leaf.lineno, leaf.col_offset, "assign",
                                     leaf.id, None)
                                )
                elif isinstance(node, ast.Call):
                    resolved = module.resolve_call(node)
                    if not resolved or not resolved.startswith("jax.random."):
                        continue
                    leaf_fn = resolved.rsplit(".", 1)[1]
                    if leaf_fn in _KEY_DERIVERS:
                        continue
                    if node.args and isinstance(node.args[0], ast.Name):
                        events.append(
                            (node.lineno, node.col_offset, "consume",
                             node.args[0].id, node)
                        )
            events.sort(key=lambda e: (e[0], e[1]))
            consumed: dict[str, int] = {}
            for line, _col, kind, name, node in events:
                if kind == "assign":
                    consumed.pop(name, None)
                else:
                    first = consumed.get(name)
                    if first is None:
                        consumed[name] = line
                    else:
                        yield _finding(
                            self,
                            node,
                            f"key {name!r} already consumed at line {first} "
                            f"in {fn.name}() and is consumed again here — "
                            "the two draws are correlated",
                        )


# ---------------------------------------------------------------- SAV104


class PythonScalarArgRetrace(Rule):
    """A loop-varying Python scalar passed straight into a jitted call.

    ``step(state, i)`` inside ``for i in range(n)`` hurts either way the
    scalar is treated: marked static, jit compiles one program per
    distinct value — ``n`` retraces, each minutes on the relay; left
    dynamic, the scalar is implicitly uploaded host→device on every
    single call (the transfer sanitizer flags exactly this at runtime).
    Loop counters belong on device (fold them into the carried state,
    like ``state.step``) or in the data, never in the jitted call's
    Python arguments.
    """

    id = "SAV104"
    name = "python-scalar-arg-retrace"
    severity = "error"
    hint = (
        "carry the counter in device state (state.step), pass it as a "
        "jnp array, or mark the parameter static on purpose"
    )

    def _int_loop_vars(self, loop: ast.For):
        """Loop targets that are Python ints: range() binds every target,
        enumerate() binds the first element of a tuple target."""
        if not isinstance(loop.iter, ast.Call):
            return set()
        if not isinstance(loop.iter.func, ast.Name):
            return set()
        fn = loop.iter.func.id
        if fn == "range":
            return {
                n.id for n in ast.walk(loop.target) if isinstance(n, ast.Name)
            }
        if fn == "enumerate" and isinstance(loop.target, ast.Tuple):
            first = loop.target.elts[0]
            if isinstance(first, ast.Name):
                return {first.id}
        return set()

    def check(self, module):
        if not module.jitted_names:
            return
        for loop in module.nodes:
            if not isinstance(loop, ast.For):
                continue
            loop_vars = self._int_loop_vars(loop)
            if not loop_vars:
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                callee = _bare_name(node.func)
                if callee not in module.jitted_names:
                    continue
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    bad = (
                        isinstance(arg, ast.Name) and arg.id in loop_vars
                    ) or (
                        isinstance(arg, ast.BinOp)
                        and any(
                            isinstance(n, ast.Name) and n.id in loop_vars
                            for n in ast.walk(arg)
                        )
                    )
                    if bad:
                        yield _finding(
                            self,
                            node,
                            f"jitted {callee}() receives the Python loop "
                            "counter as an argument — a retrace per value "
                            "if static, an implicit host→device upload "
                            "every call if not",
                        )
                        break


# ---------------------------------------------------------------- SAV105


class TimeInJit(Rule):
    """Wall-clock calls inside jit-traced code.

    ``time.time()`` in a jitted function runs **once, at trace time**:
    the value is baked into the compiled program as a constant, so the
    "timestamp" never advances and any timing math built on it is
    silently wrong (and differs between a cached and a fresh compile).
    Timing belongs on the host, around the dispatch — the span tracer
    and goodput ledger (PR 1) exist for exactly this.
    """

    id = "SAV105"
    name = "time-in-jit"
    severity = "error"
    hint = (
        "time on the host around the jitted call (obs.spans / "
        "obs.goodput), never inside the trace"
    )

    def check(self, module):
        seen: set[int] = set()
        for fn in module.jitted_defs:
            for node in ast.walk(fn):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                seen.add(id(node))
                resolved = module.resolve_call(node)
                if resolved in _TIME_CALLS:
                    yield _finding(
                        self,
                        node,
                        f"{resolved}() inside jitted {fn.name}() is evaluated "
                        "once at trace time and frozen into the program",
                    )


# ---------------------------------------------------------------- SAV106


class InlineDevicePutInFit(Rule):
    """Blocking device placement on the training thread's hot loop.

    With the async feeder on (the default since PR 2), every sharded
    ``device_put`` belongs to the feeder's background thread; a
    ``device_put``/``shard_batch`` call in ``fit()`` or ``evaluate()``
    re-serializes host→device transfer into the critical path and
    quietly undoes the overlap the feeder bought. This rule is the
    static home of the invariant tests/test_feeder.py used to assert by
    instrumenting threads; the serial fallback path
    (``async_feed=False``) is the one sanctioned exception and carries
    the pragma. Closures are exempt — a ``place`` closure handed to the
    feeder *runs on the feeder thread*.
    """

    id = "SAV106"
    name = "inline-device-put-in-fit"
    severity = "error"
    hint = (
        "route placement through the DeviceFeeder (async_feed) so the "
        "transfer overlaps device compute; see docs/input_pipeline.md"
    )

    PLACE_CALLS = {"jax.device_put", "jax.make_array_from_process_local_data"}
    PLACE_METHODS = {"shard_batch"}

    def check(self, module):
        for fn in module.functions:
            if fn.name not in ("fit", "evaluate"):
                continue
            for node in _walk_excluding_nested(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = module.resolve_call(node)
                callee = _bare_name(node.func)
                if resolved in self.PLACE_CALLS or callee in self.PLACE_METHODS:
                    yield _finding(
                        self,
                        node,
                        f"inline device placement ({callee}) on the training "
                        f"thread in {fn.name}() — transfer serializes into "
                        "the hot loop instead of overlapping via the feeder",
                    )


# ---------------------------------------------------------------- SAV107


class UnlockedThreadSharedState(Rule):
    """Cross-thread attribute writes without a lock.

    A class that starts a ``threading.Thread`` on one of its own methods
    (the feeder/watchdog pattern) shares ``self`` between threads; an
    attribute the worker method writes *and* another method also writes
    is a data race unless every write holds a lock. Single-writer
    telemetry counters (worker writes, others only read) are fine and
    not flagged; ``__init__`` writes happen before the thread starts and
    are likewise exempt.
    """

    id = "SAV107"
    name = "unlocked-thread-shared-state"
    severity = "warning"
    hint = (
        "guard multi-writer attributes with one threading.Lock (with "
        "self._lock: ...), or restructure so only one thread writes"
    )

    def _lockish(self, node, lock_attrs) -> bool:
        if isinstance(node, ast.Attribute):
            return node.attr in lock_attrs or "lock" in node.attr.lower()
        if isinstance(node, ast.Name):
            return "lock" in node.id.lower()
        return False

    def _method_writes(self, method, lock_attrs):
        """(attr, node, protected) for every self.attr assignment."""
        out = []

        def visit(node, protected):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, ast.With):
                held = protected or any(
                    self._lockish(item.context_expr, lock_attrs)
                    for item in node.items
                )
                for child in ast.iter_child_nodes(node):
                    visit(child, held)
                return
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    out.append((t.attr, node, protected))
            for child in ast.iter_child_nodes(node):
                visit(child, protected)

        for stmt in method.body:
            visit(stmt, False)
        return out

    def check(self, module):
        for cls in module.nodes:
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = [
                n
                for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            workers = set()
            lock_attrs = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Call):
                    if module.resolve_call(node) == "threading.Thread":
                        for k in node.keywords:
                            if (
                                k.arg == "target"
                                and isinstance(k.value, ast.Attribute)
                                and isinstance(k.value.value, ast.Name)
                                and k.value.value.id == "self"
                            ):
                                workers.add(k.value.attr)
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    resolved = module.resolve_call(node.value)
                    if resolved in (
                        "threading.Lock",
                        "threading.RLock",
                        "threading.Condition",
                        "threading.Semaphore",
                    ):
                        for t in node.targets:
                            if (
                                isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                            ):
                                lock_attrs.add(t.attr)
            if not workers:
                continue
            writes = {
                m.name: self._method_writes(m, lock_attrs) for m in methods
            }
            writers_of: dict[str, set] = {}
            for name, ws in writes.items():
                if name == "__init__":
                    continue
                for attr, _node, _prot in ws:
                    writers_of.setdefault(attr, set()).add(name)
            for attr, method_names in writers_of.items():
                if len(method_names) < 2 or not (method_names & workers):
                    continue
                for name in method_names:
                    for wattr, node, protected in writes[name]:
                        if wattr != attr or protected:
                            continue
                        yield _finding(
                            self,
                            node,
                            f"self.{attr} is written by "
                            f"{sorted(method_names)} while "
                            f"{sorted(method_names & workers)} runs on its "
                            "own thread — unlocked multi-writer state",
                        )


# ---------------------------------------------------------------- SAV108


class F32LiteralPromotion(Rule):
    """dtype-less float array constructor in a bf16 compute path.

    ``jnp.zeros(shape)`` defaults to float32; under bf16 compute that
    constant promotes every op it touches back to f32 — doubling the HBM
    traffic the bf16 path existed to halve, invisibly (results stay
    correct, the step just gets slower; PERF.md §6 measured the
    [B,H,L,L] case at −15% step time). Scoped to the model/ops trees
    where compute dtype is a parameter; int-valued ``arange`` is exempt.
    """

    id = "SAV108"
    name = "f32-literal-promotion"
    severity = "warning"
    hint = (
        "pass the computation's dtype explicitly "
        "(jnp.zeros(shape, dtype=x.dtype) or the module's self.dtype)"
    )

    # constructor → index of the positional dtype parameter
    CTORS = {
        "jax.numpy.zeros": 1,
        "jax.numpy.ones": 1,
        "jax.numpy.empty": 1,
        "jax.numpy.full": 2,
    }

    def check(self, module):
        if not module.relpath.startswith(BF16_PATHS):
            return
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            resolved = module.resolve_call(node)
            if resolved in self.CTORS:
                if any(k.arg == "dtype" for k in node.keywords):
                    continue
                if len(node.args) > self.CTORS[resolved]:
                    continue  # positional dtype
                yield _finding(
                    self,
                    node,
                    f"{resolved.rsplit('.', 1)[1]}() without dtype defaults "
                    "to float32 and promotes the surrounding bf16 compute",
                )
            elif resolved == "jax.numpy.linspace":
                if not any(k.arg == "dtype" for k in node.keywords):
                    yield _finding(
                        self,
                        node,
                        "linspace() without dtype defaults to float32 and "
                        "promotes the surrounding bf16 compute",
                    )
            elif resolved == "jax.numpy.arange":
                has_float = any(
                    isinstance(a, ast.Constant) and isinstance(a.value, float)
                    for a in node.args
                )
                if has_float and not any(
                    k.arg == "dtype" for k in node.keywords
                ) and len(node.args) < 4:
                    yield _finding(
                        self,
                        node,
                        "arange() over floats without dtype defaults to "
                        "float32 and promotes the surrounding bf16 compute",
                    )


# ---------------------------------------------------------------- SAV109


class JitInLoop(Rule):
    """``jax.jit`` called inside a loop body.

    ``jax.jit`` keys its compile cache on the *function object*: wrapping
    a fresh lambda/closure each iteration means a cache miss — trace and
    compile — every time around the loop. Hoist the jit outside the loop
    (or module scope) and call the one wrapped function repeatedly.
    """

    id = "SAV109"
    name = "jit-in-loop"
    severity = "warning"
    hint = "hoist the jax.jit(...) wrapping out of the loop; jit once, call many"

    def check(self, module):
        def visit(node, in_loop):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                in_loop = False
            elif isinstance(node, (ast.For, ast.While)):
                in_loop = True
            elif (
                in_loop
                and isinstance(node, ast.Call)
                and module.resolve_call(node) == "jax.jit"
            ):
                yield _finding(
                    self,
                    node,
                    "jax.jit inside a loop wraps a fresh function object "
                    "per iteration — a compile-cache miss every time",
                )
            for child in ast.iter_child_nodes(node):
                yield from visit(child, in_loop)

        yield from visit(module.tree, False)


# ---------------------------------------------------------------- SAV110


class AdhocSeedDerivation(Rule):
    """Arithmetic on seeds instead of ``fold_in`` on a key.

    ``PRNGKey(seed + 1)`` manufactures a sibling stream by poking the
    seed — nothing stops ``seed + 1`` from colliding with another run's
    ``seed``, and the derivation is invisible to anyone auditing key
    lineage. ``jax.random.fold_in(run_key, tag)`` derives a
    statistically independent stream from the run key with an explicit,
    greppable tag (trainer.py's fit() key is the in-repo example).
    """

    id = "SAV110"
    name = "adhoc-seed-derivation"
    severity = "warning"
    hint = (
        "derive from the run key: jax.random.fold_in("
        "jax.random.PRNGKey(seed), tag)"
    )

    def check(self, module):
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            if module.resolve_call(node) != "jax.random.PRNGKey":
                continue
            if node.args and isinstance(node.args[0], ast.BinOp):
                yield _finding(
                    self,
                    node,
                    "PRNGKey over seed arithmetic — derive sibling streams "
                    "with fold_in on the run key, not by perturbing the seed",
                )


# ---------------------------------------------------------------- SAV111


def _metric_rooted(node) -> bool:
    """True when the expression is rooted at a metrics-named value."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and "metric" in node.id.lower()


def _metrics_sync_findings(rule, module, fn, *, where: str, coda: str):
    """Sync detection shared by the recorder (SAV111) and fleet (SAV112)
    hot-path rules: explicit sync calls/methods, and ``float()``/
    ``int()`` pulling a metrics-named value (bare or rooted) to host
    through ``__float__``. One definition so a new sync API or a
    heuristic fix lands in both rules at once."""
    for node in _walk_excluding_nested(fn):
        if not isinstance(node, ast.Call):
            continue
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int")
            and len(node.args) == 1
        ):
            arg = node.args[0]
            if isinstance(arg, ast.Name) and "metric" in arg.id.lower():
                yield _finding(
                    rule,
                    node,
                    f"{node.func.id}() on step metrics in {where} "
                    f"{fn.name}() implicitly syncs a device scalar to "
                    "host",
                )
                continue
            if (
                isinstance(arg, (ast.Subscript, ast.Attribute))
                and _metric_rooted(arg)
            ):
                yield _finding(
                    rule,
                    node,
                    f"{node.func.id}() on a metrics subscript/attribute "
                    f"in {where} {fn.name}() implicitly syncs a device "
                    "scalar to host",
                )
                continue
        resolved = module.resolve_call(node)
        if resolved in HostSyncInHotLoop.SYNC_CALLS:
            yield _finding(
                rule,
                node,
                f"{HostSyncInHotLoop.SYNC_CALLS[resolved]} in {where} "
                f"{fn.name}() — {coda}",
            )
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in HostSyncInHotLoop.SYNC_METHODS
            and not node.args
            and not node.keywords
        ):
            yield _finding(
                rule,
                node,
                f"{HostSyncInHotLoop.SYNC_METHODS[node.func.attr]} in "
                f"{where} {fn.name}() — {coda}",
            )


class RecorderHotLoopSync(Rule):
    """Host sync on step metrics inside the recorded hot loop.

    The flight recorder's steady-state contract (sav_tpu/obs/recorder.py,
    docs/incident_replay.md) is that recording adds **no per-step device
    syncs**: the per-step path (``observe_batch``/``on_step``) is host
    bookkeeping only, and detection (``note_metrics``) runs on metrics
    the trainer *already* ``device_get``'d at its log boundary. Two ways
    an edit silently breaks that: a sync call slipped into one of the
    recorder's per-step functions (they are outside SAV101's
    fit/evaluate scope, so SAV111 owns them), or a ``float(metrics)`` /
    ``int(metric_dict)`` on a bare metrics-named value in the hot loop —
    a device scalar pulled to host through ``__float__``, invisible to
    SAV101's subscript/attribute heuristic. Sanctioned sync points carry
    the usual justification pragma.
    """

    id = "SAV111"
    name = "recorder-hot-loop-sync"
    severity = "error"
    hint = (
        "keep the recorder's per-step path host-only (detection rides the "
        "trainer's existing log-boundary device_get); if this sync is the "
        "sanctioned periodic snapshot, pragma it with a justification"
    )

    # The recorder's per-step surface: judged like the trainer's hot loop,
    # but by this rule (SAV101's HOT_FUNCTIONS stays fit/evaluate/steps).
    RECORDER_FUNCTIONS = frozenset(
        {"observe_batch", "on_step", "note_metrics", "wrap_place"}
    )

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.RECORDER_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="recorder hot path",
                    coda="recording must not add per-step syncs",
                )
            elif fn.name in HOT_FUNCTIONS:
                # In fit/evaluate only the implicit-__float__ sync on a
                # BARE metrics name is this rule's beat (SAV101's
                # subscript/attribute heuristic cannot see it); the
                # rest of the hot-loop sync catalogue is SAV101's.
                for node in _walk_excluding_nested(fn):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in ("float", "int")
                        and len(node.args) == 1
                        and isinstance(node.args[0], ast.Name)
                        and "metric" in node.args[0].id.lower()
                    ):
                        yield _finding(
                            self,
                            node,
                            f"{node.func.id}() on step metrics in "
                            f"{fn.name}() implicitly syncs a device "
                            "scalar to host",
                        )


# ---------------------------------------------------------------- SAV112


class FleetHotPathSync(Rule):
    """Host sync in the fleet-telemetry / anomaly-profiler hot path.

    The fleet layer's steady-state contract (sav_tpu/obs/fleet.py,
    sav_tpu/obs/autoprof.py, docs/fleet.md) mirrors the flight
    recorder's (SAV111): a heartbeat is one appended JSON line built
    from values that are *already* host-side at the trainer's log
    boundary — the goodput ledger's wall-clock aggregates and the
    metrics dict fit() synced anyway — and the profiler's arm/disarm
    path is pure host bookkeeping. A ``device_get`` /
    ``block_until_ready`` / ``.item()`` slipped into ``beat()`` /
    ``fleet_event()`` / ``note_window()`` / ``request()``, or a
    ``float(metrics...)`` pulling a device scalar through
    ``__float__``, would turn every logging window into a pipeline
    drain across the whole fleet. These functions sit outside SAV101's
    fit/evaluate scope (and outside SAV111's recorder set), so SAV112
    owns them.
    """

    id = "SAV112"
    name = "fleet-hot-path-sync"
    severity = "error"
    hint = (
        "keep the fleet heartbeat/autoprof path host-only (heartbeats "
        "carry values the trainer already synced at its log boundary); "
        "if a sync here is truly intentional, pragma it with a "
        "justification"
    )

    # The fleet layer's per-beat surface. Deliberately DISJOINT from
    # SAV111's RECORDER_FUNCTIONS — overlapping scopes would double-
    # report the same call. GoodputLedger.note_window shares a name and
    # the same obligation (host math only), so the rule covers it too.
    FLEET_FUNCTIONS = frozenset(
        {"beat", "fleet_event", "note_window", "request"}
    )

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.FLEET_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="fleet hot path",
                    coda="heartbeating must not add device syncs",
                )


# ---------------------------------------------------------------- SAV113


class ProfilerInHotPath(Rule):
    """``jax.profiler`` / memory-forensics calls in the training hot path.

    The profiling contract (docs/profiling.md) is that capture happens
    through the *armed windows* — the edge-synced static window
    (``TrainConfig.profile_dir``), autoprof's bounded anomaly captures,
    the OOM incident path — never ad hoc inside the hot loop. A stray
    ``start_trace``/``stop_trace`` serializes dispatch and bloats the
    trace ring on every step; ``save_device_memory_profile`` /
    ``live_arrays`` walk every live buffer; ``dump_memory_incident``
    writes a forensics bundle. All are incident/window machinery, and in
    ``fit()``/``evaluate()``/the step impls they are a steady-state tax
    that the telemetry guards (<1-2% overhead contracts) cannot see
    statically. The sanctioned sites — the static window's edges, the
    OOM dump in fit's finally — carry justification pragmas.
    """

    id = "SAV113"
    name = "profiler-in-hot-path"
    severity = "error"
    hint = (
        "capture through the armed windows (TrainConfig.profile_dir, "
        "autoprof's anomaly captures) or the incident path; a sanctioned "
        "window-edge/incident call carries a justification pragma"
    )

    PROFILER_CALLS = {
        "jax.profiler.start_trace": "jax.profiler.start_trace",
        "jax.profiler.stop_trace": "jax.profiler.stop_trace",
        "jax.profiler.trace": "jax.profiler.trace window",
        "jax.profiler.save_device_memory_profile":
            "device-memory pprof dump",
        "jax.profiler.device_memory_profile": "device-memory profile",
        "jax.live_arrays": "live-buffer walk",
        "sav_tpu.utils.profiler.start_trace": "profiler.start_trace",
        "sav_tpu.utils.profiler.stop_trace": "profiler.stop_trace",
        "sav_tpu.utils.profiler.trace": "profiler trace window",
        "sav_tpu.obs.memdump.dump_memory_incident":
            "memory-forensics dump",
        "sav_tpu.obs.memdump.live_buffer_ranking": "live-buffer ranking",
        "sav_tpu.obs.memdump.live_bytes_total": "live-buffer walk",
        "sav_tpu.obs.memdump.save_device_memory_profile":
            "device-memory pprof dump",
    }

    def check(self, module):
        for fn in module.functions:
            if fn.name not in HOT_FUNCTIONS:
                continue
            for node in _walk_excluding_nested(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = module.resolve_call(node)
                if resolved in self.PROFILER_CALLS:
                    yield _finding(
                        self,
                        node,
                        f"{self.PROFILER_CALLS[resolved]} in {fn.name}() "
                        "— profiling/forensics belong to the armed "
                        "windows or the incident path, not the hot loop",
                    )


# ---------------------------------------------------------------- SAV114


class BareExitInLibrary(Rule):
    """``sys.exit`` / ``os._exit`` / ``raise SystemExit`` in library code.

    The elasticity layer (docs/elasticity.md) depends on a strict
    exit-code contract: 0 ok, 2 usage, 3 backend-unreachable, 4 hang —
    and on every abnormal exit flowing through the paths that finalize
    the run manifest, drain in-flight async checkpoint saves, and dump
    incident bundles. A bare exit buried in ``sav_tpu/`` breaks both at
    once: ``sys.exit`` raises ``SystemExit`` from an arbitrary depth
    (callers' except-Exception blocks don't see it; an unexpected code
    confuses supervisors into misclassifying the restart reason), and
    ``os._exit`` skips every finally/atexit — the crash telemetry the
    whole obs stack exists to write. Library code raises exceptions;
    only the CLIs (train.py, bench.py, tools/) own process exit. The two
    sanctioned library sites — the hang watchdog's ``os._exit`` (a
    wedged main thread cannot be unwound) and the backend probe's
    ``SystemExit(3)`` (the documented abort contract) — carry
    justification pragmas, and ``os._exit`` *references* are findings
    too (handing the capability around is how it escapes audit).
    """

    id = "SAV114"
    name = "bare-exit-in-library"
    severity = "error"
    hint = (
        "raise a typed exception and let the CLI own process exit; the "
        "watchdog/probe contracts are the only sanctioned library exits "
        "and carry justification pragmas"
    )

    EXIT_CALLS = {
        "sys.exit": "sys.exit() raises SystemExit from library depth",
        "os._exit": "os._exit() skips every finally/atexit "
                    "(manifest finalize, checkpoint drain, incident dumps)",
    }
    LIBRARY_PREFIX = "sav_tpu/"

    def check(self, module):
        if not module.relpath.startswith(self.LIBRARY_PREFIX):
            return  # CLIs and tools legitimately own process exit
        consumed_funcs = set()
        for node in module.nodes:
            if isinstance(node, ast.Call):
                resolved = module.resolve_call(node)
                if resolved in self.EXIT_CALLS:
                    consumed_funcs.add(id(node.func))
                    yield _finding(
                        self, node,
                        f"{self.EXIT_CALLS[resolved]} — library code must "
                        "raise, not exit",
                    )
            elif isinstance(node, ast.Raise):
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) and isinstance(
                    exc.func, ast.Name
                ):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name == "SystemExit":
                    yield _finding(
                        self, node,
                        "raise SystemExit in library code — callers' "
                        "except-Exception blocks never see it; raise a "
                        "typed error and let the CLI exit",
                    )
        for node in module.nodes:
            # Bare references (default args, callbacks): handing the
            # hard-exit capability around is how it escapes audit.
            if (
                isinstance(node, (ast.Attribute, ast.Name))
                and id(node) not in consumed_funcs
                and module.resolve(node) in self.EXIT_CALLS
            ):
                yield _finding(
                    self, node,
                    f"reference to {module.resolve(node)} in library code "
                    "— the exit capability itself needs a pragma'd "
                    "contract, not a pass-around",
                )


# ---------------------------------------------------------------- SAV115


class ServeHotLoopSync(Rule):
    """Host sync in the serving batcher's admission/drain path.

    The serving engine's steady-state contract (sav_tpu/serve/,
    docs/serving.md) mirrors the training hot loop's: request admission
    (``submit``/``submit_raw``), batch forming (``next_batch`` and the
    engine's ``_formed_batches`` drain iterator) and placement
    (``_place_formed``, which runs on the feeder thread so the
    device_put of batch N+1 overlaps batch N's execution) are host-only
    bookkeeping. The ONE device sync per shipped batch is the device
    loop's post-execution result fetch. A ``device_get`` /
    ``block_until_ready`` / ``.item()`` slipped into the drain — e.g. a
    per-request result read inside ``next_batch`` — would serialize
    every request behind a pipeline drain and void both the overlap and
    the p99 budget. These functions sit outside SAV101's fit/evaluate
    scope (and outside SAV111/SAV112's sets), so SAV115 owns them.
    """

    id = "SAV115"
    name = "serve-hot-loop-sync"
    severity = "error"
    hint = (
        "keep admission/drain/placement host-only; results sync ONCE per "
        "shipped batch in the device loop — if a sync here is truly "
        "intentional, pragma it with a justification"
    )

    # The serving hot path's surface. Disjoint from SAV101's
    # HOT_FUNCTIONS and SAV111/SAV112's sets (overlap would double-report).
    SERVE_FUNCTIONS = frozenset(
        {"submit", "submit_raw", "next_batch", "_formed_batches",
         "_place_formed"}
    )

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.SERVE_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="serve hot path",
                    coda="the batcher drain must not sync",
                )


# ---------------------------------------------------------------- SAV116


class ServeTelemetryHotPathSync(Rule):
    """Host sync in the serve-telemetry span/window/heartbeat path.

    The serve telemetry layer (sav_tpu/serve/telemetry.py,
    docs/serving.md) rides INSIDE the paths SAV115 keeps sync-free: span
    stamps fire in the batcher's admission/drain and the engine's device
    loop, window observation runs on every completed batch, and the
    heartbeat thread snapshots windows that those paths feed. The
    contract mirrors the recorder's (SAV111) and fleet's (SAV112):
    every value a stamp/window/heartbeat touches is already host-side —
    monotonic clock reads, the latency floats the device loop computed
    after its one sanctioned sync. A ``device_get`` /
    ``block_until_ready`` / ``.item()`` slipped into ``stamp()`` /
    ``begin_trace()`` / ``observe_window()`` / ``observe_completed()``
    / ``observe_shed()`` / ``serve_beat()``, or a ``float(metrics...)``
    pulling a device scalar through ``__float__``, would serialize the
    batcher drain or the device loop behind a pipeline drain and void
    the p99 the telemetry exists to report. These functions sit outside
    SAV101's fit/evaluate scope and outside SAV111/SAV112/SAV115's
    sets, so SAV116 owns them.
    """

    id = "SAV116"
    name = "serve-telemetry-hot-path-sync"
    severity = "error"
    hint = (
        "keep span stamps / window observation / heartbeats host-only "
        "(the device loop's ONE post-execution fetch already synced "
        "every value telemetry needs); if a sync here is truly "
        "intentional, pragma it with a justification"
    )

    # The serve-telemetry hot surface. Deliberately DISJOINT from
    # SAV101's HOT_FUNCTIONS, SAV111's recorder set ("observe_batch" —
    # which also covers LatencyLedger.observe_batch), SAV112's fleet set
    # ("beat"/"note_window"/"request") and SAV115's serve set (overlap
    # would double-report the same call).
    TELEMETRY_FUNCTIONS = frozenset(
        {"stamp", "begin_trace", "observe_window", "observe_completed",
         "observe_shed", "serve_beat"}
    )

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.TELEMETRY_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="serve telemetry hot path",
                    coda="span/window/heartbeat telemetry must not sync",
                )


# ---------------------------------------------------------------- SAV118


class RouterHotPathSync(Rule):
    """Host sync in the fleet router's admit/route/drain path.

    The fleet router (sav_tpu/serve/router.py, docs/serving.md "Fleet")
    is the one component EVERY request in the fleet passes through: its
    admission projection, replica choice, completion bookkeeping, and
    view refresh run on the submit path or the dispatch workers, and
    every value they touch is host-side by construction — parsed
    heartbeat JSON, wall clocks, the router's own counters (the module
    is stdlib-only; jax is structurally unimportable from it). A
    ``device_get`` / ``block_until_ready`` / ``.item()`` slipped into
    ``admit()`` / ``route()`` / ``note_result()`` / ``_refresh_views()``
    / ``drain()`` / ``resume()``, or a ``float(metrics...)`` pulling a
    device scalar through ``__float__``, would serialize every request
    in the FLEET behind one pipeline drain — the whole-fleet version of
    the failure SAV115 guards one replica against. These functions sit
    outside SAV101's fit/evaluate scope and outside
    SAV111/SAV112/SAV115/SAV116's sets, so SAV118 owns them.
    """

    id = "SAV118"
    name = "router-hot-path-sync"
    severity = "error"
    hint = (
        "keep the router's admission/routing/drain path host-only (it "
        "routes on parsed heartbeat lines and its own counters — no "
        "device value belongs in reach); if a sync here is truly "
        "intentional, pragma it with a justification"
    )

    # The router's hot surface. Deliberately DISJOINT from SAV101's
    # HOT_FUNCTIONS and the SAV111/SAV112/SAV115/SAV116 sets (overlap
    # would double-report the same call).
    ROUTER_FUNCTIONS = frozenset(
        {"admit", "route", "note_result", "_refresh_views", "drain",
         "resume"}
    )

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.ROUTER_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="router hot path",
                    coda="routing must not sync the whole fleet",
                )


class RouterTraceHotPathSync(Rule):
    """Host sync in the fleet router's TRACING surface (ISSUE 16).

    The distributed-tracing layer grew the router new per-request hot
    functions: ``_dispatch()`` (the worker loop that stamps
    route_selected/connect/sent/reply/completed and the terminal
    shed/failed spans), ``_route_with_waits()`` (the candidate-wait
    table every route decision records), ``_observe_completion()`` (the
    span-ring/window fold that runs once per terminal request), and
    ``router_beat()`` (the kind=router heartbeat snapshot). Every value
    they touch is host-side by construction — monotonic clock stamps,
    parsed heartbeat JSON, the router's own counters — and the whole
    point of the ≤100µs per-request stamp budget is that OBSERVING a
    request must not slow it: a ``device_get`` / ``block_until_ready``
    / ``.item()`` / device-``float()`` in any of these would serialize
    every request in the fleet behind a pipeline drain, turning the
    telemetry into the regression it exists to catch. Deliberately
    DISJOINT from SAV118's set (admit/route/note_result/_refresh_views/
    drain/resume) — same module, different surface, so a finding names
    the layer that actually regressed.
    """

    id = "SAV119"
    name = "router-trace-hot-path-sync"
    severity = "error"
    hint = (
        "keep the router's tracing surface host-only (stamps are "
        "monotonic clock reads; the span ring and windows hold plain "
        "floats — no device value belongs in reach); if a sync here "
        "is truly intentional, pragma it with a justification"
    )

    # The router's per-request trace surface. Deliberately DISJOINT
    # from SAV101's HOT_FUNCTIONS and the SAV111/SAV112/SAV115/SAV116/
    # SAV118 sets (overlap would double-report the same call).
    TRACE_FUNCTIONS = frozenset(
        {"_dispatch", "_route_with_waits", "_observe_completion",
         "router_beat"}
    )

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.TRACE_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="router trace hot path",
                    coda="observing a request must not slow it",
                )


# ---------------------------------------------------------------- SAV117


class AdhocPartitionSpec(Rule):
    """``PartitionSpec``/``NamedSharding`` constructed outside the layout
    module.

    :class:`sav_tpu.parallel.layout.SpecLayout` is the single source of
    truth for every param/activation spec in the repo (ISSUE 13): the
    trainer, the serve engine, and the tools place tensors through the
    layout's derived shardings (``BoundLayout.param_shardings`` /
    ``batch_sharding``) or the :mod:`sav_tpu.parallel.mesh` helpers. An
    inline ``P(...)`` or ``NamedSharding(...)`` anywhere else forks that
    source of truth — the spec it states is invisible to the layout's
    golden snapshots, to ``tools/mesh_tune.py``'s search space, and to
    the ``notes.layout`` provenance stamp, so a layout change silently
    stops covering it. Scoped to everything OUTSIDE ``sav_tpu/parallel/``
    (the layout subsystem and the collective ops that implement it are
    where specs legitimately originate).
    """

    id = "SAV117"
    name = "adhoc-partition-spec"
    severity = "warning"
    hint = (
        "derive the sharding from the layout (BoundLayout.param_shardings"
        "/batch_sharding) or the sav_tpu.parallel.mesh helpers "
        "(batch_sharding/batch_sharding_at/replicated) instead of "
        "constructing PartitionSpec/NamedSharding inline"
    )

    LAYOUT_PATHS = ("sav_tpu/parallel/",)
    CTORS = {
        "jax.sharding.PartitionSpec": "PartitionSpec",
        "jax.sharding.NamedSharding": "NamedSharding",
    }

    def check(self, module):
        if module.relpath.startswith(self.LAYOUT_PATHS):
            return
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            resolved = module.resolve_call(node)
            if resolved in self.CTORS:
                yield _finding(
                    self,
                    node,
                    f"ad-hoc {self.CTORS[resolved]}() outside "
                    "sav_tpu/parallel/ forks the SpecLayout source of "
                    "truth",
                )


# ---------------------------------------------------------------- SAV120


class UnscaledInt8Cast(Rule):
    """Raw int8 cast outside the quantization module.

    ``sav_tpu/ops/quant.py`` is the single source of int8 truth (ISSUE
    17): every int8 tensor in the repo is born next to a per-channel
    scale (``quantize_channelwise`` / ``quantize_stochastic``) so that
    ``q * scale ≈ a`` always holds and the int32-accumulating dot can
    dequantize on exit. A bare ``x.astype(jnp.int8)`` or
    ``jnp.asarray(x, jnp.int8)`` anywhere else in the model/op/serve
    stack produces an int8 tensor with NO scale: values outside
    [-128, 127] wrap silently, fractional values truncate, and the
    result still *type-checks* into every quantized dot — the numeric
    corruption only surfaces as an accuracy drift long after the cast.
    Scoped to ``sav_tpu/ops|models|serve`` (the layers quantized
    tensors flow through); ``quant.py`` itself is exempt — scaled casts
    are its whole job.
    """

    id = "SAV120"
    name = "unscaled-int8-cast"
    severity = "error"
    hint = (
        "go through sav_tpu.ops.quant (quantize_channelwise / "
        "quantize_stochastic / quantize_params) so the int8 tensor "
        "carries its per-channel scale; if an unscaled cast is truly "
        "intentional, pragma it with a justification"
    )

    SCOPE = ("sav_tpu/ops/", "sav_tpu/models/", "sav_tpu/serve/")
    EXEMPT = ("sav_tpu/ops/quant.py",)
    INT8_DTYPES = frozenset({"jax.numpy.int8", "numpy.int8"})
    ARRAY_CTORS = frozenset(
        {
            "jax.numpy.asarray", "jax.numpy.array", "jax.numpy.full",
            "jax.numpy.zeros", "jax.numpy.ones", "numpy.asarray",
            "numpy.array",
        }
    )

    def _is_int8(self, module, node) -> bool:
        if isinstance(node, ast.Constant) and node.value == "int8":
            return True
        return module.resolve(node) in self.INT8_DTYPES

    def check(self, module):
        if (
            not module.relpath.startswith(self.SCOPE)
            or module.relpath in self.EXEMPT
        ):
            return
        for node in module.nodes:
            if not isinstance(node, ast.Call):
                continue
            dtype_nodes = [
                kw.value for kw in node.keywords if kw.arg == "dtype"
            ]
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
            ):
                dtype_nodes += node.args[:1]
                what = ".astype(int8)"
            elif module.resolve_call(node) in self.ARRAY_CTORS:
                # asarray/array take dtype positionally second; the
                # zeros/ones/full family keyword-only in this repo's
                # idiom (positional shapes) — the dtype kwarg covers it.
                dtype_nodes += node.args[1:2]
                what = f"{node.func.attr}(..., int8)"
            else:
                continue
            if any(self._is_int8(module, d) for d in dtype_nodes):
                yield _finding(
                    self,
                    node,
                    f"unscaled int8 cast ({what}) outside "
                    "sav_tpu/ops/quant.py — an int8 tensor with no "
                    "per-channel scale wraps/truncates silently",
                )


# ---------------------------------------------------------------- SAV125


def _attr_chain(node) -> list:
    """Lowercased name parts along an attribute chain, root first:
    ``self.alerts.observe`` -> ``["self", "alerts", "observe"]``."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr.lower())
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id.lower())
    parts.reverse()
    return parts


class AlertEvalInHotPath(Rule):
    """Alert evaluation / rollup writes inside request hot paths.

    The fleet metrics pipeline runs at heartbeat cadence by design:
    ``serve_beat()`` evaluates the alert rules once per beat, the
    router's heartbeat thread (``_hb_loop`` -> ``_roll_tick``) advances
    the rollup ladder once per interval, and the bench parent flushes
    once post-run — so the pipeline's cost is O(rules + new bytes) per
    *beat*, never per request. Calling ``AlertEngine.observe()`` /
    ``AlertRule.evaluate()`` or ``Roller.roll_once()/flush()`` from the
    batcher's submit path, the per-batch telemetry stamps, or the
    router's admission/dispatch surface would put rule evaluation, JSON
    encoding, and file appends on the request latency path — the
    observability regressing the p99 it exists to guard. The scope
    deliberately overlaps the SAV115/SAV116/SAV118/SAV119 function sets
    (same hot paths) but reports DIFFERENT calls (pipeline writes, not
    device syncs), so nothing double-reports.
    """

    id = "SAV125"
    name = "alert-eval-in-hot-path"
    severity = "error"
    hint = (
        "alert rules and rollups belong at heartbeat cadence: evaluate "
        "in serve_beat()/the router heartbeat thread (or post-run), "
        "never in submit/dispatch/per-batch stamp paths; if a hot-path "
        "evaluation is truly intentional, pragma it with a "
        "justification"
    )

    # The request hot paths: the batcher's submit/forming surface, the
    # per-batch telemetry stamps, and the router's admission/dispatch
    # functions. serve_beat/_hb_loop/_roll_tick/router_beat are the
    # sanctioned cadenced homes and are deliberately NOT in scope.
    FUNCTIONS = frozenset({
        # batcher (SAV115's set)
        "submit", "submit_raw", "next_batch", "_formed_batches",
        "_place_formed",
        # per-batch telemetry stamps (SAV116's set, minus serve_beat)
        "stamp", "begin_trace", "observe_window", "observe_completed",
        "observe_shed",
        # router request surface (SAV118 + SAV119's sets, minus
        # router_beat)
        "admit", "route", "note_result", "_refresh_views",
        "_dispatch", "_route_with_waits", "_observe_completion",
    })

    _ALERT_METHODS = frozenset({"observe", "evaluate"})
    _ROLL_METHODS = frozenset({"roll_once", "roll", "flush"})

    def check(self, module):
        for fn in module.functions:
            if fn.name not in self.FUNCTIONS:
                continue
            for node in _walk_excluding_nested(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = module.resolve_call(node) or ""
                if resolved.startswith(
                    ("sav_tpu.obs.alerts.", "sav_tpu.obs.rollup.")
                ):
                    yield _finding(
                        self,
                        node,
                        f"{resolved}() in request hot path {fn.name}() — "
                        "the metrics pipeline runs at heartbeat cadence, "
                        "not per request",
                    )
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                chain = _attr_chain(node.func)
                attr = node.func.attr
                if attr in self._ALERT_METHODS and any(
                    "alert" in part for part in chain[:-1]
                ):
                    yield _finding(
                        self,
                        node,
                        f"alert evaluation (.{attr}() on "
                        f"{'.'.join(chain[:-1])}) in request hot path "
                        f"{fn.name}() — rules evaluate once per beat in "
                        "serve_beat(), not per request",
                    )
                elif attr in self._ROLL_METHODS and any(
                    "roll" in part for part in chain[:-1]
                ):
                    yield _finding(
                        self,
                        node,
                        f"rollup write (.{attr}() on "
                        f"{'.'.join(chain[:-1])}) in request hot path "
                        f"{fn.name}() — the ladder advances on the "
                        "router's heartbeat thread, not per request",
                    )


# ---------------------------------------------------------------- SAV126


class QualityEvalInHotPath(Rule):
    """Prediction-quality evaluation inside request hot paths.

    The quality layer's contract (sav_tpu/serve/quality.py,
    sav_tpu/obs/quality.py, docs/quality.md) is that measuring
    prediction quality adds ZERO device syncs and zero per-request
    eval to the serving path: the output digests are traced INTO the
    serving executable and ride the device loop's one sanctioned
    result fetch; the windowed folds/drift gates run on values that
    are already host-side; probes run on their own low-cadence thread;
    shadow scoring runs on the router's dedicated shadow worker (the
    dispatch path only does an O(1) bounded queue put). Two ways an
    edit silently breaks that, and this rule owns both:

    1. A device sync slipped into the quality fold functions
       themselves (``observe_digests`` / ``score_shadow`` /
       ``quality_snapshot`` / ``observe_probe`` — outside every other
       sync rule's scope, so SAV126 audits them with the shared
       ``_metrics_sync_findings`` catalogue). ``observe_probe`` may
       block on request FUTURES by design — it never runs on the hot
       path — but a raw ``device_get``/``.item()`` there would still
       be a smell the catalogue rightly flags.
    2. A quality evaluation called FROM a request hot path — a
       ``sav_tpu.{obs,serve}.quality`` call, or a
       snapshot/score/digest method on a quality/probe/shadow/scorer
       object, inside the batcher submit path, the per-batch telemetry
       stamps, or the router admission/dispatch surface. Windowed
       churn/PSI folds and logit comparisons are O(window·classes)
       host math: cheap at heartbeat cadence, poison at request rate.
       The scope deliberately overlaps SAV125's hot-path set (same
       functions) but reports DIFFERENT calls (quality evals, not
       alert/rollup writes), so nothing double-reports. The engine's
       ``_complete`` is deliberately NOT in scope: its
       ``observe_digests`` fold on the already-fetched host digests is
       the sanctioned per-batch fold, like the latency ledger's.
    """

    id = "SAV126"
    name = "quality-eval-in-hot-path"
    severity = "error"
    hint = (
        "quality folds belong off the request path: digests ride the "
        "device loop's existing fetch, probes run on the probe thread, "
        "shadow scoring on the shadow worker, snapshots at heartbeat "
        "cadence (serve_beat/_quality_tick); if a hot-path evaluation "
        "is truly intentional, pragma it with a justification"
    )

    # The quality layer's own surface: audited host-only by the shared
    # sync catalogue. Disjoint from SAV111/SAV112/SAV115/SAV116/
    # SAV118/SAV119's sets — overlapping scopes would double-report.
    QUALITY_FUNCTIONS = frozenset({
        "observe_digests", "observe_probe", "score_shadow",
        "quality_snapshot",
    })

    # The request hot paths (SAV125's set — same paths, different
    # calls). _complete and the heartbeat/shadow-worker homes are
    # deliberately absent.
    FUNCTIONS = AlertEvalInHotPath.FUNCTIONS

    _EVAL_METHODS = frozenset({
        "observe_digests", "observe_probe", "score_shadow",
        "quality_snapshot", "snapshot", "score",
    })
    _QUALITY_ROOTS = ("quality", "probe", "shadow", "scorer")

    def check(self, module):
        for fn in module.functions:
            if fn.name in self.QUALITY_FUNCTIONS:
                yield from _metrics_sync_findings(
                    self, module, fn,
                    where="quality fold",
                    coda="digests ride the device loop's existing fetch",
                )
            if fn.name not in self.FUNCTIONS:
                continue
            for node in _walk_excluding_nested(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = module.resolve_call(node) or ""
                if resolved.startswith(
                    ("sav_tpu.obs.quality.", "sav_tpu.serve.quality.")
                ):
                    yield _finding(
                        self,
                        node,
                        f"{resolved}() in request hot path {fn.name}() — "
                        "quality evaluation runs at heartbeat/probe "
                        "cadence, not per request",
                    )
                    continue
                if not isinstance(node.func, ast.Attribute):
                    continue
                chain = _attr_chain(node.func)
                attr = node.func.attr
                if attr in self._EVAL_METHODS and any(
                    root in part
                    for part in chain[:-1]
                    for root in self._QUALITY_ROOTS
                ):
                    yield _finding(
                        self,
                        node,
                        f"quality evaluation (.{attr}() on "
                        f"{'.'.join(chain[:-1])}) in request hot path "
                        f"{fn.name}() — fold/score off the request path "
                        "(heartbeat, probe thread, or shadow worker)",
                    )


# ----------------------------------------------------------- SAV100 (meta)


class _PragmaHygiene(Rule):
    """Suppressions must name real rules and record a justification.

    A ``# savlint: disable=...`` with no ``-- reason`` (or an unknown
    rule id) defeats the audit trail the pragma system exists for; this
    meta-rule makes such pragmas findings themselves, and cannot be
    pragma'd away.
    """

    id = "SAV100"
    name = "pragma-hygiene"
    severity = "error"
    hint = "write '# savlint: disable=<RULE-ID> -- one-line justification'"


_PRAGMA_HYGIENE = _PragmaHygiene()


def check_pragma_hygiene(module: ModuleInfo) -> list[Finding]:
    findings = []
    known = {r.id for r in ALL_RULES} | {"SAV001"}
    for p in module.pragmas:
        unknown = sorted(p.rules - known)
        if unknown:
            findings.append(
                _finding(
                    _PRAGMA_HYGIENE,
                    type("L", (), {"lineno": p.line, "col_offset": 0,
                                   "end_lineno": p.line})(),
                    f"pragma names unknown rule(s) {', '.join(unknown)}",
                    code=module.function_source_line(p.line),
                )
            )
        if not p.justification:
            findings.append(
                _finding(
                    _PRAGMA_HYGIENE,
                    type("L", (), {"lineno": p.line, "col_offset": 0,
                                   "end_lineno": p.line})(),
                    "pragma has no justification — every suppression must "
                    "say why the violation is intentional",
                    code=module.function_source_line(p.line),
                )
            )
    return findings


ALL_RULES = [
    HostSyncInHotLoop(),
    JitWithoutDonation(),
    PrngKeyReuse(),
    PythonScalarArgRetrace(),
    TimeInJit(),
    InlineDevicePutInFit(),
    UnlockedThreadSharedState(),
    F32LiteralPromotion(),
    JitInLoop(),
    AdhocSeedDerivation(),
    RecorderHotLoopSync(),
    FleetHotPathSync(),
    ProfilerInHotPath(),
    BareExitInLibrary(),
    ServeHotLoopSync(),
    ServeTelemetryHotPathSync(),
    AdhocPartitionSpec(),
    RouterHotPathSync(),
    RouterTraceHotPathSync(),
    UnscaledInt8Cast(),
    AlertEvalInHotPath(),
    QualityEvalInHotPath(),
]

# The whole-program concurrency pass (SAV121–SAV124) lives in its own
# module — it is the one ProjectRule family and carries the shared
# lockset/lock-graph analysis tools/lockgraph.py also imports.
from sav_tpu.analysis.concurrency import CONCURRENCY_RULES  # noqa: E402

ALL_RULES = ALL_RULES + CONCURRENCY_RULES


def rule_catalog() -> list[dict]:
    """Machine-readable rule table (CLI --list-rules, docs generation)."""
    catalog = [
        {
            "id": r.id,
            "name": r.name,
            "severity": r.severity,
            "summary": (r.__doc__ or "").strip().splitlines()[0],
            "hint": r.hint,
        }
        for r in [_PRAGMA_HYGIENE] + ALL_RULES
    ]
    return catalog
