"""Static analysis + runtime sanitizers for TPU/JAX discipline (ISSUE 3).

Two complementary layers:

- **savlint** (:mod:`sav_tpu.analysis.lint`, :mod:`sav_tpu.analysis.rules`)
  — an AST pass over the repo with TPU-specific rules: host syncs in the
  hot loop, un-donated state-carrying jits, PRNG key reuse, retrace
  triggers, inline ``device_put`` in ``fit()``/``evaluate()``, unlocked
  cross-thread state, f32 literal promotion in bf16 paths. Run it via
  ``python tools/savlint.py`` or :func:`lint_paths`; tier-1
  (tests/test_savlint_self.py) runs it over the whole repo so new
  violations fail CI. Stdlib-only — importing this layer never imports
  jax, so the linter works in device-free contexts (pre-commit, CI
  frontends).
- **Runtime sanitizers** (:mod:`sav_tpu.analysis.sanitize`) — opt-in
  hard-fail guards for the invariants statics cannot see:
  ``jax.transfer_guard("disallow")`` armed around the steady-state hot
  loop, and a retrace sanitizer that aborts the run the moment the step
  function re-traces after warmup. Wired through
  ``TrainConfig.sanitize`` / ``train.py --sanitize``.

See docs/static_analysis.md for the rule catalogue, pragma/baseline
workflow, and how to add a rule.
"""

from sav_tpu.analysis.lint import (  # noqa: F401
    Finding,
    LintResult,
    lint_paths,
    load_baseline,
    write_baseline,
)
from sav_tpu.analysis.rules import ALL_RULES, rule_catalog  # noqa: F401
