"""Run telemetry — the observability layer for training runs.

Five signals, one design rule each:

- :mod:`sav_tpu.obs.diagnostics` — **in-jit** optimization diagnostics
  (grad/param/update norms, update-to-param ratio, per-layer-group grad
  norms, nonfinite counts) folded into the step-metrics dict so they ride
  the existing per-log ``device_get`` with zero extra transfers.
- :mod:`sav_tpu.obs.spans` — **host-side** span tracer emitting
  Chrome-trace-event JSON (Perfetto-loadable) around ``fit()``'s phases,
  so input-bound vs compute-bound is diagnosable without an XPlane capture.
- :mod:`sav_tpu.obs.goodput` — wall-time ledger splitting a run into
  compile / step / input-wait / eval / checkpoint / stall buckets, with
  per-window anomaly flags for the relay's >5x transient slowdowns.
- :mod:`sav_tpu.obs.memory` — HBM telemetry from ``device.memory_stats()``
  plus a retrace counter that makes silent recompilation visible.
- :mod:`sav_tpu.obs.watchdog` — heartbeat thread that turns a steady-state
  hang (the relay's documented failure mode, ``utils/backend_probe``) into
  a stack dump + labeled exit instead of a job that stalls forever.
- :mod:`sav_tpu.obs.costs` — FLOPs/bytes cost model (XLA cost-analysis
  with an analytic per-layer-group fallback) behind the ``goodput/mfu``
  and per-group attribution gauges.
- :mod:`sav_tpu.obs.manifest` — structured run manifests finalized with a
  machine-readable outcome on every exit path, plus the normalized
  run-record reading shared by the report/sentinel tools.
- :mod:`sav_tpu.obs.recorder` — flight recorder: bounded ring of host-side
  step context (batch hash/raw batches, rng recipe, metrics, periodic
  state snapshots) dumped as a replayable incident bundle on nonfinite
  metrics, loss spikes, hangs, or crashes (``tools/replay_step.py``).
- :mod:`sav_tpu.obs.fleet` — cross-process fleet telemetry: per-process
  heartbeat streams (``fleet/proc_<i>.jsonl``), the merged fleet manifest
  with step skew / straggler ranking / dead-host suspicion, and the
  backend-probe timeline in the same artifact layout
  (``tools/fleet_status.py``, docs/fleet.md).
- :mod:`sav_tpu.obs.autoprof` — anomaly-triggered profiling: a goodput
  stall anomaly, a robust step-time spike, or the watchdog's soft stage
  arms a bounded ``jax.profiler`` window, budgeted like the recorder's
  incidents and stamped into the run manifest.

Re-exports are lazy (PEP 562, same pattern as :mod:`sav_tpu.utils`):
:mod:`spans`, :mod:`goodput`, and :mod:`watchdog` are stdlib-only and must
stay importable without dragging ``jax`` into the process.
"""

from __future__ import annotations

from sav_tpu._lazy import install_lazy_exports

_EXPORTS = {
    "diagnostics_metrics": "sav_tpu.obs.diagnostics",
    "grad_group_norms": "sav_tpu.obs.diagnostics",
    "nonfinite_count": "sav_tpu.obs.diagnostics",
    "SpanTracer": "sav_tpu.obs.spans",
    "GoodputLedger": "sav_tpu.obs.goodput",
    "hbm_stats": "sav_tpu.obs.memory",
    "RetraceCounter": "sav_tpu.obs.memory",
    "HangWatchdog": "sav_tpu.obs.watchdog",
    "WATCHDOG_EXIT_CODE": "sav_tpu.obs.watchdog",
    "StepCost": "sav_tpu.obs.costs",
    "resolve_peak_flops": "sav_tpu.obs.costs",
    "train_step_cost": "sav_tpu.obs.costs",
    "FlightRecorder": "sav_tpu.obs.recorder",
    "HeartbeatWriter": "sav_tpu.obs.fleet",
    "aggregate_fleet": "sav_tpu.obs.fleet",
    "write_fleet_manifest": "sav_tpu.obs.fleet",
    "AutoProfiler": "sav_tpu.obs.autoprof",
    "RunManifest": "sav_tpu.obs.manifest",
    "RunRecord": "sav_tpu.obs.manifest",
    "classify_exception": "sav_tpu.obs.manifest",
    "load_run_history": "sav_tpu.obs.manifest",
    "normalize_run_record": "sav_tpu.obs.manifest",
}

__all__ = list(_EXPORTS)

__getattr__, __dir__ = install_lazy_exports(
    globals(),
    _EXPORTS,
    {"diagnostics", "spans", "goodput", "memory", "watchdog", "costs",
     "manifest", "recorder"},
)
