"""Memory forensics — make OOM a debuggable incident.

``oom`` has been a manifest *outcome* since PR 4, but an outcome with
zero forensics: the run died, the allocator said RESOURCE_EXHAUSTED, and
nothing recorded **what was resident**. This module is the memory twin
of the flight recorder's incident bundles:

- :class:`HbmWatermark` — the run's peak device-memory occupancy,
  observed at the trainer's existing log boundaries
  (``device.memory_stats()`` is a host-side PJRT counter read — no
  device sync) and stamped into the run manifest as a first-class
  field (``metrics.hbm_peak_bytes``) on every exit path, so OOM
  post-mortems and the regression sentinel see the watermark without
  the goodput file. CPU backends report no memory stats; the finalize
  pass falls back to one ``jax.live_arrays()`` walk (labeled
  ``live-arrays``) so the plumbing stays assertable in tier-1.
- :func:`live_buffer_ranking` — every live device buffer, classified
  against the training state (``params`` / ``opt_state`` /
  ``batch_stats`` by buffer identity; everything else is
  ``unattributed`` — activations, placed batches, donation leaks) and
  ranked by size. The classes sum against the cost model's per-group
  parameter-byte estimates (:func:`sav_tpu.obs.costs.param_group_bytes`),
  so "params grew" reads differently from "something unattributed is
  eating HBM".
- :func:`dump_memory_incident` — on any ``oom``-classified exception,
  write an incident bundle under the recorder's ``incidents/`` layout
  and budget discipline: ``memdump.json`` (snapshot + watermark +
  ranking + per-group estimates), plus a
  ``jax.profiler.save_device_memory_profile`` pprof when the backend
  supports one. Dumping is telemetry: every path is
  exception-contained, and a failed dump never outruns the OOM it is
  documenting.

Rendered by ``tools/run_report.py`` (incidents section) and cross-linked
from the manifest (``notes.memdump``). docs/profiling.md documents the
bundle layout.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

MEMDUMP_SCHEMA = 1

# Buffer classes in the ranking. 'unattributed' is the interesting one:
# live buffers that are not the training state — activations held by
# in-flight dispatches, placed batches, and (the classic leak) buffers
# kept alive by a stray host reference after donation.
CLASSES = ("params", "opt_state", "batch_stats", "unattributed")


class HbmWatermark:
    """Running peak of device bytes in use.

    ``observe()`` at log boundaries (host-side counter read, cheap, no
    sync); ``finalize()`` once in fit's finally — it backfills from a
    single ``jax.live_arrays()`` walk when the backend never reported
    memory stats (CPU), so the manifest field exists on every backend.
    """

    def __init__(self):
        self.peak_bytes = 0.0
        self.in_use_bytes = 0.0
        self.limit_bytes: Optional[float] = None
        self.source: Optional[str] = None
        self.samples = 0

    def observe(self, stats: Optional[dict] = None) -> None:
        """Fold one ``hbm_stats()`` sample in (callers that already hold
        the dict pass it; otherwise it is read here)."""
        if stats is None:
            from sav_tpu.obs.memory import hbm_stats

            try:
                stats = hbm_stats()
            except Exception:
                return
        if not stats:
            return
        self.samples += 1
        self.source = "device-stats"
        # hbm_stats() units differ per key: in_use/limit are SUMS over
        # local devices, peak is the MAX over devices — the OOM-relevant
        # number on a symmetric mesh. Never fold the summed in_use into
        # the per-device peak: on a 4-device host that would report 4x
        # the real per-device occupancy and drown the one device
        # transiently brushing its limit. Only when the backend reports
        # no peak counter at all does the sum stand in (degraded,
        # better than zero).
        self.in_use_bytes = float(stats.get("hbm_bytes_in_use", 0.0))
        per_device_peak = float(stats.get("hbm_peak_bytes", 0.0))
        self.peak_bytes = max(
            self.peak_bytes, per_device_peak or self.in_use_bytes
        )
        limit = stats.get("hbm_bytes_limit")
        if limit:
            self.limit_bytes = float(limit)

    def finalize(self) -> dict:
        """Final watermark record for the manifest. One more device-stats
        read (the peak may have moved since the last log boundary); when
        the backend never reported, one live-arrays walk stands in."""
        self.observe()
        if self.samples == 0:
            live = live_bytes_total()
            if live is not None:
                self.peak_bytes = max(self.peak_bytes, live)
                self.in_use_bytes = live
                self.source = "live-arrays"
        return self.as_dict()

    def as_dict(self) -> dict:
        return {
            "peak_bytes": self.peak_bytes,
            "in_use_bytes": self.in_use_bytes,
            "limit_bytes": self.limit_bytes,
            "source": self.source,
            "samples": self.samples,
        }


def live_bytes_total() -> Optional[float]:
    """Total bytes of all live jax arrays (host-side aval metadata —
    no device read); None when jax is unavailable or the walk fails."""
    try:
        import jax

        return float(
            sum(getattr(x, "nbytes", 0) or 0 for x in jax.live_arrays())
        )
    except Exception:
        return None


def _state_buffer_ids(state: Any) -> dict[int, tuple[str, str]]:
    """``id(buffer) -> (class, layer group)`` over a TrainState's trees.

    Identity, not equality: the ranking must attribute the *actual live
    buffers* — a donated-then-leaked copy of a param is exactly what
    must NOT read as 'params'.
    """
    import jax

    from sav_tpu.obs.diagnostics import _group_of

    out: dict[int, tuple[str, str]] = {}

    def fold(tree, cls, grouped: bool):
        if tree is None:
            return
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            if hasattr(leaf, "nbytes"):
                out[id(leaf)] = (cls, _group_of(path) if grouped else None)

    fold(getattr(state, "params", None), "params", True)
    # Opt-state paths mirror the params tree somewhere below wrapper
    # nodes (or not at all under the fused flat-buffer optimizer), so
    # the class is the honest granularity here.
    fold(getattr(state, "opt_state", None), "opt_state", False)
    fold(getattr(state, "batch_stats", None), "batch_stats", False)
    return out


def live_buffer_ranking(
    state: Any = None, *, limit: int = 20
) -> Optional[dict]:
    """Rank live device buffers by size, classified against ``state``.

    Aggregates by (class, shape, dtype) — an OOM dump with 200 identical
    activation buffers should read as one row with count 200. Returns
    None when jax is unavailable (never raises: this runs inside an OOM
    handler).
    """
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:
        return None
    known = _state_buffer_ids(state) if state is not None else {}
    rows: dict[tuple, dict] = {}
    class_bytes = {c: 0.0 for c in CLASSES}
    total = 0.0
    for x in arrays:
        nbytes = float(getattr(x, "nbytes", 0) or 0)
        total += nbytes
        cls, group = known.get(id(x), ("unattributed", None))
        class_bytes[cls] = class_bytes.get(cls, 0.0) + nbytes
        key = (cls, group, tuple(getattr(x, "shape", ())),
               str(getattr(x, "dtype", "?")))
        row = rows.get(key)
        if row is None:
            rows[key] = {
                "class": cls,
                "group": group,
                "shape": list(key[2]),
                "dtype": key[3],
                "bytes": nbytes,
                "count": 1,
            }
        else:
            row["bytes"] += nbytes
            row["count"] += 1
    ranking = sorted(rows.values(), key=lambda r: -r["bytes"])
    return {
        "total_bytes": total,
        "num_buffers": len(arrays),
        "class_bytes": class_bytes,
        "buffers": ranking[:limit],
        "truncated": max(0, len(ranking) - limit),
    }


def save_device_memory_profile(path: str) -> bool:
    """``jax.profiler.save_device_memory_profile`` → pprof, backend
    permitting; False (never an exception) otherwise."""
    try:
        import jax

        jax.profiler.save_device_memory_profile(path)
        return os.path.exists(path)
    except Exception:
        return False


def _existing_dumps(log_dir: str) -> list[str]:
    root = os.path.join(log_dir, "incidents")
    if not os.path.isdir(root):
        return []
    return sorted(
        d for d in os.listdir(root)
        if d.startswith("memdump_")
        and os.path.isdir(os.path.join(root, d))
    )


def dump_memory_incident(
    log_dir: str,
    *,
    trigger: str = "oom",
    step: Optional[int] = None,
    error: Optional[str] = None,
    state: Any = None,
    watermark: Optional[HbmWatermark] = None,
    cost=None,
    manifest=None,
    max_dumps: int = 2,
    limit: int = 20,
) -> Optional[str]:
    """Write one memory-forensics bundle under ``<log_dir>/incidents/``.

    Budgeted like the flight recorder's incidents (``max_dumps`` per log
    dir — an OOM loop under a supervisor restart must not fill the
    disk). Returns the bundle path, or None when the budget is spent or
    anything failed — this runs on the way out of an OOM and must never
    replace the real traceback with its own.
    """
    try:
        if len(_existing_dumps(log_dir)) >= max_dumps:
            return None
        bundle = os.path.join(
            log_dir, "incidents", f"memdump_{int(step or 0):08d}"
        )
        if os.path.isdir(bundle):
            bundle = f"{bundle}-{int(time.time())}"
            if os.path.isdir(bundle):
                return None
        os.makedirs(bundle, exist_ok=True)
        from sav_tpu.obs.memory import hbm_stats

        try:
            hbm = hbm_stats()
        except Exception:
            hbm = {}
        group_bytes = None
        if state is not None and getattr(state, "params", None) is not None:
            try:
                from sav_tpu.obs.costs import param_group_bytes

                group_bytes = param_group_bytes(state.params)
            except Exception:
                group_bytes = None
        pprof_path = os.path.join(bundle, "memory.pprof")
        doc = {
            "schema": MEMDUMP_SCHEMA,
            "trigger": trigger,
            "step": step,
            "error": error,
            "created_unix": round(time.time(), 3),
            "hbm": hbm,
            # finalize(), not as_dict(): the dump runs before fit's own
            # finally-stamp, and on CPU the live-arrays backfill is the
            # only nonzero watermark there is.
            "watermark": watermark.finalize() if watermark is not None
            else None,
            "live": live_buffer_ranking(state, limit=limit),
            # The cost model's shape-derived per-group parameter bytes:
            # the predicted side the live 'params' class is read against
            # (divergence = a param-shaped buffer the state no longer
            # owns, i.e. a donation leak).
            "param_group_bytes": group_bytes,
            "cost_model": {
                "flops_per_device": getattr(cost, "flops", None),
                "bytes_accessed": getattr(cost, "bytes_accessed", None),
                "source": getattr(cost, "source", None),
            } if cost is not None else None,
            "pprof": save_device_memory_profile(pprof_path),
        }
        tmp = os.path.join(bundle, "memdump.json.tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, default=str)
        os.replace(tmp, os.path.join(bundle, "memdump.json"))
    except Exception as e:
        import sys

        print(f"memdump: incident dump failed: {e!r}", file=sys.stderr)
        return None
    if manifest is not None:
        try:
            manifest.note("memdump", {
                "path": bundle,
                "trigger": trigger,
                "step": step,
            })
        except Exception:
            pass
    return bundle
