"""Declarative alert rules over heartbeat records (ISSUE 19).

PR 11 hardwired the fleet's only alert: the SLO two-window burn pair
inside :class:`~sav_tpu.serve.telemetry.SLOTracker`. This module
generalizes it into data: a rule is a named set of metric comparisons
against the heartbeat record (dotted paths into the beat — ``w.p99_ms``,
``slo.burn_fast``, ``queued``), a for-duration, a resolve hold, and a
severity, JSON-loadable so an operator arms a new alert without a
deploy::

    {"rules": [{"name": "p99-high", "metric": "w.p99_ms", "op": ">",
                "value": 250, "for_s": 10, "resolve_s": 10,
                "severity": "warn"}]}

The windowing discipline is the beats' own: every metric a rule reads
is already a *windowed* value (the live window's trailing ``w.*``
snapshot, the SLO burn windows), so a rule adds only the for-duration
hold on top — the Google-SRE shape (condition sustained for N seconds)
without re-deriving windows the telemetry already maintains.

State machine per rule (flap-suppressed, once-per-episode)::

    inactive -> pending (condition true)        no event
    pending  -> firing  (held for for_s)        ONE "firing" event
    pending  -> inactive (condition dropped)    no event
    firing   -> cooling (condition false)       no event
    cooling  -> firing  (condition returns      no event (same episode
                         within resolve_s)       — flap suppressed)
    cooling  -> resolved (held for resolve_s)   ONE "resolved" event

A missing or non-numeric metric evaluates the condition **false** —
exactly :class:`SLOTracker`'s semantics (``burning`` is False while a
burn window is still empty), which is what makes the built-in SLO rule
(:func:`slo_burn_rule`) bit-identical to the tracker on a replayed
stream (test-pinned parity gate).

Events append to ``fleet/alerts.jsonl`` (one JSON line per transition,
torn-tail-tolerant readers, same substrate discipline as the heartbeat
streams); active rule names are stamped into the emitting replica's
heartbeats and the episode summary into the serve manifest's
``notes.alerts``. Evaluation runs at heartbeat cadence only — savlint
SAV125 statically pins it out of the batcher/engine/router hot paths.

Stdlib-only (no jax, no numpy): rules must evaluate in the serve/fleet
plane and load on a laptop over rsynced logs.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

ALERTS_SCHEMA = 1

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def alerts_path(log_dir: str) -> str:
    return os.path.join(log_dir, "fleet", "alerts.jsonl")


def _lookup(record: dict, path: str):
    """Dotted-path read into a beat record (``w.p99_ms`` ->
    ``record["w"]["p99_ms"]``); None on any missing hop."""
    node = record
    for part in path.split("."):
        if not isinstance(node, dict):
            return None
        node = node.get(part)
    return node


class AlertRule:
    """One declarative rule: AND-composed conditions + hold durations.

    ``when`` is a list of ``(metric, op, value)`` conditions — ALL must
    hold (the SLO burn pair is the canonical two-condition rule). The
    JSON shorthand ``{"metric", "op", "value"}`` becomes a one-condition
    ``when``.
    """

    __slots__ = ("name", "severity", "for_s", "resolve_s", "when")

    def __init__(
        self,
        name: str,
        *,
        when: list,
        severity: str = "warn",
        for_s: float = 0.0,
        resolve_s: float = 0.0,
    ):
        if not name:
            raise ValueError("alert rule needs a name")
        if not when:
            raise ValueError(f"alert rule {name!r} has no conditions")
        conditions = []
        for metric, op, value in when:
            if op not in _OPS:
                raise ValueError(
                    f"alert rule {name!r}: unknown comparator {op!r} "
                    f"(have {sorted(_OPS)})"
                )
            conditions.append((str(metric), str(op), float(value)))
        self.name = str(name)
        self.severity = str(severity)
        self.for_s = float(for_s)
        self.resolve_s = float(resolve_s)
        self.when = tuple(conditions)

    @classmethod
    def from_dict(cls, doc: dict) -> "AlertRule":
        when = doc.get("when")
        if when is None and "metric" in doc:
            when = [{
                "metric": doc["metric"],
                "op": doc.get("op", ">"),
                "value": doc.get("value", 0.0),
            }]
        if not isinstance(when, list):
            raise ValueError(
                f"alert rule {doc.get('name')!r}: no conditions "
                "(want 'when' or metric/op/value shorthand)"
            )
        return cls(
            doc.get("name") or "",
            when=[
                (c.get("metric", ""), c.get("op", ">"),
                 c.get("value", 0.0))
                for c in when
            ],
            severity=doc.get("severity", "warn"),
            for_s=doc.get("for_s", 0.0),
            resolve_s=doc.get("resolve_s", 0.0),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "severity": self.severity,
            "for_s": self.for_s,
            "resolve_s": self.resolve_s,
            "when": [
                {"metric": m, "op": op, "value": v}
                for m, op, v in self.when
            ],
        }

    def evaluate(self, record: dict) -> bool:
        """True iff every condition holds on this record. Missing /
        non-numeric metrics are FALSE (SLOTracker's empty-window
        semantics — the parity gate depends on this)."""
        for metric, op, value in self.when:
            observed = _lookup(record, metric)
            if not isinstance(observed, (int, float)) or isinstance(
                observed, bool
            ):
                return False
            if not _OPS[op](float(observed), value):
                return False
        return True


def slo_burn_rule(
    burn_threshold: float = 2.0, *, severity: str = "page"
) -> AlertRule:
    """The PR-11 SLO fast/slow burn pair as ONE declarative rule —
    fires exactly when ``SLOTracker.state()["burning"]`` is True on the
    same beat (both windows non-empty and above threshold; for/resolve
    hold 0 because the tracker's own windows already debounce). The
    parity gate in tests/test_alerts.py replays a beat stream through
    both and pins bit-identical firing/resolved edges."""
    return AlertRule(
        "slo-burn",
        when=[
            ("slo.burn_fast", ">", float(burn_threshold)),
            ("slo.burn_slow", ">", float(burn_threshold)),
        ],
        severity=severity,
        for_s=0.0,
        resolve_s=0.0,
    )


def default_rules(slo_burn_threshold: float = 2.0) -> list:
    """The built-in rule set every armed replica carries: the SLO burn
    pair (the two PR-11 alerts, now data)."""
    return [slo_burn_rule(slo_burn_threshold)]


def quality_rules() -> list:
    """The prediction-quality rule set (ISSUE 20, docs/quality.md) —
    a SEPARATE set from :func:`default_rules` on purpose: the
    default-rules contract ("exactly the SLO rule") is pinned by
    tests/test_alerts.py, and quality rules arm alongside it, not
    inside it.

    The two integrity rules gate on CUMULATIVE MONOTONIC counters
    (``quality.probe_mismatch``, ``shadow.breach``) with ``for_s=0``
    and a long ``resolve_s``: a planted fault fires exactly one
    episode that resolves only at finalize — the exactly-once shape
    the straggler battery pins for latency alerts. The two drift
    rules (churn / entropy shift) gate on windowed statistics and
    debounce with for/resolve holds instead. Records without quality
    fields (training beats, pre-reference windows) evaluate False —
    missing metrics never fire."""
    return [
        AlertRule(
            "quality-churn",
            when=[("quality.churn", ">", 0.5)],
            severity="warn",
            for_s=10.0,
            resolve_s=30.0,
        ),
        AlertRule(
            "quality-entropy-shift",
            when=[("quality.entropy_shift", ">", 6.0)],
            severity="warn",
            for_s=10.0,
            resolve_s=30.0,
        ),
        AlertRule(
            "quality-probe-mismatch",
            when=[("quality.probe_mismatch", ">", 0.0)],
            severity="page",
            for_s=0.0,
            resolve_s=3600.0,
        ),
        AlertRule(
            "shadow-agreement",
            when=[("shadow.breach", ">", 0.0)],
            severity="page",
            for_s=0.0,
            resolve_s=3600.0,
        ),
    ]


def load_rules(source) -> list:
    """Rules from a JSON file path, a JSON string, or a parsed doc
    (``{"rules": [...]}`` or a bare list). Raises ValueError on
    malformed rules — arming a fleet with a typo'd rule set should fail
    loudly at startup, not silently never fire."""
    doc = source
    if isinstance(source, str):
        if os.path.exists(source):
            with open(source) as f:
                doc = json.load(f)
        else:
            doc = json.loads(source)
    if isinstance(doc, dict):
        doc = doc.get("rules")
    if not isinstance(doc, list):
        raise ValueError(
            "alert rules want {'rules': [...]} or a bare list"
        )
    return [AlertRule.from_dict(d) for d in doc]


class AlertEngine:
    """The firing/resolved state machine over a rule set.

    One engine per emitting process (each replica judges its OWN
    beats — per-replica alerts carry ``proc`` so a fleet view can
    attribute them). ``observe()`` is called once per heartbeat by the
    telemetry's cadenced beat path — never from a request path (savlint
    SAV125). Events append to ``fleet/alerts.jsonl``; a failed append
    drops the line (telemetry never takes serving down) but the state
    machine still advances.
    """

    def __init__(
        self,
        rules: list,
        *,
        log_dir: Optional[str] = None,
        proc: Optional[int] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names: {names}")
        self.log_dir = log_dir
        self.proc = proc
        self._clock = clock
        self._state = {
            r.name: {"status": "inactive", "since": None, "episodes": 0}
            for r in self.rules
        }
        self.emitted = 0
        self.dropped = 0

    # -------------------------------------------------------- evaluation

    def observe(self, record: dict, now: Optional[float] = None) -> list:
        """Advance every rule on one beat record; returns (and appends)
        the transition events this beat produced."""
        now = self._clock() if now is None else float(now)
        events = []
        for rule in self.rules:
            state = self._state[rule.name]
            cond = rule.evaluate(record)
            status = state["status"]
            if status == "inactive":
                if cond:
                    state["status"] = "pending"
                    state["since"] = now
                    status = "pending"
            if status == "pending":
                if not cond:
                    state["status"] = "inactive"
                    state["since"] = None
                elif now - state["since"] >= rule.for_s:
                    state["status"] = "firing"
                    state["episodes"] += 1
                    events.append(self._event("firing", rule, record, now))
            elif status == "firing":
                if not cond:
                    state["status"] = "cooling"
                    state["since"] = now
                    status = "cooling"
            if status == "cooling":
                if cond:
                    # Flap suppression: the episode survives a dip
                    # shorter than resolve_s — no new event.
                    state["status"] = "firing"
                elif now - state["since"] >= rule.resolve_s:
                    state["status"] = "inactive"
                    state["since"] = None
                    events.append(
                        self._event("resolved", rule, record, now)
                    )
        if events:
            self._append(events)
        return events

    def finalize(self, now: Optional[float] = None) -> list:
        """End of stream: resolve every firing/cooling episode (an
        episode cannot outlive its emitter — the final beat is the
        recovery edge). Idempotent."""
        now = self._clock() if now is None else float(now)
        events = []
        for rule in self.rules:
            state = self._state[rule.name]
            if state["status"] in ("firing", "cooling"):
                state["status"] = "inactive"
                state["since"] = None
                events.append(self._event("resolved", rule, {}, now))
            elif state["status"] == "pending":
                state["status"] = "inactive"
                state["since"] = None
        if events:
            self._append(events)
        return events

    def _event(
        self, edge: str, rule: AlertRule, record: dict, now: float
    ) -> dict:
        observed = {}
        for metric, _, _ in rule.when:
            value = _lookup(record, metric)
            if isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                observed[metric] = value
        event = {
            "v": ALERTS_SCHEMA,
            "kind": "alert",
            "event": edge,
            "rule": rule.name,
            "severity": rule.severity,
            "episode": self._state[rule.name]["episodes"],
            "t": round(now, 3),
        }
        if self.proc is not None:
            event["proc"] = self.proc
        if observed:
            event["observed"] = observed
        return event

    def _append(self, events: list) -> None:
        self.emitted += len(events)
        if self.log_dir is None:
            return
        path = alerts_path(self.log_dir)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            # One write() per line: concurrent replicas append to the
            # shared file, and O_APPEND keeps whole small lines intact
            # (the torn-tolerant reader absorbs the pathological case).
            with open(path, "a") as f:
                for event in events:
                    f.write(json.dumps(event) + "\n")
                f.flush()
        except OSError:
            self.dropped += len(events)

    # ----------------------------------------------------------- queries

    def active(self) -> list:
        """Names of currently-firing rules (cooling counts: the episode
        is still open), sorted — the heartbeat stamp."""
        return sorted(
            name for name, s in self._state.items()
            if s["status"] in ("firing", "cooling")
        )

    def state(self) -> dict:
        """The manifest ``notes.alerts`` snapshot."""
        return {
            "schema": ALERTS_SCHEMA,
            "rules": len(self.rules),
            "active": self.active(),
            "episodes": {
                name: s["episodes"]
                for name, s in self._state.items()
                if s["episodes"]
            },
            "emitted": self.emitted,
            "dropped": self.dropped,
        }


# ---------------------------------------------------------------- readers


def read_alerts(log_dir: str) -> list:
    """Every alert event in ``fleet/alerts.jsonl``, oldest first
    (torn/foreign lines skipped — same discipline as the heartbeat
    readers)."""
    out = []
    try:
        with open(alerts_path(log_dir), "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(doc, dict) and doc.get("kind") == "alert":
                    out.append(doc)
    except OSError:
        pass
    return out


def episodes(events: list) -> dict:
    """Fold an event list into per-rule episode accounting:
    ``{rule: {"fired": n, "resolved": n, "active": bool, "severity",
    "last_t"}}`` — the console's alert table and the bench line's
    episode assertions read this."""
    out: dict = {}
    for event in events:
        rule = event.get("rule")
        if not rule:
            continue
        entry = out.setdefault(rule, {
            "fired": 0, "resolved": 0, "active": False,
            "severity": event.get("severity"), "last_t": None,
        })
        edge = event.get("event")
        if edge == "firing":
            entry["fired"] += 1
            entry["active"] = True
        elif edge == "resolved":
            entry["resolved"] += 1
            entry["active"] = False
        entry["severity"] = event.get("severity", entry["severity"])
        entry["last_t"] = event.get("t", entry["last_t"])
    return out
