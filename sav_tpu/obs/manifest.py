"""Run manifests — one structured JSON record per run, finalized on every
exit path.

``BENCH_r05.json`` is the motivating failure: a backend-unreachable bench
recorded ``"rc": 3, "parsed": null`` plus a free-text stderr tail, so no
tool could tell "infra was down" from "the code regressed". The manifest
replaces that parse-a-text-tail status quo: ``train.py`` and ``bench.py``
write a :class:`RunManifest` at start (config, argv, environment
fingerprint) and finalize it with a machine-readable **outcome** on every
way out — success, exception, watchdog fire, backend-unreachable abort.

Outcome taxonomy (:data:`OUTCOMES`):

  ok                   — the run completed
  backend_unreachable  — the startup probe gave up (backend_probe exit 3)
  retrace              — killed by the retrace sanitizer (steady-state
                         recompile, sav_tpu.analysis.sanitize)
  hang                 — the hang watchdog fired (obs.watchdog exit 4)
  oom                  — device allocator exhaustion
  error                — any other exception
  running              — transient: the run is (or died too hard to say)

Design rules: stdlib-only (the backend-unreachable path must run without
jax — importing it is exactly what hangs); every write is atomic
(tmp + ``os.replace``) so a watchdog ``os._exit`` mid-write cannot tear
the file; ``finalize`` is first-wins idempotent and thread-safe, so the
watchdog thread and a crashing main thread cannot double-report; and a
failed manifest write never takes the run down (telemetry must not).

The module also owns run-record *reading*: :func:`normalize_run_record` /
:func:`load_run_history` fold the three shapes history comes in (driver
``BENCH_r*.json`` wrappers, raw bench JSON lines, manifests) into one
:class:`RunRecord` view that separates infra failures from measurements —
shared by ``tools/regression_sentinel.py`` and ``tools/run_report.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import platform as _platform
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Optional

OUTCOMES = (
    "ok", "backend_unreachable", "retrace", "hang", "oom", "error",
)
MANIFEST_SCHEMA = 1


def classify_exception(exc: BaseException) -> str:
    """Map an exception to a manifest outcome.

    Matches on type *names* (not imports) so this stays stdlib-only:
    ``RetraceSanitizerError`` → ``retrace``; allocator exhaustion
    (``RESOURCE_EXHAUSTED``, "out of memory", ``MemoryError``) → ``oom``;
    everything else → ``error``.
    """
    name = type(exc).__name__
    if name == "RetraceSanitizerError":
        return "retrace"
    text = f"{name}: {exc}".lower()
    if (
        "resource_exhausted" in text
        or "out of memory" in text
        or isinstance(exc, MemoryError)
    ):
        return "oom"
    return "error"


def _git_sha() -> Optional[str]:
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=2.0,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except Exception:
        return None
    sha = proc.stdout.strip()
    return sha if proc.returncode == 0 and sha else None


def environment_fingerprint() -> dict:
    """Host/toolchain fingerprint, safe to call before (and without) jax.

    Deliberately does NOT import jax and does NOT touch ``jax.devices()``
    even when jax is already imported — on a wedged relay that is the
    call that hangs, and the unreachable-backend path is exactly where
    the fingerprint must still work. Callers that hold live devices add
    backend facts via :meth:`RunManifest.note`.
    """
    env = {
        "python": sys.version.split()[0],
        "platform": _platform.platform(),
        "hostname": socket.gethostname(),
        "argv0": sys.argv[0] if sys.argv else None,
        "git_sha": _git_sha(),
        "jax_platforms": os.environ.get("JAX_PLATFORMS") or None,
        "accelerator_env": bool(os.environ.get("PALLAS_AXON_POOL_IPS")),
    }
    if "jax" in sys.modules:  # version only — never device init
        env["jax"] = getattr(sys.modules["jax"], "__version__", None)
    return env


class RunManifest:
    """Lifecycle: ``begin()`` writes an in-progress record; ``note()`` /
    ``set_metrics()`` accrete facts; ``finalize(outcome)`` stamps the one
    terminal outcome (first caller wins — later finalizes are ignored, so
    an exception handler racing the watchdog cannot overwrite ``hang``).
    """

    def __init__(
        self,
        path: str,
        *,
        kind: str,
        argv: Optional[list] = None,
        config: Optional[dict] = None,
        clock=time.time,
    ):
        self.path = path
        self._clock = clock
        self._lock = threading.Lock()
        self._enabled = True
        self._data: dict[str, Any] = {
            "schema": MANIFEST_SCHEMA,
            "kind": kind,
            "outcome": "running",
            "argv": list(argv) if argv is not None else None,
            "config": config,
            "env": environment_fingerprint(),
            "created_unix": round(float(clock()), 3),
            "finalized_unix": None,
            "exit_code": None,
            "error": None,
            "notes": {},
            "metrics": {},
        }

    # ------------------------------------------------------------ lifecycle

    @property
    def outcome(self) -> str:
        return self._data["outcome"]

    @property
    def finalized(self) -> bool:
        return self._data["outcome"] != "running"

    def begin(self) -> Optional[str]:
        """Write the in-progress record; returns the path (None if the
        write failed — telemetry never takes a run down)."""
        return self._write()

    def disable(self) -> None:
        """Stop writing (non-zero processes of a multi-host run share the
        log dir; only process 0 may own the manifest file)."""
        with self._lock:
            self._enabled = False

    def set_config(self, config: Optional[dict]) -> None:
        with self._lock:
            self._data["config"] = config
        self._write()

    def note(self, key: str, value: Any) -> None:
        """Record one machine-readable fact (replication fallback, cost
        model source, probe timings...). Last write per key wins."""
        with self._lock:
            self._data["notes"][key] = value
        self._write()

    def set_metrics(self, metrics: dict) -> None:
        """Merge flat scalar metrics (e.g. ``GoodputLedger.flat_metrics``:
        ``goodput/mfu``, ``goodput/flops/<comp>_frac``, ...)."""
        with self._lock:
            for k, v in (metrics or {}).items():
                self._data["metrics"][k] = v
        self._write()

    def finalize(
        self,
        outcome: str,
        *,
        error: Optional[str] = None,
        exit_code: Optional[int] = None,
        metrics: Optional[dict] = None,
        notes: Optional[dict] = None,
    ) -> bool:
        """Stamp the terminal outcome; True iff this call won the race."""
        if outcome not in OUTCOMES:
            raise ValueError(
                f"unknown outcome {outcome!r}; use one of {OUTCOMES}"
            )
        with self._lock:
            if self._data["outcome"] != "running":
                return False
            self._data["outcome"] = outcome
            self._data["error"] = error
            self._data["exit_code"] = exit_code
            self._data["finalized_unix"] = round(float(self._clock()), 3)
            for k, v in (metrics or {}).items():
                self._data["metrics"][k] = v
            for k, v in (notes or {}).items():
                self._data["notes"][k] = v
        self._write()
        return True

    def move_to(self, path: str) -> None:
        """Re-home the manifest (config resolution can change the log
        dir after the early, pre-probe record was written)."""
        with self._lock:
            old = self.path
            self.path = path
        self._write()
        if old != path:
            try:
                os.remove(old)
            except OSError:
                pass

    # ----------------------------------------------------------------- I/O

    def _write(self) -> Optional[str]:
        with self._lock:
            if not self._enabled:
                return None
            payload = json.dumps(self._data, indent=2, default=str)
            path = self.path
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, path)  # atomic: a crash mid-write cannot tear
            return path
        except OSError:
            return None

    @classmethod
    def load(cls, path: str) -> dict:
        with open(path) as f:
            return json.load(f)


# ---------------------------------------------------------- record reading


@dataclasses.dataclass
class RunRecord:
    """One history entry, normalized: infra failure or measurement."""

    label: str
    order: float
    outcome: str
    metrics: dict[str, float]  # throughput / mfu / input_wait_frac
    detail: str
    raw: dict

    @property
    def ok(self) -> bool:
        return self.outcome == "ok"


def _bench_line_metrics(parsed: dict) -> dict[str, float]:
    out: dict[str, float] = {}
    if isinstance(parsed.get("value"), (int, float)):
        out["throughput"] = float(parsed["value"])
    if isinstance(parsed.get("mfu"), (int, float)):
        out["mfu"] = float(parsed["mfu"])
    goodput = parsed.get("goodput") or {}
    frac = (goodput.get("fractions") or {}).get("input_wait")
    if isinstance(frac, (int, float)):
        out["input_wait_frac"] = float(frac)
    return out


def _manifest_metrics(metrics: dict) -> dict[str, float]:
    out: dict[str, float] = {}
    value = metrics.get("value")
    if isinstance(value, (int, float)):
        out["throughput"] = float(value)
    mfu = metrics.get("goodput/mfu", metrics.get("mfu"))
    if isinstance(mfu, (int, float)):
        out["mfu"] = float(mfu)
    wall = metrics.get("goodput/wall_s")
    wait = metrics.get("goodput/input_wait_s")
    if (
        isinstance(wall, (int, float))
        and isinstance(wait, (int, float))
        and wall > 0
    ):
        out["input_wait_frac"] = float(wait) / float(wall)
    return out


def normalize_run_record(
    obj: dict, *, label: str = "?", index: int = 0
) -> RunRecord:
    """Fold any of the three record shapes into a :class:`RunRecord`.

    Shapes: the driver's ``BENCH_r*.json`` wrapper (``rc``/``parsed``/
    ``tail``), a raw bench output line (``value``/``unit``), or a
    manifest (``schema``/``outcome``/``metrics``). Infra failures come
    back with a non-``ok`` outcome and empty-or-partial metrics — never
    an exception, so one bad record cannot crash a report over the rest.
    """
    order = float(index)
    if "rc" in obj and "parsed" in obj:  # driver wrapper
        if isinstance(obj.get("n"), (int, float)):
            order = float(obj["n"])
        rc, parsed = obj.get("rc"), obj.get("parsed")
        if rc == 0 and isinstance(parsed, dict):
            inner = normalize_run_record(parsed, label=label, index=index)
            return dataclasses.replace(inner, order=order, raw=obj)
        tail = (obj.get("tail") or "").lower()
        if isinstance(parsed, dict) and parsed.get("outcome") in OUTCOMES:
            outcome = parsed["outcome"]
        elif "backend unreachable" in tail or rc == 3:
            outcome = "backend_unreachable"
        elif rc == 4:
            outcome = "hang"
        else:
            outcome = "error"
        last = (obj.get("tail") or "").strip().splitlines()
        return RunRecord(
            label=label, order=order, outcome=outcome, metrics={},
            detail=f"rc={rc}" + (f": {last[-1][:100]}" if last else ""),
            raw=obj,
        )
    if obj.get("schema") == MANIFEST_SCHEMA and "outcome" in obj:  # manifest
        outcome = obj.get("outcome")
        outcome = outcome if outcome in OUTCOMES else "error"
        metrics = _manifest_metrics(obj.get("metrics") or {})
        return RunRecord(
            label=label, order=order, outcome=outcome, metrics=metrics,
            detail=obj.get("error") or f"{obj.get('kind', 'run')} manifest",
            raw=obj,
        )
    # Raw bench line.
    outcome = obj.get("outcome")
    if outcome not in OUTCOMES:
        outcome = "ok" if isinstance(obj.get("value"), (int, float)) else "error"
    metrics = _bench_line_metrics(obj) if outcome == "ok" else {}
    detail = (
        f"{obj.get('value')} {obj.get('unit', '')}".strip()
        if outcome == "ok" else obj.get("error") or outcome
    )
    return RunRecord(
        label=label, order=order, outcome=outcome, metrics=metrics,
        detail=detail, raw=obj,
    )


def load_run_history(paths: list) -> list[RunRecord]:
    """Load + normalize + order a list of record files.

    Raises ``OSError``/``ValueError`` for unreadable input (the sentinel
    maps those to its usage/IO exit code 2 — a torn file is an infra
    problem to surface, not a regression verdict).
    """
    records = []
    for i, path in enumerate(sorted(paths)):
        with open(path) as f:
            try:
                obj = json.load(f)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}: not valid JSON ({e})") from e
        if not isinstance(obj, dict):
            raise ValueError(f"{path}: expected a JSON object")
        records.append(
            normalize_run_record(
                obj, label=os.path.basename(path), index=i
            )
        )
    records.sort(key=lambda r: (r.order, r.label))
    return records
