"""Steady-state hang watchdog.

``utils.backend_probe`` guards *startup*: a down/wedged TPU relay hangs
in-process backend init, so the CLIs probe from a subprocess before
touching jax. This module extends that philosophy to *steady state*: once
training is running, the same relay failure mode (observed rounds 3-5 —
a dial-retry loop inside the plugin, a wedged chip grant) presents as a
step that never completes, usually with the host blocked inside
``device_get``. Without a watchdog that is a job silently holding its
slot forever; BENCH_r05.json's rc=3 came after 570 s of probing for
exactly this reason.

:class:`HangWatchdog` is a daemon heartbeat thread. The train loop calls
:meth:`beat` every iteration; if no beat arrives within ``deadline_s``
the watchdog dumps every Python thread's stack (so the blocked
``device_get``/``next(iterator)`` frame is in the log), the goodput
ledger summary if one was attached, and exits the process with
:data:`WATCHDOG_EXIT_CODE` — distinct from the backend probe's exit 3 so
wrapper scripts can tell "never started" from "hung mid-run".

Stdlib-only, and ``os._exit`` (not ``sys.exit``) by design: the main
thread is presumed wedged in a C call that never returns, so unwinding
it is not an option.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

# Exit-code contract: backend_probe aborts startup with 3; the watchdog
# aborts a hung steady-state run with 4. Wrapper scripts key on both.
WATCHDOG_EXIT_CODE = 4


def dump_all_stacks(stream=None) -> None:
    """Write every live Python thread's stack to ``stream`` (stderr)."""
    stream = stream if stream is not None else sys.stderr
    frames = sys._current_frames()
    threads = {t.ident: t for t in threading.enumerate()}
    for ident, frame in frames.items():
        thread = threads.get(ident)
        name = thread.name if thread is not None else f"thread-{ident}"
        print(f"--- stack of {name} (ident={ident}) ---", file=stream)
        for line in traceback.format_stack(frame):
            stream.write(line)
    stream.flush()


class HangWatchdog:
    """Fires when no :meth:`beat` arrives within ``deadline_s``.

    ``ledger``: optional :class:`~sav_tpu.obs.goodput.GoodputLedger`
    whose summary is dumped alongside the stacks (where the time went
    before the hang). ``manifest``: optional
    :class:`~sav_tpu.obs.manifest.RunManifest` finalized with
    ``outcome: "hang"`` *before* the process exits — the hang must be
    machine-visible in the run record, not only in a stderr dump
    (``os._exit`` skips every atexit/finally, so nothing downstream gets
    another chance). ``recorder``: optional
    :class:`~sav_tpu.obs.recorder.FlightRecorder` — its incident bundle
    (trigger ``hang``: the ring's last steps, kept batches, nearest state
    snapshot) is dumped before the manifest is finalized, and the bundle
    path rides the manifest's finalize notes, for the same reason: after
    ``os._exit`` nothing gets another chance. The dump runs on a side
    thread bounded by ``dump_timeout_s`` (default 30 s): the log dir's
    filesystem may be the hang's own cause, and the guaranteed-exit
    contract outranks telemetry. ``checkpointer``: optional
    :class:`~sav_tpu.train.checkpoint.Checkpointer` whose in-flight
    async save is drained (bounded the same way) before the exit —
    ``os._exit`` skips ``fit()``'s finally, and an abandoned save is
    wall time the next attempt re-pays (docs/elasticity.md).
    ``exit_fn``/``stream`` are
    injectable for tests — production uses ``os._exit`` so a wedged main
    thread cannot swallow the abort.

    **Two-stage escalation** (``soft_deadline_s``): an optional *soft*
    (warning) stage below the hard deadline. Crossing it dumps every
    thread's stack and invokes ``on_soft(silent_s)`` — the trainer wires
    that to a fleet-heartbeat event plus arming the anomaly profiler
    (sav_tpu.obs.fleet / sav_tpu.obs.autoprof, docs/fleet.md) — but the
    run *continues*: a slow eval or a transient relay stall recovers,
    and the evidence of where it was stuck is already on disk if it
    does not. The soft stage fires once per silent episode (re-armed by
    the next beat); the hard stage's exit-4 contract is unchanged.
    ``on_soft`` runs on a side thread bounded by ``dump_timeout_s`` and
    is exception-guarded — the log dir's filesystem may be the stall's
    own cause, and neither a failing nor a *blocking* callback may stop
    the hard stage from ever firing.
    """

    def __init__(
        self,
        deadline_s: float,
        *,
        ledger=None,
        manifest=None,
        recorder=None,
        checkpointer=None,
        tag: str = "watchdog",
        exit_code: int = WATCHDOG_EXIT_CODE,
        exit_fn: Optional[Callable[[int], None]] = None,
        stream=None,
        poll_s: Optional[float] = None,
        dump_timeout_s: float = 30.0,
        soft_deadline_s: Optional[float] = None,
        on_soft: Optional[Callable[[float], None]] = None,
    ):
        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        if soft_deadline_s is not None and not (
            0 < soft_deadline_s < deadline_s
        ):
            raise ValueError(
                f"soft_deadline_s must be in (0, deadline_s={deadline_s}), "
                f"got {soft_deadline_s}"
            )
        self.deadline_s = deadline_s
        self.soft_deadline_s = soft_deadline_s
        self.on_soft = on_soft
        self.ledger = ledger
        self.manifest = manifest
        self.recorder = recorder
        self.checkpointer = checkpointer
        self.tag = tag
        self.exit_code = exit_code
        self._exit_fn = exit_fn if exit_fn is not None else os._exit  # savlint: disable=SAV114 -- THE sanctioned hard-exit contract: a wedged main thread cannot be unwound, and manifest/recorder/checkpoint drains run bounded above before _fire exits
        self._stream = stream
        self._poll_s = poll_s if poll_s is not None else min(deadline_s / 4, 5.0)
        self._dump_timeout_s = dump_timeout_s
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self.fired = threading.Event()
        self.soft_fired = threading.Event()
        self.soft_count = 0
        self._soft_fired_episode = False
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        """Mark progress; call once per completed step/loop iteration."""
        self._last_beat = time.monotonic()

    def start(self) -> "HangWatchdog":
        if self._thread is not None:
            return self
        self.beat()  # the deadline counts from start, not construction
        self._thread = threading.Thread(
            target=self._run, name=f"{self.tag}-thread", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Disarm (normal shutdown, eval/checkpoint-free exit paths)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._poll_s)
            self._thread = None

    def __enter__(self) -> "HangWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            silent_s = time.monotonic() - self._last_beat
            if silent_s >= self.deadline_s:
                self._fire(silent_s)
                return
            if self.soft_deadline_s is not None:
                if silent_s >= self.soft_deadline_s:
                    if not self._soft_fired_episode:
                        self._soft_fired_episode = True
                        self._fire_soft(silent_s)
                else:
                    # A beat arrived since the soft fire: the episode is
                    # over, re-arm the warning stage for the next stall.
                    self._soft_fired_episode = False

    def _fire_soft(self, silent_s: float) -> None:
        """Warning stage: evidence to disk, run continues.

        The dump + ``on_soft`` run on a side thread bounded by
        ``dump_timeout_s`` — the same discipline as the hard stage's
        recorder dump, and for the same reason: the callback writes to
        the very log dir whose filesystem may BE the stall's cause (or
        waits on a lock a wedged training thread holds), and a blocked
        monitor thread would silently void the hard stage's
        guaranteed-exit contract. Exceptions are printed, never raised.
        """
        stream = self._stream if self._stream is not None else sys.stderr
        print(
            f"{self.tag}: SOFT — no step completed in {silent_s:.0f}s "
            f"(soft deadline {self.soft_deadline_s:.0f}s, hard "
            f"{self.deadline_s:.0f}s); dumping stacks, run continues",
            file=stream,
        )

        def _dump():
            try:
                dump_all_stacks(stream)
                if self.ledger is not None:
                    print(
                        f"{self.tag}: goodput ledger at soft stage: "
                        + json.dumps(self.ledger.summary()),
                        file=stream,
                    )
            except Exception as e:
                print(f"{self.tag}: soft dump failed: {e!r}", file=stream)
            if self.on_soft is not None:
                try:
                    self.on_soft(silent_s)
                except Exception as e:
                    print(f"{self.tag}: on_soft failed: {e!r}", file=stream)
            try:
                stream.flush()
            except Exception:
                pass

        dumper = threading.Thread(
            target=_dump, name=f"{self.tag}-soft-dump", daemon=True
        )
        dumper.start()
        # Never wait past the hard deadline: the monitor thread must be
        # back polling silent_s when it expires, or a wedged dump would
        # delay the exit-4 contract wrapper scripts key on.
        dumper.join(timeout=min(
            self._dump_timeout_s,
            max(self.deadline_s - silent_s, 0.1),
        ))
        if dumper.is_alive():
            print(
                f"{self.tag}: soft-stage dump still blocked after "
                f"{self._dump_timeout_s:.0f}s (wedged filesystem?); "
                "abandoning it — the hard deadline stays armed",
                file=stream,
            )
        self.soft_count += 1
        self.soft_fired.set()

    def _fire(self, silent_s: float) -> None:
        stream = self._stream if self._stream is not None else sys.stderr
        print(
            f"{self.tag}: HANG — no step completed in {silent_s:.0f}s "
            f"(deadline {self.deadline_s:.0f}s); dumping stacks and "
            f"aborting with exit {self.exit_code}",
            file=stream,
        )
        try:
            dump_all_stacks(stream)
            if self.ledger is not None:
                print(
                    f"{self.tag}: goodput ledger at hang: "
                    + json.dumps(self.ledger.summary()),
                    file=stream,
                )
        except Exception as e:  # diagnostics must not mask the abort
            print(f"{self.tag}: dump failed: {e!r}", file=stream)
        # Flight-recorder bundle BEFORE the manifest finalize, both BEFORE
        # exiting: os._exit skips every finally/atexit, so this is the only
        # chance for the hang's context (last steps, batches, snapshot) to
        # reach disk and for the manifest to point at it. The dump is
        # unbounded file I/O to the very log_dir whose filesystem may BE
        # the hang's cause — so it runs on a bounded side thread: if the
        # write wedges, the abort proceeds anyway (the watchdog's
        # guaranteed-exit contract outranks its telemetry).
        incident_path = None
        if self.recorder is not None:
            dumped: dict = {}

            def _dump():
                try:
                    dumped["path"] = self.recorder.dump_incident(
                        "hang",
                        error=(
                            f"{self.tag}: no step completed in "
                            f"{silent_s:.0f}s"
                        ),
                    )
                except Exception as e:
                    dumped["error"] = e
            dumper = threading.Thread(
                target=_dump, name=f"{self.tag}-dump", daemon=True
            )
            dumper.start()
            dumper.join(timeout=self._dump_timeout_s)
            incident_path = dumped.get("path")
            if dumper.is_alive():
                print(
                    f"{self.tag}: recorder dump still blocked after "
                    f"{self._dump_timeout_s:.0f}s (wedged filesystem?); "
                    "aborting without it",
                    file=stream,
                )
            elif "error" in dumped:
                print(
                    f"{self.tag}: recorder dump failed: "
                    f"{dumped['error']!r}",
                    file=stream,
                )
            elif incident_path:
                print(
                    f"{self.tag}: incident bundle: {incident_path}",
                    file=stream,
                )
        if self.checkpointer is not None:
            # Drain any in-flight async checkpoint save before os._exit
            # abandons it (fit()'s finally never runs on this path). The
            # checkpointer's own wait(timeout_s) bounds the drain on a
            # side thread — a hang whose cause IS the checkpoint
            # filesystem must not stall the exit-4 contract.
            try:
                if not self.checkpointer.wait(
                    timeout_s=self._dump_timeout_s
                ):
                    print(
                        f"{self.tag}: in-flight checkpoint save still "
                        f"unfinished after {self._dump_timeout_s:.0f}s; "
                        "aborting without it (the previous committed "
                        "step remains restorable)",
                        file=stream,
                    )
            except Exception as e:
                print(
                    f"{self.tag}: checkpoint drain failed: {e!r}",
                    file=stream,
                )
        try:
            if self.manifest is not None:
                metrics = None
                if self.ledger is not None:
                    metrics = self.ledger.flat_metrics()
                self.manifest.finalize(
                    "hang",
                    error=(
                        f"{self.tag}: no step completed in "
                        f"{silent_s:.0f}s (deadline {self.deadline_s:.0f}s)"
                    ),
                    exit_code=self.exit_code,
                    metrics=metrics,
                    notes=(
                        {"incident": incident_path} if incident_path else None
                    ),
                )
        except Exception as e:
            print(f"{self.tag}: manifest finalize failed: {e!r}", file=stream)
        stream.flush()
        self.fired.set()
        self._exit_fn(self.exit_code)
