"""Device-memory telemetry and retrace detection.

Two silent failure modes this module makes visible:

- **HBM creep** — fragmentation or a leaked donation growing
  bytes-in-use until a late-run OOM. :func:`hbm_stats` samples
  ``device.memory_stats()`` (a PJRT API: present on TPU, absent or empty
  on CPU — degrade to ``{}``, never raise) and the trainer folds the
  numbers into its logged metrics.
- **Silent recompilation** — a leaked weak type or shape-polymorphic
  batch makes ``jit`` re-trace every step; on the relay each retrace is
  minutes, and nothing in the metrics says why the run got slow.
  :class:`RetraceCounter` diffs a jitted function's compile-cache size
  between logging windows, so a nonzero ``retraces`` metric after warmup
  is an immediate red flag.
"""

from __future__ import annotations

from typing import Optional


def hbm_stats(devices=None) -> dict[str, float]:
    """Aggregate ``memory_stats()`` over local devices; ``{}`` when the
    backend has none (CPU) or the relay refuses the query.

    Keys: ``hbm_bytes_in_use`` (sum), ``hbm_peak_bytes`` (max over
    devices — the OOM-relevant number on a symmetric mesh), and
    ``hbm_bytes_limit`` (sum) when the backend reports it.
    """
    import jax

    devices = jax.local_devices() if devices is None else devices
    in_use = peak = limit = 0.0
    seen = False
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        seen = True
        in_use += float(stats.get("bytes_in_use", 0))
        peak = max(peak, float(stats.get("peak_bytes_in_use", 0)))
        limit += float(stats.get("bytes_limit", 0))
    if not seen:
        return {}
    out = {"hbm_bytes_in_use": in_use, "hbm_peak_bytes": peak}
    if limit:
        out["hbm_bytes_limit"] = limit
    return out


class RetraceCounter:
    """Counts new traces of a ``jax.jit`` function between checks.

    Uses the private-but-stable ``_cache_size()`` accessor; when the
    running jax lacks it the counter degrades to always-zero (``active``
    is False) rather than failing — telemetry must never take a run down.
    """

    def __init__(self, fn):
        self._fn = fn
        self._last = self._size()

    def _size(self) -> Optional[int]:
        try:
            return int(self._fn._cache_size())
        except Exception:
            return None

    @property
    def active(self) -> bool:
        return self._size() is not None

    def delta(self) -> int:
        """New traces since the previous ``delta()`` (or construction).

        The first trace of a fresh function is expected compilation, not a
        *re*-trace, so callers typically take one ``delta()`` after
        warmup and treat any later nonzero as an anomaly.
        """
        size = self._size()
        if size is None:
            return 0
        new = max(size - (self._last or 0), 0)
        self._last = size
        return new
