"""Flight recorder — bounded step-context ring + incident dumps.

The rest of the obs stack says *that* a run went bad (nonfinite counters,
hang watchdog, manifest outcomes); this module makes the failure
*reproducible*. Production training stacks treat that as table stakes:
PaLM (Chowdhery et al. 2022) handled loss spikes by rewinding and
skipping the offending batches, and MegaScale (Jiang et al. 2024)
attributes much of its goodput to in-flight diagnosis + replay tooling.

:class:`FlightRecorder` keeps a bounded ring buffer of the last ``depth``
steps' **host-side** context — batch content hash + shapes/dtypes, the
rng derivation, and the logged step metrics — plus, for the most recent
``keep_batches`` steps, the raw host batches, and a periodic pre-step
``TrainState`` snapshot. The steady-state cost discipline is the same as
the diagnostics module's: **no extra device syncs**. Everything the
recorder touches per step is already on the host — the batch passes
through the feeder's place callback (or the serial fetch), the metrics
arrive at the trainer's existing per-log ``device_get``, and the rng is a
derivation recipe (``fold_in(PRNGKey(seed), 1)``), not a device read.
The one sync recording adds is the *periodic* snapshot ``device_get``,
every ``snapshot_every`` steps, carried by the trainer under an explicit
SAV101 pragma; savlint's SAV111 statically enforces that the per-step
path stays sync-free.

On an **incident** — nonfinite logged metrics, a loss spike beyond a
robust z-score gate (median + ``spike_sigma`` scaled MADs, the same
MAD machinery as tools/regression_sentinel.py), a watchdog hang, or an
uncaught exception in ``fit()`` — :meth:`dump_incident` writes a bundle:

    <log_dir>/incidents/step_<N>/
      incident.json        ring index, trigger, config, rng recipe
      batch_<S>.npz        raw host batches for the kept steps
      state/               nearest pre-step TrainState snapshot
                           (sav_tpu.train.checkpoint.Checkpointer)
      replay_verdict.json  written later by tools/replay_step.py

``tools/replay_step.py`` re-executes the captured steps deterministically
from the bundle and names the first layer group to go nonfinite
(docs/incident_replay.md has the full escalation ladder).

Thread-safety: the feeder thread calls the wrapped place callback, the
training thread calls :meth:`on_step`/:meth:`note_metrics`, and the
watchdog thread may call :meth:`dump_incident` — one lock covers the
shared ring/pending state. jax/orbax are imported only inside
:meth:`dump_incident`; steady-state recording is numpy + stdlib.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

INCIDENT_SCHEMA = 1

# Incident triggers (incident.json "trigger"): what tripped the dump.
TRIGGERS = (
    "nonfinite",        # nonfinite value in the logged step metrics
    "loss_spike",       # loss beyond the robust z-score gate
    "eval_nonfinite",   # nonfinite evaluation metrics
    "hang",             # the hang watchdog fired
    "exception",        # fit() died on an uncaught exception
)

# Host-only keys merged into the logged metrics dict by the trainer; they
# are not produced by the jitted step and are excluded from nonfinite
# detection and from replay comparison (tools/replay_step.py imports this).
HOST_METRIC_KEYS = frozenset({"step", "images_per_sec", "mfu", "retraces"})
HOST_METRIC_PREFIXES = ("hbm_", "goodput/")


def device_metric_items(metrics: dict) -> list:
    """(key, value) pairs of the step-produced metrics — the subset that a
    deterministic replay must reproduce bit-exactly."""
    return [
        (k, v)
        for k, v in sorted(metrics.items())
        if k not in HOST_METRIC_KEYS
        and not any(k.startswith(p) for p in HOST_METRIC_PREFIXES)
        and isinstance(v, (int, float))
    ]


def batch_fingerprint(batch: dict) -> dict:
    """Content hash + shapes/dtypes of a host batch.

    blake2b over the raw bytes (shape/dtype folded in so a reshape cannot
    alias). Runs on whatever thread holds the host batch — the feeder's
    background thread in async mode, so steady-state hashing overlaps
    device compute.
    """
    h = hashlib.blake2b(digest_size=16)
    shapes: dict[str, list] = {}
    dtypes: dict[str, str] = {}
    for key in sorted(batch):
        leaf = batch[key]
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        shapes[key] = list(shape)
        dtypes[key] = dtype
        h.update(key.encode())
        h.update(f"{shape}{dtype}".encode())
        data = getattr(leaf, "tobytes", None)
        h.update(data() if data is not None else repr(leaf).encode())
    return {"hash": h.hexdigest(), "shapes": shapes, "dtypes": dtypes}


class _RingEntry:
    """Host-side context of one training step."""

    __slots__ = ("step", "fingerprint", "batch", "metrics")

    def __init__(self, step, fingerprint, batch):
        self.step = step              # 1-indexed completed-step number
        self.fingerprint = fingerprint  # {hash, shapes, dtypes} or None
        self.batch = batch            # raw host batch (kept steps only)
        self.metrics = None           # logged metrics dict (log windows)

    def to_json(self) -> dict:
        return {
            "step": self.step,
            "batch": self.fingerprint,
            "has_batch": self.batch is not None,
            "metrics": self.metrics,
        }


class FlightRecorder:
    """Bounded ring of step context + incident bundles.

    Args:
      log_dir: incident bundles land in ``<log_dir>/incidents/``.
      depth: ring entries (steps of context) retained.
      keep_batches: raw host batches retained (≤ depth). Snapshot cadence
        must not exceed this or the bundle cannot replay up to the
        incident step.
      snapshot_every: pre-step TrainState snapshot cadence in steps
        (default: ``keep_batches``). The recorder retains the two most
        recent snapshots so the ring window is always covered.
      spike_sigma: loss-spike gate — flag a logged loss more than
        ``spike_sigma`` scaled MADs above the rolling median of healthy
        windows (upward only; a collapsing loss is progress). ``0``
        disables the gate.
      spike_window / spike_min_history: rolling history length and the
        minimum healthy windows before the gate arms (early-training
        noise must not false-fire).
      config: JSON-able run config (``dataclasses.asdict(TrainConfig)``)
        embedded in the bundle so ``tools/replay_step.py`` can rebuild
        the exact trainer.
      seed: the run seed; the bundle records the rng *derivation recipe*
        (``fold_in(PRNGKey(seed), 1)`` — trainer.py's fit stream) rather
        than device-reading the key, keeping recording sync-free.
      manifest: optional RunManifest; every dump cross-links under
        ``notes.incidents``.
      max_incidents: dump budget per recorder (a NaN that persists across
        every later window must not fill the disk).
      clock: injectable for deterministic overhead tests.
    """

    def __init__(
        self,
        log_dir: str,
        *,
        depth: int = 16,
        keep_batches: int = 4,
        snapshot_every: Optional[int] = None,
        spike_sigma: float = 6.0,
        spike_window: int = 32,
        spike_min_history: int = 8,
        config: Optional[dict] = None,
        seed: Optional[int] = None,
        manifest=None,
        max_incidents: int = 4,
        clock: Callable[[], float] = time.perf_counter,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if keep_batches < 1 or keep_batches > depth:
            raise ValueError(
                f"keep_batches must be in [1, depth={depth}], got {keep_batches}"
            )
        self.log_dir = log_dir
        self.depth = depth
        self.keep_batches = keep_batches
        self.snapshot_every = (
            snapshot_every if snapshot_every is not None else keep_batches
        )
        if self.snapshot_every > keep_batches:
            raise ValueError(
                f"snapshot_every={self.snapshot_every} must not exceed "
                f"keep_batches={keep_batches}: the steps between a snapshot "
                "and an incident need their batches to replay"
            )
        self.spike_sigma = spike_sigma
        self.spike_window = spike_window
        self.spike_min_history = spike_min_history
        self.config = config
        self.seed = seed
        self.manifest = manifest
        self.max_incidents = max_incidents
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque[_RingEntry] = deque(maxlen=depth)
        # Host batches observed (feeder thread) but not yet consumed by a
        # step (training thread); the feeder delivers in FIFO order, so a
        # plain queue matches batch to step. Bounded by the feeder's own
        # backpressure (depth + in-flight), not by us.
        self._pending: deque = deque()
        # (state_step, host TrainState) — the two most recent snapshots.
        self._snapshots: deque = deque(maxlen=2)
        self._snap_anchor: Optional[int] = None
        self._loss_history: deque = deque(maxlen=spike_window)
        self.incidents: list[dict] = []
        self.last_step: Optional[int] = None
        # Training-thread bookkeeping (on_step/note_metrics) vs hashing
        # (observe_batch — the feeder's thread in async mode, overlapped
        # with device compute like placement itself) vs the periodic
        # snapshot copy: three separate gauges so the <2% steady-state
        # overhead contract is assertable against the right clock.
        self._overhead_s = 0.0
        self._hash_s = 0.0
        self._snapshot_s = 0.0
        self._steps = 0
        # One bundle per nonfinite *episode*: once NaN is in the state,
        # every later window stays nonfinite — re-dumping each would just
        # burn the incident budget on copies of the same failure.
        self._nonfinite_active = False

    @classmethod
    def from_config(
        cls, config, log_dir: str, *, manifest=None, **overrides
    ) -> "FlightRecorder":
        """Build a recorder from a ``TrainConfig`` — the single source for
        the config→knob mapping (fit(), standalone evaluate(), and
        bench.py all construct through here).

        A shallow ring implies a shallow batch window: ``--record-depth 2``
        with the default ``record_batches=4`` means "keep 2 steps of
        context", so the batch/snapshot knobs clamp down to the depth
        instead of failing the run at fit start (the raw constructor
        stays strict — explicit contradictions should raise).
        """
        import dataclasses

        keep = min(config.record_batches, config.record_depth)
        snap = config.record_snapshot_every
        kwargs = dict(
            depth=config.record_depth,
            keep_batches=keep,
            snapshot_every=min(snap, keep) if snap is not None else None,
            spike_sigma=config.spike_sigma,
            config=dataclasses.asdict(config),
            seed=config.seed,
            manifest=manifest,
        )
        kwargs.update(overrides)
        return cls(log_dir, **kwargs)

    # --------------------------------------------------------- steady state

    def wrap_place(self, place_fn: Callable) -> Callable:
        """Wrap the feeder's place callback: fingerprint + retain the host
        batch on the feeder's thread (overlapped with device compute),
        then place as usual."""

        def place(batch):
            self.observe_batch(batch)
            return place_fn(batch)

        return place

    def observe_batch(self, batch: dict) -> None:
        """Record one host batch about to be placed/consumed (FIFO)."""
        t0 = self._clock()
        info = (batch_fingerprint(batch), batch)
        with self._lock:
            self._pending.append(info)
            self._hash_s += self._clock() - t0

    def on_step(self, step: int) -> None:
        """One training step dispatched; pairs with the oldest observed
        batch. Host-only bookkeeping — never touches device values."""
        t0 = self._clock()
        with self._lock:
            fingerprint, batch = (
                self._pending.popleft() if self._pending else (None, None)
            )
            entry = _RingEntry(step, fingerprint, batch)
            self._ring.append(entry)
            # Batch retention window: only the newest keep_batches entries
            # hold raw data.
            held = [e for e in self._ring if e.batch is not None]
            for stale in held[: max(0, len(held) - self.keep_batches)]:
                stale.batch = None
            self.last_step = step
            self._steps += 1
        self._overhead_s += self._clock() - t0

    def note_metrics(self, step: int, metrics: dict) -> Optional[str]:
        """Attach logged (already host-side) metrics to the ring entry and
        run incident detection. Returns a trigger name or None.

        Called at the trainer's log boundaries with the dict it already
        ``device_get``'d — detection adds no transfers of its own.
        """
        t0 = self._clock()
        trigger = None
        with self._lock:
            for entry in reversed(self._ring):
                if entry.step == step:
                    entry.metrics = dict(metrics)
                    break
        device_items = device_metric_items(metrics)
        if any(not math.isfinite(v) for _, v in device_items):
            # One trigger per nonfinite episode: once NaN is in the state
            # every later window stays nonfinite, and re-dumping would
            # spend the incident budget on copies of the same failure.
            if not self._nonfinite_active:
                self._nonfinite_active = True
                trigger = "nonfinite"
        else:
            self._nonfinite_active = False
            loss = metrics.get("loss")
            if self.spike_sigma and isinstance(loss, (int, float)):
                spike = self._spike_gate(loss)
                if spike is not None:
                    trigger = "loss_spike"
        self._overhead_s += self._clock() - t0
        return trigger

    def _spike_gate(self, loss: float) -> Optional[dict]:
        """Robust z-score gate (median + spike_sigma scaled MADs, upward
        only). Healthy losses enter the rolling history; a flagged one
        does not, so one spike cannot poison the baseline."""
        history = list(self._loss_history)
        if len(history) >= self.spike_min_history:
            med = sorted(history)[len(history) // 2]
            mad = sorted(abs(v - med) for v in history)[len(history) // 2]
            # Same floor logic as the regression sentinel: a zero-MAD
            # (flat) history must not flag sub-percent jitter.
            threshold = self.spike_sigma * max(
                1.4826 * mad, 0.05 * abs(med), 1e-9
            )
            if loss > med + threshold:
                return {"loss": loss, "median": med, "mad": mad,
                        "threshold": threshold}
        self._loss_history.append(float(loss))
        return None

    # ------------------------------------------------------------ snapshots

    def wants_snapshot(self, step: int) -> bool:
        """True when the caller should hand over a pre-step state copy
        (every ``snapshot_every`` steps, anchored at the first ask)."""
        if self._snap_anchor is None:
            self._snap_anchor = step
        return (step - self._snap_anchor) % self.snapshot_every == 0

    def snapshot(self, state_step: int, host_state: Any) -> None:
        """Retain a host-side (already device_get'd) pre-step TrainState.

        The *caller* owns the ``device_get`` — it is the one sync recording
        costs, periodic and pragma'd at the call site (trainer.py), never
        hidden in here.
        """
        t0 = self._clock()
        with self._lock:
            self._snapshots.append((int(state_step), host_state))
        self._snapshot_s += self._clock() - t0

    # ------------------------------------------------------------ incidents

    def stats(self) -> dict[str, float]:
        """Gauge view for the goodput ledger (``recorder/*``)."""
        with self._lock:
            return {
                "steps": float(self._steps),
                "overhead_s": self._overhead_s,
                "hash_s": self._hash_s,
                "snapshot_s": self._snapshot_s,
                "incidents": float(len(self.incidents)),
            }

    def dump_incident(
        self,
        trigger: str,
        step: Optional[int] = None,
        *,
        error: Optional[str] = None,
        extra: Optional[dict] = None,
    ) -> Optional[str]:
        """Write one incident bundle; returns its directory (None when the
        budget is spent, the step already dumped, or I/O failed — dumping
        is telemetry and must never take the run down with it)."""
        if trigger not in TRIGGERS:
            raise ValueError(f"unknown trigger {trigger!r}; use {TRIGGERS}")
        with self._lock:
            if len(self.incidents) >= self.max_incidents:
                return None
            step = step if step is not None else (self.last_step or 0)
            if any(i["step"] == step and i["trigger"] == trigger
                   for i in self.incidents):
                return None
            ring = list(self._ring)
            snapshots = list(self._snapshots)
        bundle = os.path.join(self.log_dir, "incidents", f"step_{step:08d}")
        if os.path.isdir(bundle):
            bundle = f"{bundle}-{trigger}"
            if os.path.isdir(bundle):
                return None
        try:
            path = self._write_bundle(
                bundle, trigger, step, ring, snapshots, error, extra
            )
        except Exception as e:  # never let telemetry kill the run
            import sys

            print(f"flight recorder: incident dump failed: {e!r}",
                  file=sys.stderr)
            return None
        record = {"step": step, "trigger": trigger, "path": path}
        with self._lock:
            self.incidents.append(record)
            incidents = list(self.incidents)
        if self.manifest is not None:
            try:
                self.manifest.note("incidents", incidents)
            except Exception:
                pass
        return path

    def _write_bundle(
        self, bundle, trigger, step, ring, snapshots, error, extra
    ) -> str:
        os.makedirs(bundle, exist_ok=True)
        # Nearest usable snapshot: a snapshot at state-step S replays steps
        # S+1..incident, so EVERY one of those steps must still hold its
        # batch — contiguity, not just overlap (bench's window-granularity
        # recordings hold sparse steps and must come out replayable:
        # false). Snapshot cadence <= keep_batches guarantees a candidate
        # exists in fit() once recording is warm.
        snap_step = None
        snap_state = None
        batch_held = {e.step for e in ring if e.batch is not None}
        batch_steps = sorted(batch_held)
        usable = [
            (s, st) for s, st in snapshots
            if s < step and set(range(s + 1, step + 1)) <= batch_held
        ]
        replayable = bool(usable)
        if usable:
            snap_step, snap_state = max(usable, key=lambda x: x[0])
        elif snapshots:
            # Not replayable up to the incident step, but still the nearest
            # recorded context (replayable: false in the manifest below).
            snap_step, snap_state = max(snapshots, key=lambda x: x[0])
            batch_steps = [s for s in batch_steps if s > snap_step]
        for entry in ring:
            if entry.batch is None:
                continue
            arrays = {}
            for key in sorted(entry.batch):
                leaf = np.asarray(entry.batch[key])
                if leaf.dtype.kind not in "biufc?":
                    # ml_dtypes (bfloat16, float8) round-trip as raw bytes;
                    # the ring entry's dtypes map restores the view
                    # (np.savez cannot serialize them natively).
                    leaf = leaf.view(np.uint8).reshape(leaf.shape + (-1,))
                arrays[key] = leaf
            np.savez(
                os.path.join(bundle, f"batch_{entry.step:08d}.npz"), **arrays
            )
        if snap_step is not None:
            from sav_tpu.train.checkpoint import Checkpointer

            ckpt = Checkpointer(os.path.join(bundle, "state"), keep=1)
            try:
                ckpt.save(snap_step, snap_state)
                ckpt.wait()  # savlint: disable=SAV123 -- crash-path incident dump: a truncated snapshot flush is a non-replayable bundle
            finally:
                ckpt.close()
        doc = {
            "schema": INCIDENT_SCHEMA,
            "trigger": trigger,
            "step": step,
            "created_unix": round(time.time(), 3),
            "error": error,
            "ring": [e.to_json() for e in ring],
            "batch_steps": batch_steps,
            "snapshot_step": snap_step,
            "replayable": replayable,
            "rng": {
                "seed": self.seed,
                "derivation":
                    "jax.random.fold_in(jax.random.PRNGKey(seed), 1), "
                    "then fold_in(rng, state.step) inside the step",
            },
            "config": self.config,
            "extra": extra,
        }
        tmp = os.path.join(bundle, "incident.json.tmp")
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, default=str)
        os.replace(tmp, os.path.join(bundle, "incident.json"))
        return bundle


def load_bundle_batch(bundle: str, step: int, dtypes: dict) -> dict:
    """Load one recorded batch, restoring non-native dtypes (bfloat16 &
    friends were stored as raw uint8 bytes) via the ring's dtype map."""
    import ml_dtypes  # noqa: F401  (registers bfloat16 et al. with numpy)

    out = {}
    with np.load(os.path.join(bundle, f"batch_{step:08d}.npz")) as data:
        for key in data.files:
            arr = data[key]
            want = np.dtype(dtypes.get(key, arr.dtype))
            if arr.dtype != want:
                arr = arr.reshape(arr.shape[:-1] + (-1,)).view(want)
                arr = arr.reshape(arr.shape[:-1])
            out[key] = arr
    return out
