"""Trace intelligence — machine-read the profiles the run already captures.

The capture layer (``TrainConfig.profile_dir``, ``obs/autoprof.py``,
``tools/profile_step.py``) writes ``jax.profiler`` chrome-trace files that
until now only a human in TensorBoard could read; every optimization in
PERF.md (the 70% attention tax, the fused-kernel promotion) came from
hand-reading them. This module is the machine version of that read:

- :func:`load_trace` / :func:`device_op_times` — parse the
  ``*.trace.json.gz`` chrome-trace export and sum complete-event ("X")
  durations per HLO op on the *device* planes. TPU traces carry device
  processes (``"TPU"`` in the process name); CPU-backend traces — what
  autoprof's tier-1 e2e actually captures — have no device plane at all,
  but their XLA execution threads tag op events with an ``hlo_op`` arg,
  so the selector falls back to exactly those events and the parser is
  exercisable without an accelerator.
- :func:`count_steps` — per-step segmentation via the module-execution /
  pjit step markers (top-level occurrences only: the markers nest).
- :func:`parse_hlo_op_index` — map HLO instruction names (what the trace
  calls an op, e.g. ``multiply_reduce_fusion.16``) to their
  ``metadata={op_name="..."}`` scope paths from the compiled
  executable's HLO text. Flax threads module names through those scopes
  (``Encoder_0/block_1/FFBlock_0/fc1/dot_general``), and the path roots
  are the same top-level parameter-tree groups
  ``obs/diagnostics._group_of`` / ``obs/costs.py`` key on.
- :func:`attribute` / :func:`summarize` — fold per-op time through the
  scope index into the cost model's component vocabulary
  (``patch_embed`` / ``attention_proj`` / ``attention_qkav`` / ``ffn`` /
  ``head`` / ``other``) and layer groups, so every trace renders as a
  *measured* ``flops/<comp>_frac``-shaped table next to the cost
  model's *predicted* one — with per-component deltas and a
  disagreement flag (:func:`compare`) when measured time attribution
  diverges from predicted FLOPs attribution beyond a pinned tolerance.
  Measured fractions are time, predicted are FLOPs; on a roofline-bound
  step they should agree, and a large delta is exactly the finding
  (e.g. the dense-softmax HBM tax made attention's time share double
  its FLOPs share — PERF.md §3).

Deliberately **stdlib-only** (no jax, no numpy): ``tools/trace_report.py``
and ``tools/run_report.py`` run this against rsynced logs on a laptop,
and the backend-unreachable post-mortem must never import jax. The
component marker tables are mirrored from ``obs/costs.py`` (which imports
jax transitively); ``tests/test_traceview.py`` pins the two vocabularies
equal.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Iterable, Optional

# Component vocabulary — MUST stay equal to obs/costs.py's COMP_* values
# (test_traceview.py pins this; costs imports jax transitively via
# diagnostics, so the names are mirrored rather than imported).
COMP_PATCH_EMBED = "patch_embed"
COMP_ATTN_PROJ = "attention_proj"
COMP_ATTN_QKAV = "attention_qkav"
COMP_FFN = "ffn"
COMP_HEAD = "head"
COMP_OTHER = "other"
COMPONENTS = (
    COMP_PATCH_EMBED, COMP_ATTN_PROJ, COMP_ATTN_QKAV, COMP_FFN, COMP_HEAD,
    COMP_OTHER,
)

# Scope-segment markers (lowercase substring match). The attention set
# splits into the projections (named qkv/out submodules — the parameter
# matmuls costs.py books as attention_proj) vs the parameter-free core
# (QK^T/AV einsums, softmax — attention_qkav); a segment naming an
# attention *module* without a projection submodule below it is core.
_ATTN_MODULE_MARKERS = (
    "attention", "attn", "selfattention", "talkingheads", "classattention",
)
_ATTN_PROJ_MARKERS = (
    "to_qkv", "to_out", "to_q", "to_kv", "to_v", "query", "key", "value",
    "proj_q", "proj_k", "proj_v", "out_proj",
)
_FFN_MARKERS = ("ffblock", "feedforward", "mlp", "fc1", "fc2", "moeff")
_PATCH_MARKERS = ("patchembed", "patch_embed", "stem", "conv_stem")
_HEAD_MARKERS = ("head",)

# Default measured-vs-predicted disagreement tolerance: absolute gap in
# attribution fraction. 0.15 = fifteen points of step share — big enough
# that FLOPs-vs-time skew on healthy steps (softmax/norms cost time but
# ~no FLOPs) stays quiet, small enough that a dense-softmax-sized tax
# (PERF.md §3 measured attention at ~70% time vs ~35% FLOPs) flags.
DISAGREEMENT_TOLERANCE = 0.15

# A transform wrapper segment in an HLO metadata op_name path:
# jit(main), jvp(ViT), transpose(jvp(ViT)), checkpoint(...), vmap(...).
_TRANSFORM_RE = re.compile(r"^[\w.\-]+\(.*\)$")

# One HLO instruction line with metadata: captures the instruction name
# (the trace's op name) and its op_name scope path.
_HLO_METADATA_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<instr>[\w.\-]+)\s*=\s*.*"
    r"metadata=\{[^}]*op_name=\"(?P<op_name>[^\"]+)\"",
)


# ------------------------------------------------------------------ loading


def find_traces(root: str) -> list[str]:
    """``*.trace.json.gz`` files under ``root`` (a profile dir, an
    autoprof capture dir, or a log dir), oldest → newest by mtime."""
    if os.path.isfile(root):
        return [root]
    pattern = os.path.join(root, "**", "*.trace.json.gz")
    return sorted(glob.glob(pattern, recursive=True), key=os.path.getmtime)


def load_trace(path: str) -> list[dict]:
    """The ``traceEvents`` list of one chrome-trace file (.json or
    .json.gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        doc = json.load(f)
    # Chrome's JSON Array Format is a bare list of events; the Object
    # Format wraps them in {"traceEvents": [...]}.
    events = doc if isinstance(doc, list) else doc.get("traceEvents", [])
    return [e for e in events if isinstance(e, dict)]


# ----------------------------------------------------------- device planes


def _process_names(events: Iterable[dict]) -> dict:
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[e.get("pid")] = (e.get("args") or {}).get("name", "")
    return names


def _thread_names(events: Iterable[dict]) -> dict:
    names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            names[(e.get("pid"), e.get("tid"))] = (
                (e.get("args") or {}).get("name", "")
            )
    return names


# Device-process threads that are NOT the per-op plane: the xprof
# chrome export puts "XLA Modules" (one event spanning the whole module
# execution) and "Steps" rows under the same device pid as the op rows
# — summing them would double/triple-count every op's time and pin
# idle_frac at 0 on real TPU traces.
def _is_aggregate_thread(name: str) -> bool:
    low = name.strip().lower()
    return "module" in low or low == "steps" or low.startswith("step ")


def device_events(events: list[dict]) -> tuple[list[dict], str]:
    """The device-plane complete events and which selector matched.

    TPU first: the ``"X"`` events on processes whose name contains
    ``"TPU"`` — restricted to the per-op rows: threads named
    ``XLA Ops...`` when present, otherwise everything except the
    aggregate ``XLA Modules``/``Steps`` rows (whose events span whole
    steps and would double-count every op under them). CPU fallback:
    the CPU backend emits no device process, but its XLA execution
    threads tag each op event with an ``hlo_op`` arg — select those, so
    tier-1 CPU captures parse to real totals instead of the empty dict
    the old ``"TPU" in process_name`` selector produced.
    Returns ``(events, "tpu" | "cpu-hlo-op" | "none")``.
    """
    names = _process_names(events)
    tpu_pids = {pid for pid, name in names.items() if "TPU" in name}
    if tpu_pids:
        threads = _thread_names(events)
        tpu_x = [
            e for e in events
            if e.get("ph") == "X" and e.get("pid") in tpu_pids
        ]
        op_tids = {
            key for key, name in threads.items()
            if key[0] in tpu_pids and "xla ops" in name.lower()
        }
        if op_tids:
            picked = [
                e for e in tpu_x
                if (e.get("pid"), e.get("tid")) in op_tids
            ]
        else:
            picked = [
                e for e in tpu_x
                if not _is_aggregate_thread(
                    threads.get((e.get("pid"), e.get("tid")), "")
                )
            ]
        if picked:
            return picked, "tpu"
    picked = [
        e for e in events
        if e.get("ph") == "X" and "hlo_op" in (e.get("args") or {})
    ]
    return picked, ("cpu-hlo-op" if picked else "none")


def _op_name(event: dict) -> str:
    args = event.get("args") or {}
    return args.get("hlo_op") or event.get("name", "")


def device_op_times(
    events: list[dict],
) -> tuple[dict[str, float], dict[str, int], str]:
    """Per-op total duration (ms) and event counts on the device planes.

    Keys are HLO op (instruction) names — ``hlo_op`` when tagged, the
    event name otherwise (TPU planes name events by instruction
    already). Returns ``(totals_ms, counts, selector)``.
    """
    picked, selector = device_events(events)
    totals: dict[str, float] = {}
    counts: dict[str, int] = {}
    for e in picked:
        name = _op_name(e)
        if not name:
            continue
        totals[name] = totals.get(name, 0.0) + float(e.get("dur", 0)) / 1e3
        counts[name] = counts.get(name, 0) + 1
    return totals, counts, selector


def span_and_busy_ms(events: list[dict]) -> tuple[float, float]:
    """(wall span, summed busy time) of the device planes in ms.

    Busy can exceed span when ops run on parallel device threads (the
    CPU backend's intra-op pool); idle accounting clamps at zero.
    """
    picked, _ = device_events(events)
    if not picked:
        return 0.0, 0.0
    start = min(float(e.get("ts", 0.0)) for e in picked)
    end = max(float(e.get("ts", 0.0)) + float(e.get("dur", 0.0))
              for e in picked)
    busy = sum(float(e.get("dur", 0.0)) for e in picked)
    return (end - start) / 1e3, busy / 1e3


# ------------------------------------------------------------------- steps

# Step markers, in preference order: a train-step pjit dispatch (named,
# so an eval pass or a bench probe in the same window cannot inflate the
# count), then module executions, then any pjit dispatch. Names nest
# (the dispatch TraceMe re-enters), so only top-level occurrences count.
_STEP_MARKER_RES = (
    re.compile(r"^PjitFunction\(.*train.*\)$"),
    re.compile(r"^jit_?_?.*train.*"),
    re.compile(r"^TfrtCpuExecutable::ExecuteHelper$"),
    re.compile(r"^PjitFunction\(.*\)$"),
)


def _top_level_count(events: list[dict]) -> int:
    """Occurrences of same-named events that are not nested inside a
    previous occurrence (the profiler emits one TraceMe per frame, so a
    re-entrant marker shows up twice at the same wall instant)."""
    spans = sorted(
        (float(e.get("ts", 0.0)), float(e.get("dur", 0.0))) for e in events
    )
    count = 0
    horizon = float("-inf")
    for ts, dur in spans:
        if ts >= horizon:
            count += 1
            horizon = ts + dur
    return count


def count_steps(events: list[dict]) -> Optional[int]:
    """Number of training steps the capture covers, from the step
    markers; None when nothing matched (caller may know the count from
    its own capture window — autoprof does)."""
    by_name: dict[str, list[dict]] = {}
    for e in events:
        if e.get("ph") == "X" and isinstance(e.get("name"), str):
            by_name.setdefault(e["name"], []).append(e)
    for marker in _STEP_MARKER_RES:
        candidates = [
            evs for name, evs in by_name.items() if marker.match(name)
        ]
        if candidates:
            # The most frequent matching name is the per-step one.
            best = max(candidates, key=len)
            n = _top_level_count(best)
            if n > 0:
                return n
    return None


# ------------------------------------------------------------ HLO op index


def parse_hlo_op_index(hlo_text: str) -> dict[str, str]:
    """``{instruction_name: metadata op_name scope}`` from post-
    optimization HLO text (``compiled.as_text()``).

    The trace's op names are instruction names (``dot.19``,
    ``multiply_reduce_fusion.16``); the metadata ``op_name`` is the
    jax name-stack path (``jit(step)/jvp(ViT)/Encoder_0/block_1/...``)
    that carries the flax module scopes. Fusions inherit their root
    instruction's metadata, which is exactly the right attribution.
    """
    index: dict[str, str] = {}
    for line in hlo_text.splitlines():
        if "metadata=" not in line or "op_name=" not in line:
            continue
        m = _HLO_METADATA_RE.match(line)
        if m:
            index.setdefault(m.group("instr"), m.group("op_name"))
    return index


def scope_segments(op_name: str) -> list[str]:
    """Module-path segments of a metadata op_name, transform wrappers
    (``jit(...)``, ``jvp(Model)``, ``transpose(jvp(Model))``) stripped."""
    return [
        seg for seg in op_name.split("/")
        if seg and not _TRANSFORM_RE.match(seg)
    ]


def is_backward(op_name: str) -> bool:
    """True when the op belongs to the backward pass (jax marks the
    transposed computation with a ``transpose(...)`` wrapper segment)."""
    return "transpose(" in op_name


def component_of_scope(op_name: str) -> str:
    """Map a metadata op_name scope onto the cost model's component
    vocabulary (the keys of ``StepCost.attribution``)."""
    segments = scope_segments(op_name)
    joined = "/".join(segments).lower()
    if not segments:
        return COMP_OTHER
    if any(m in joined for m in _PATCH_MARKERS):
        return COMP_PATCH_EMBED
    if any(m in joined for m in _ATTN_MODULE_MARKERS):
        if any(m in joined for m in _ATTN_PROJ_MARKERS):
            return COMP_ATTN_PROJ
        return COMP_ATTN_QKAV
    if any(m in joined for m in _FFN_MARKERS):
        return COMP_FFN
    if any(seg.lower().startswith(m) for seg in segments
           for m in _HEAD_MARKERS):
        return COMP_HEAD
    return COMP_OTHER


def group_of_scope(op_name: str) -> str:
    """Top-level module segment — the same layer-group key
    ``obs/diagnostics._group_of`` derives from the parameter tree
    (``Encoder_0``, ``PatchEmbedBlock_0``, ``head``, ...).

    A module scope always has at least two segments (module path + the
    primitive, e.g. ``Encoder_0/block_0/.../dot_general``); a
    single-segment scope is a bare top-level primitive — the loss math,
    the optimizer update, a donation copy — and belongs to ``other``,
    not to a fake group named after the primitive.
    """
    segments = scope_segments(op_name)
    return segments[0] if len(segments) >= 2 else COMP_OTHER


# ----------------------------------------------------------- op-name kinds

# HLO op-name buckets for traces WITHOUT a scope index (the offline case
# where only the trace file survived). Coarser than components — op names
# alone cannot tell attention from FFN — but they still rank softmax /
# transpose / dot time, which is how PERF.md's §3 profile was read.
OP_KINDS = (
    "softmax", "dot/conv", "transpose", "copy/layout", "collective",
    "fusion(other)", "other",
)


def op_kind(name: str) -> str:
    n = name.lower()
    if "softmax" in n:
        return "softmax"
    if "transpose" in n:
        return "transpose"
    if "dot" in n or "conv" in n or "einsum" in n:
        return "dot/conv"
    if "copy" in n or "bitcast" in n:
        return "copy/layout"
    if "all-reduce" in n or "all-gather" in n or "reduce-scatter" in n \
            or "collective" in n or "ppermute" in n or "all-to-all" in n:
        return "collective"
    if "fusion" in n:
        return "fusion(other)"
    return "other"


# ------------------------------------------------------------- attribution


def attribute(
    totals_ms: dict[str, float],
    op_index: Optional[dict[str, str]] = None,
) -> dict:
    """Fold per-op time into components / layer groups / op kinds.

    With an ``op_index`` (scope metadata), components and groups are
    exact; without one, every op lands in the kind buckets only and
    ``indexed_frac`` is 0. Ops the index does not know stay honest in
    ``unattributed_ms`` instead of silently padding ``other``.
    """
    components = {c: 0.0 for c in COMPONENTS}
    groups: dict[str, float] = {}
    kinds: dict[str, float] = {}
    fwd = bwd = 0.0
    unattributed = 0.0
    total = 0.0
    for name, ms in totals_ms.items():
        total += ms
        kinds[op_kind(name)] = kinds.get(op_kind(name), 0.0) + ms
        scope = (op_index or {}).get(name)
        if scope is None:
            unattributed += ms
            continue
        components[component_of_scope(scope)] += ms
        group = group_of_scope(scope)
        groups[group] = groups.get(group, 0.0) + ms
        if is_backward(scope):
            bwd += ms
        else:
            fwd += ms
    indexed = total - unattributed
    return {
        "total_ms": total,
        "indexed_ms": indexed,
        "unattributed_ms": unattributed,
        "indexed_frac": (indexed / total) if total else 0.0,
        "components_ms": components,
        "components_frac": {
            c: (v / indexed if indexed else 0.0)
            for c, v in components.items()
        },
        "groups_ms": dict(sorted(groups.items())),
        "groups_frac": {
            g: (v / indexed if indexed else 0.0)
            for g, v in sorted(groups.items())
        },
        "kinds_ms": dict(sorted(kinds.items(), key=lambda kv: -kv[1])),
        "fwd_ms": fwd,
        "bwd_ms": bwd,
    }


def attention_core_frac(attribution: dict) -> Optional[float]:
    """The measured attention-core share (``attention_qkav`` time over
    indexed time) — the number the regression sentinel gates on so a
    perf change is attributable to *where* time went. None when the
    trace had no scope index (an unindexed share is not a measurement).
    """
    if not attribution.get("indexed_ms"):
        return None
    return attribution["components_frac"].get(COMP_ATTN_QKAV, 0.0)


def compare(
    measured_frac: dict[str, float],
    predicted_frac: dict[str, float],
    *,
    tolerance: float = DISAGREEMENT_TOLERANCE,
) -> dict:
    """Measured (time) vs predicted (FLOPs) attribution, per component.

    Rows carry the delta; components whose absolute gap exceeds
    ``tolerance`` are flagged, and the summary-level ``disagrees`` bit
    is the falsifiability link ROADMAP items 1/3 hinge on: when the
    cost model's picture of a step stops matching the measured one,
    autotuning over that model is guessing again.
    """
    rows = []
    disagrees = []
    for comp in sorted(set(measured_frac) | set(predicted_frac)):
        measured = float(measured_frac.get(comp, 0.0))
        predicted = float(predicted_frac.get(comp, 0.0))
        delta = measured - predicted
        flagged = abs(delta) > tolerance
        if flagged:
            disagrees.append(comp)
        rows.append({
            "component": comp,
            "measured_frac": round(measured, 4),
            "predicted_frac": round(predicted, 4),
            "delta": round(delta, 4),
            "flagged": flagged,
        })
    return {
        "tolerance": tolerance,
        "rows": rows,
        "disagrees": disagrees,
    }


# --------------------------------------------------------- request planes


def request_spans(events: list[dict]) -> dict[int, dict]:
    """Per-request serve span timelines from a chrome trace.

    The serving telemetry layer (``sav_tpu/serve/telemetry.py``) exports
    its span ring as complete events tagged with a ``request`` arg (one
    row per request, one event per lifecycle interval). This reads them
    back — the request-timeline twin of :func:`device_op_times`, so
    ``tools/trace_report.py`` renders request traces with the machinery
    that reads device profiles. Returns ``{request_id: {"stages":
    [(name, start_ms, dur_ms)...], "total_ms", "dominant_stage",
    "bucket", "deadline_ms", "overrun_ms"}}`` (empty when the trace has
    no request plane).
    """
    out: dict[int, dict] = {}
    for e in events:
        args = e.get("args") or {}
        if e.get("ph") != "X" or "request" not in args:
            continue
        rid = args["request"]
        view = out.setdefault(rid, {
            "stages": [],
            "total_ms": 0.0,
            "bucket": args.get("bucket"),
            "deadline_ms": args.get("deadline_ms"),
            "overrun_ms": args.get("overrun_ms"),
        })
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        view["stages"].append(
            (e.get("name", "?"), float(e.get("ts", 0.0)) / 1e3, dur_ms)
        )
        view["total_ms"] += dur_ms
    for view in out.values():
        view["stages"].sort(key=lambda s: s[1])
        view["total_ms"] = round(view["total_ms"], 3)
        view["dominant_stage"] = (
            max(view["stages"], key=lambda s: s[2])[0]
            if view["stages"] else None
        )
    return out


# ----------------------------------------------------------- fleet merge

#: The merged fleet-walk vocabulary (ISSUE 16) — one contiguous
#: router→replica→router chain per request. ``depad`` covers the
#: replica's whole post-device tail (depad + deliver); a request whose
#: replica export is missing degrades to the router-only chain
#: (``replica_wait`` stays opaque) — never dropped.
FLEET_STAGES = (
    "router_queue",    # router: admit -> route_selected
    "route",           # router: route_selected -> connect
    "transport_send",  # connect -> replica admission (clock-shifted)
    "replica_queue",   # replica: submit -> device dispatch
    "device",          # replica: the batch step itself
    "depad",           # replica: device done -> reply written
    "transport_reply", # replica done (shifted) -> router completed
)

#: Router-only degradation chain: the replica decomposition collapses
#: into the opaque ``replica_wait`` span the router measured itself.
FLEET_STAGES_ROUTER_ONLY = (
    "router_queue", "route", "transport_send", "replica_wait",
    "transport_reply",
)

FLEET_TRACE_SCHEMA = 1


def _span_bounds(events: list[dict]) -> dict:
    """Per-request interval bounds from one export's chrome events:
    ``{rid: {"at": {name: (start_us, end_us)}, "args": {...}}}`` (first
    occurrence of a name wins, matching ``intervals()``' first-stamp
    rule)."""
    out: dict = {}
    for e in events:
        args = e.get("args") or {}
        if e.get("ph") != "X" or "request" not in args:
            continue
        rid = args["request"]
        view = out.setdefault(rid, {"at": {}, "args": {}})
        name = e.get("name", "?")
        ts = float(e.get("ts", 0.0))
        if name not in view["at"]:
            view["at"][name] = (ts, ts + float(e.get("dur", 0.0)))
        for k, v in args.items():
            if k != "request" and v is not None:
                view["args"].setdefault(k, v)
    return out


def _replica_boundaries(at: dict) -> Optional[dict]:
    """The four replica instants the merge needs, from the replica's
    interval bounds (its own clock, µs): ``submit`` (admission start),
    ``dispatched`` / ``executed`` (device bounds), ``completed`` (end
    of the last present tail interval). None when the export lacks the
    device span — a torn record degrades to router-only."""
    if "admission" not in at or "device" not in at:
        return None
    completed = at["device"][1]
    for tail in ("depad", "deliver"):
        if tail in at:
            completed = max(completed, at[tail][1])
    return {
        "submit": at["admission"][0],
        "dispatched": at["device"][0],
        "executed": at["device"][1],
        "completed": completed,
    }


def _estimate_offset(pairs: list[tuple]) -> Optional[dict]:
    """Per-replica clock offset (replica clock + offset = router clock)
    from ``(sent, reply, r_submit, r_completed)`` handshake tuples (µs).

    Causality bounds each request: the replica admitted AFTER the router
    sent (``offset >= sent - r_submit``) and the router saw the reply
    AFTER the replica finished (``offset <= reply - r_completed``).
    Intersecting all requests' bounds gives an interval; its midpoint is
    the estimate and its half-width the HONEST skew bound stamped into
    the merged output. An empty intersection (stamp jitter beyond the
    physics) falls back to the median of per-request midpoints with the
    violation size as the bound.
    """
    lbs = [s - rs for s, _, rs, _ in pairs]
    ubs = [r - rc for _, r, _, rc in pairs]
    if not lbs:
        return None
    lb, ub = max(lbs), min(ubs)
    if lb <= ub:
        return {
            "offset_us": (lb + ub) / 2.0,
            "skew_us": (ub - lb) / 2.0,
            "pairs": len(pairs),
        }
    mids = sorted(
        ((s - rs) + (r - rc)) / 2.0 for s, r, rs, rc in pairs
    )
    return {
        "offset_us": mids[len(mids) // 2],
        "skew_us": (lb - ub) / 2.0,
        "pairs": len(pairs),
    }


def fleet_request_spans(log_dir: str) -> dict:
    """The offline fleet-trace joiner (ISSUE 16 tentpole, part 2).

    Reads the router's span-ring export
    (``serve_traces/requests_router.trace.json.gz``) plus every replica
    export (``serve_traces/requests_proc<i>.trace.json.gz``), estimates
    each replica's clock offset from the per-request handshake pairs
    (:func:`_estimate_offset` — bounded-skew midpoint), and merges each
    request into ONE contiguous router→replica→router chain in the
    :data:`FLEET_STAGES` vocabulary. Requests whose replica record is
    missing or torn keep the router-only chain
    (:data:`FLEET_STAGES_ROUTER_ONLY`, ``router_only=True``) — a
    request is NEVER dropped for a lost replica export.

    Returns ``{"schema", "router_export", "replicas": {proc:
    {"offset_ms", "skew_ms", "pairs"}}, "requests": {rid: {...}}}`` —
    empty ``requests`` when there is no router export. Stdlib-only like
    the rest of this module: runs against rsynced logs on a laptop.
    """
    out: dict = {
        "schema": FLEET_TRACE_SCHEMA,
        "router_export": None,
        "replicas": {},
        "requests": {},
    }
    router_path = os.path.join(
        log_dir, "serve_traces", "requests_router.trace.json.gz"
    )
    if not os.path.isfile(router_path):
        return out
    try:
        router = _span_bounds(load_trace(router_path))
    except (OSError, json.JSONDecodeError, EOFError):
        return out
    out["router_export"] = router_path
    # Replica exports: proc index from the filename; a torn file is a
    # degraded (router-only) merge for its requests, not a failure.
    replica: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(
        log_dir, "serve_traces", "requests_proc*.trace.json.gz"
    ))):
        m = re.search(r"requests_proc(\d+)\.trace\.json\.gz$",
                      os.path.basename(path))
        if not m:
            continue
        try:
            replica[int(m.group(1))] = _span_bounds(load_trace(path))
        except (OSError, json.JSONDecodeError, EOFError):
            continue
    # Clock offsets: pair each completed router record with its final
    # replica's record (args["rank"] names the replica that replied).
    offsets: dict[int, Optional[dict]] = {}
    for proc, bounds in sorted(replica.items()):
        pairs = []
        for rid, rview in router.items():
            if rview["args"].get("rank") != proc:
                continue
            if rview["args"].get("outcome") not in (None, "completed"):
                continue
            at = rview["at"]
            if "replica_wait" not in at:
                continue
            rep = bounds.get(rid)
            rb = _replica_boundaries(rep["at"]) if rep else None
            if rb is None:
                continue
            sent, reply = at["replica_wait"]
            pairs.append((sent, reply, rb["submit"], rb["completed"]))
        est = _estimate_offset(pairs)
        offsets[proc] = est
        if est is not None:
            out["replicas"][proc] = {
                "offset_ms": round(est["offset_us"] / 1e3, 3),
                "skew_ms": round(est["skew_us"] / 1e3, 3),
                "pairs": est["pairs"],
            }
    # Merge each router record.
    for rid, rview in sorted(router.items(), key=lambda kv: str(kv[0])):
        at = rview["at"]
        args = rview["args"]
        rank = args.get("rank")
        if "router_queue" not in at or "replica_wait" not in at:
            # Shed/failed before the exchange: no cross-process walk to
            # merge, but NEVER drop the request — keep whatever router
            # spans exist (admission, maybe router_queue/route).
            stages = sorted(
                ((name, round(b[0] / 1e3, 3),
                  round((b[1] - b[0]) / 1e3, 3))
                 for name, b in at.items()),
                key=lambda s: s[1],
            )
            out["requests"][rid] = {
                "rank": rank,
                "outcome": args.get("outcome"),
                "deadline_ms": args.get("deadline_ms"),
                "overrun_ms": args.get("overrun_ms"),
                "router_only": True,
                "skew_ms": None,
                "stages": stages,
                "total_ms": round(
                    (max(b[1] for b in at.values())
                     - min(b[0] for b in at.values())) / 1e3, 3
                ) if at else 0.0,
                "dominant_stage": (
                    max(stages, key=lambda s: s[2])[0] if stages else None
                ),
            }
            continue
        admit = at["router_queue"][0]
        selected = at["router_queue"][1]
        connect = at["route"][1] if "route" in at else selected
        sent, reply = at["replica_wait"]
        completed = (
            at["deliver"][1] if "deliver" in at else reply
        )
        est = offsets.get(rank) if rank is not None else None
        rep = replica.get(rank, {}).get(rid) if rank is not None else None
        rb = _replica_boundaries(rep["at"]) if rep else None
        entry = {
            "rank": rank,
            "outcome": args.get("outcome"),
            "deadline_ms": args.get("deadline_ms"),
            "overrun_ms": args.get("overrun_ms"),
            "router_only": rb is None or est is None,
            "skew_ms": (
                round(est["skew_us"] / 1e3, 3) if est is not None else None
            ),
        }
        if rb is None or est is None:
            cuts = [admit, selected, connect, sent, reply, completed]
            names = FLEET_STAGES_ROUTER_ONLY
        else:
            off = est["offset_us"]
            cuts = [admit, selected, connect,
                    rb["submit"] + off, rb["dispatched"] + off,
                    rb["executed"] + off, rb["completed"] + off,
                    completed]
            names = FLEET_STAGES
        # Contiguity by construction: clamp each boundary to the one
        # before it (a ±skew shift may nudge a replica instant past its
        # neighbour; the chain must stay monotone).
        for i in range(1, len(cuts)):
            cuts[i] = max(cuts[i], cuts[i - 1])
        stages = [
            (name, round(cuts[i] / 1e3, 3),
             round((cuts[i + 1] - cuts[i]) / 1e3, 3))
            for i, name in enumerate(names)
        ]
        entry["stages"] = stages
        entry["total_ms"] = round((cuts[-1] - cuts[0]) / 1e3, 3)
        entry["dominant_stage"] = (
            max(stages, key=lambda s: s[2])[0] if stages else None
        )
        out["requests"][rid] = entry
    return out


def write_fleet_trace(log_dir: str) -> Optional[str]:
    """Persist the merged fleet walk as ONE chrome trace —
    ``serve_traces/fleet.trace.json.gz`` — readable by every existing
    trace consumer (``trace_report``, :func:`request_spans`). Returns
    the path, or None when there was nothing to merge (telemetry
    discipline: never raises)."""
    merged = fleet_request_spans(log_dir)
    if not merged["requests"]:
        return None
    events = [{
        "ph": "M", "pid": 1, "name": "process_name",
        "args": {"name": "Fleet Requests"},
    }]
    for rid, entry in merged["requests"].items():
        for name, start_ms, dur_ms in entry["stages"]:
            events.append({
                "ph": "X", "pid": 1, "tid": rid, "name": name,
                "ts": round(start_ms * 1e3, 1),
                "dur": round(dur_ms * 1e3, 1),
                "args": {
                    "request": rid,
                    "rank": entry["rank"],
                    "outcome": entry["outcome"],
                    "router_only": entry["router_only"],
                    "skew_ms": entry["skew_ms"],
                    "deadline_ms": entry["deadline_ms"],
                    "overrun_ms": entry["overrun_ms"],
                },
            })
    path = os.path.join(log_dir, "serve_traces", "fleet.trace.json.gz")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with gzip.open(tmp, "wt") as f:
            json.dump({"traceEvents": events}, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def write_fleet_exemplars(
    log_dir: str, *, max_exemplars: int = 8
) -> list[str]:
    """Dump the slowest merged requests as fleet exemplars —
    ``serve_traces/slow_fleet_<seq>_req<rid>.json`` with the full
    cross-process walk — under the PR-11 budget discipline (a bounded
    count, slowest first; ``telemetry.find_exemplars``' ``slow_*.json``
    glob picks them up next to the replica-local ones)."""
    merged = fleet_request_spans(log_dir)
    ranked = sorted(
        merged["requests"].items(),
        key=lambda kv: kv[1]["total_ms"], reverse=True,
    )[:max(int(max_exemplars), 0)]
    written = []
    for seq, (rid, entry) in enumerate(ranked):
        doc = {
            "fleet": True,
            "rid": rid,
            "latency_ms": entry["total_ms"],
            "deadline_ms": entry["deadline_ms"],
            "overrun_ms": entry["overrun_ms"],
            "rank": entry["rank"],
            "outcome": entry["outcome"],
            "router_only": entry["router_only"],
            "skew_ms": entry["skew_ms"],
            "dominant_stage": entry["dominant_stage"],
            "stages_ms": {
                name: dur for name, _, dur in entry["stages"]
            },
            "walk": [list(s) for s in entry["stages"]],
        }
        safe_rid = re.sub(r"[^\w.\-]", "_", str(rid))
        path = os.path.join(
            log_dir, "serve_traces",
            f"slow_fleet_{seq:04d}_req{safe_rid}.json",
        )
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
            os.replace(tmp, path)
            written.append(path)
        except OSError:
            continue
    return written


# --------------------------------------------------------------- summaries

TRACEVIEW_SCHEMA = 1


def summarize(
    trace_path: str,
    *,
    op_index: Optional[dict[str, str]] = None,
    predicted: Optional[dict[str, float]] = None,
    steps: Optional[int] = None,
    tolerance: float = DISAGREEMENT_TOLERANCE,
    top_ops: int = 10,
    events: Optional[list[dict]] = None,
) -> dict:
    """One trace file → the machine-readable summary every consumer
    renders (autoprof sidecars, ``tools/trace_report.py``,
    ``run_report.py --trace``, bench's JSON line). Pass ``events`` when
    the trace is already loaded (a real capture gunzips+parses tens of
    MB — callers that also need the raw events must not pay it twice).
    """
    if events is None:
        events = load_trace(trace_path)
    totals, counts, selector = device_op_times(events)
    span_ms, busy_ms = span_and_busy_ms(events)
    n_steps = steps if steps is not None else count_steps(events)
    attribution = attribute(totals, op_index)
    summary = {
        "schema": TRACEVIEW_SCHEMA,
        "trace": trace_path,
        "device_selector": selector,
        "num_ops": len(totals),
        "steps": n_steps,
        "span_ms": round(span_ms, 3),
        "busy_ms": round(busy_ms, 3),
        # Device-plane gap share of the captured span: host stalls,
        # input waits, dispatch bubbles. Parallel device threads can
        # push busy past span (CPU's intra-op pool) — clamp, don't lie.
        "idle_frac": round(max(0.0, 1.0 - busy_ms / span_ms), 4)
        if span_ms > 0 else None,
        "total_ms": round(attribution["total_ms"], 3),
        "per_step_ms": round(attribution["total_ms"] / n_steps, 3)
        if n_steps else None,
        "indexed_frac": round(attribution["indexed_frac"], 4),
        "components_frac": {
            k: round(v, 4)
            for k, v in attribution["components_frac"].items()
        },
        "groups_frac": {
            k: round(v, 4) for k, v in attribution["groups_frac"].items()
        },
        "kinds_ms": {
            k: round(v, 3) for k, v in attribution["kinds_ms"].items()
        },
        "fwd_ms": round(attribution["fwd_ms"], 3),
        "bwd_ms": round(attribution["bwd_ms"], 3),
        "attention_core_frac": (
            round(attention_core_frac(attribution), 6)
            if attention_core_frac(attribution) is not None else None
        ),
        "top_ops": [
            {
                "op": name,
                "ms": round(ms, 3),
                "count": counts.get(name, 0),
                "kind": op_kind(name),
                **(
                    {"scope": op_index[name]}
                    if op_index and name in op_index else {}
                ),
            }
            for name, ms in sorted(
                totals.items(), key=lambda kv: -kv[1]
            )[:top_ops]
        ],
    }
    if predicted is not None and attribution["indexed_ms"]:
        summary["vs_predicted"] = compare(
            attribution["components_frac"], predicted, tolerance=tolerance
        )
    return summary


def save_op_index(path: str, op_index: dict[str, str]) -> Optional[str]:
    """Persist an op index next to a capture (``op_index.json``) so the
    offline tools can attribute without the live executable. Telemetry:
    returns None instead of raising on I/O failure."""
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(op_index, f)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def load_op_index(root: str) -> Optional[dict[str, str]]:
    """Find and load an ``op_index.json`` for a trace: next to the trace
    file, in the capture dir, or any parent up to (and including) the
    log dir's ``autoprof/``. None when absent or unreadable."""
    if os.path.isfile(root):
        root = os.path.dirname(root)
    probe = root
    for _ in range(6):
        candidate = os.path.join(probe, "op_index.json")
        if os.path.exists(candidate):
            try:
                with open(candidate) as f:
                    doc = json.load(f)
                if isinstance(doc, dict):
                    return {str(k): str(v) for k, v in doc.items()}
            except (OSError, json.JSONDecodeError):
                return None
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    return None
