"""Host-side span tracing — Chrome-trace-event JSON around ``fit()`` phases.

A full XPlane capture (``utils.profiler``) answers "what is the device
doing" at ~GB granularity; these spans answer the cheaper, always-on
question "where did the *host* spend wall time" — batch fetch vs
``shard_batch``/H2D vs step dispatch vs the log-sync ``device_get`` vs
eval vs checkpoint. The output is the Trace Event Format
(``{"traceEvents": [...]}`` with ``ph: "X"`` complete events, microsecond
timestamps), which both Perfetto (https://ui.perfetto.dev) and
``chrome://tracing`` load directly.

Stdlib-only on purpose: the tracer must be constructible before (and
usable without) any jax import, and a disabled tracer
(``SpanTracer(None)``) costs one ``if`` per span so call sites wire it
unconditionally.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional


class SpanTracer:
    """Collects complete-events in memory; :meth:`write` dumps the file.

    ``path=None`` disables the tracer entirely (every method is a cheap
    no-op), so the trainer wires spans unconditionally and the flag only
    decides whether anything is recorded. Thread-safe: the watchdog and
    checkpoint threads may emit instants while the train loop records
    spans.
    """

    def __init__(self, path: Optional[str], *, process_name: str = "sav_tpu"):
        self.path = path
        self.enabled = path is not None
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        if self.enabled:
            # Metadata event names the process row in the Perfetto UI.
            self._events.append({
                "name": "process_name", "ph": "M", "pid": os.getpid(),
                "tid": threading.get_ident(),
                "args": {"name": process_name},
            })

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """Record a complete event around the ``with`` body."""
        if not self.enabled:
            yield
            return
        start = self._now_us()
        try:
            yield
        finally:
            event = {
                "name": name, "ph": "X", "ts": start,
                "dur": self._now_us() - start,
                "pid": os.getpid(), "tid": threading.get_ident(),
            }
            if args:
                event["args"] = args
            with self._lock:
                self._events.append(event)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker (eval boundaries, stall anomalies...)."""
        if not self.enabled:
            return
        event = {
            "name": name, "ph": "i", "ts": self._now_us(), "s": "t",
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)

    @property
    def num_events(self) -> int:
        with self._lock:
            return len(self._events)

    def write(self) -> Optional[str]:
        """Write the trace file (returns its path; None when disabled).

        Safe to call repeatedly — crash-prone loops can flush
        periodically and the final file wins.
        """
        if not self.enabled:
            return None
        with self._lock:
            events = list(self._events)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(self.path, "w") as f:
            json.dump(
                {"traceEvents": events, "displayTimeUnit": "ms"},
                f,
            )
        return self.path
