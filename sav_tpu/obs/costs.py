"""Per-step compute cost model — where do the FLOPs go, and how close to
the roofline is the run.

The ROADMAP's "fast as the hardware allows" north star is unfalsifiable
without an achieved-vs-peak number, so this module turns a train step into
a FLOPs/bytes estimate two ways (the MFU accounting popularized by PaLM,
Chowdhery et al. 2022, and the scaling-efficiency methodology of
Megatron-LM, Shoeybi et al. 2019):

- **XLA cost analysis** — ``jit(step).lower(...).compile().cost_analysis()``
  reports the *per-device* FLOPs of the partitioned executable
  (:mod:`sav_tpu.utils.flops`). Exact for whatever XLA actually emitted,
  but a single opaque total.
- **Analytic fallback** — a per-layer-group walk of the parameter tree
  (matmul kernels cost ``2 * tokens * prod(shape)``; attention adds the
  parameter-free QK^T / AV einsums, ``4 * B * L^2 * H * Dh`` per block)
  keyed off the same top-level group naming
  :func:`sav_tpu.obs.diagnostics._group_of` uses. Approximate (it ignores
  norms/bias/softmax flops, a few percent on ViT shapes), but it exists
  on any backend and — unlike the XLA total — it decomposes, so it is
  also the *attribution* source even when the total comes from XLA.

MFU is per chip: ``per_device_flops / step_time / per_chip_peak``. The
peak table lives in :data:`sav_tpu.utils.flops.PEAK_FLOPS_PER_CHIP`;
:func:`resolve_peak_flops` adds an explicit override (``--peak-flops``)
and a deterministic fake peak for CPU so the whole MFU/attribution
pipeline is assertable in tier-1 without an accelerator (the fake is
labeled ``cpu-fake`` everywhere it surfaces — never compare it to the
hardware baseline).

Training-step FLOPs use the standard forward + backward ≈ 3x forward
multiplier (the backward pass does ~2x the forward matmul work); gradient
accumulation does not change the total (same images per optimizer step).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from sav_tpu.obs.diagnostics import _group_of
from sav_tpu.utils.flops import per_chip_peak_flops, xla_cost_analysis

# Deterministic stand-in peak for CPU runs: obviously fake (no CPU does
# 1 TFLOP/s dense f32 on one core), but stable across hosts so tier-1
# can assert the MFU plumbing end-to-end. Labeled 'cpu-fake' wherever it
# is used.
CPU_FAKE_PEAK_FLOPS = 1.0e12

# Forward+backward multiplier over forward matmul FLOPs.
TRAIN_STEP_MULTIPLIER = 3.0

# ---- dot-dtype axis (ISSUE 17). The cost model's traffic and roofline
# numbers are dtype-dependent once the int8 arm exists: an int8 dot
# moves 1 byte/element where bf16 moves 2, and the MXU's int8 pipe peaks
# at 2x its bf16 FLOP/s (the TPU generations the peak table knows all
# share the 2:1 int8:bf16 ratio; the same convention the AQT paper's
# speedups are quoted against). ``None`` keys mean "whatever the compute
# dtype was" — the pre-quant behavior, so existing callers are unchanged.
DOT_DTYPE_BYTES = {"f32": 4, "float32": 4, "bf16": 2, "bfloat16": 2, "int8": 1}

# Peak-FLOP/s multiplier over the table's (bf16) number, per dot dtype.
DOT_DTYPE_PEAK_FACTOR = {"bf16": 1.0, "bfloat16": 1.0, "f32": 1.0,
                         "float32": 1.0, "int8": 2.0}


def dot_dtype_bytes(dot_dtype: Optional[str], default: int = 2) -> int:
    """Bytes per element moved by a dot of the named dtype (``None`` =
    ``default``, the caller's compute-dtype width)."""
    if dot_dtype is None:
        return default
    return DOT_DTYPE_BYTES.get(str(dot_dtype).lower(), default)

# Attribution component names (the gauge/manifest vocabulary). The
# analytic walk buckets every parameter into one of these; QK/AV is the
# parameter-free attention einsum pair, ATTN_PROJ the qkv/out projections.
COMP_PATCH_EMBED = "patch_embed"
COMP_ATTN_PROJ = "attention_proj"
COMP_ATTN_QKAV = "attention_qkav"
COMP_FFN = "ffn"
COMP_HEAD = "head"
COMP_OTHER = "other"

_ATTN_MARKERS = (
    "attention", "attn", "to_qkv", "to_out", "to_q", "to_kv",
    "query", "key", "value",
)
_FFN_MARKERS = ("ffblock", "feedforward", "mlp", "fc1", "fc2", "moeff")
_PATCH_MARKERS = ("patchembed", "patch_embed", "stem", "conv_stem")
_QKV_KERNEL_MARKERS = ("to_qkv", "to_q", "query")


def resolve_peak_flops(
    override: Optional[float] = None,
    devices=None,
    *,
    dot_dtype: Optional[str] = None,
) -> tuple[Optional[float], str]:
    """Per-chip peak FLOP/s and where the number came from.

    Resolution order: explicit ``override`` (``--peak-flops`` /
    ``TrainConfig.peak_flops``) → the device-kind table
    (:data:`~sav_tpu.utils.flops.PEAK_FLOPS_PER_CHIP`) → the
    deterministic CPU fake → ``(None, 'unknown')`` for an accelerator the
    table does not know (MFU is then unreportable rather than wrong).

    ``dot_dtype`` keys the peak by what the dots actually run in
    (:data:`DOT_DTYPE_PEAK_FACTOR` — ``"int8"`` doubles the table's bf16
    number, the MXU's 2:1 int8:bf16 ratio; the source string carries the
    scaling so an int8-scaled peak is never mistaken for the table's).
    An explicit ``override`` is taken verbatim — the operator stated the
    peak for the arm they are measuring.
    """
    if override:
        return float(override), "override"
    import jax

    factor = DOT_DTYPE_PEAK_FACTOR.get(
        str(dot_dtype).lower() if dot_dtype is not None else "bf16", 1.0
    )
    tag = f":{str(dot_dtype).lower()}" if factor != 1.0 else ""
    devices = jax.devices() if devices is None else devices
    peak = per_chip_peak_flops(devices)
    if peak:
        return peak * factor, "device-table" + tag
    if getattr(devices[0], "platform", None) == "cpu":
        return CPU_FAKE_PEAK_FLOPS * factor, "cpu-fake" + tag
    return None, "unknown"


@dataclasses.dataclass
class StepCost:
    """One training step's compute cost, per device.

    ``flops``/``bytes_accessed`` are per-device (matching XLA's
    ``cost_analysis`` convention — the batch shards over devices);
    ``attribution`` maps component → fraction of the *analytic* total
    (sums to ~1.0) and is always analytic, because the XLA total does
    not decompose; ``groups`` is the same attribution keyed by the
    top-level parameter-tree groups diagnostics uses
    (``grad_norm/<group>``), so the two telemetry families line up.
    """

    flops: float
    bytes_accessed: Optional[float]
    source: str  # 'xla-cost-analysis' | 'analytic'
    attribution: dict[str, float]
    groups: dict[str, float]
    num_tokens: int
    per_device_batch: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _leaf_info(path, leaf) -> tuple[str, str, tuple, int]:
    """(joined lowercase path, top group, shape, itemsize) of a param leaf.

    Works on concrete arrays and ``ShapeDtypeStruct``s alike, so the cost
    model can run on ``jax.eval_shape`` output without materializing
    parameters.
    """
    names = [str(getattr(k, "key", getattr(k, "name", k))) for k in path]
    joined = "/".join(names).lower()
    try:
        itemsize = np.dtype(leaf.dtype).itemsize
    except Exception:
        itemsize = 4
    return joined, _group_of(path), tuple(leaf.shape), itemsize


def infer_num_tokens(params: Any, image_size: int) -> int:
    """Sequence length of the encoder trunk, estimated from the params.

    Preference order: a learned ``pos_embed`` table ``(1, L, D)`` states L
    outright; else the patch-embed conv kernel ``(ph, pw, C, D)`` gives
    the patch grid (+1 when a top-level ``cls`` token exists); else assume
    the ViT-default 16px patch. An estimate — rotary/sincos models without
    a patch stem fall through to the default.
    """
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    has_cls = any(
        "cls" in _leaf_info(p, l)[0].split("/")[0] for p, l in leaves
    )
    for path, leaf in leaves:
        joined, _, shape, _ = _leaf_info(path, leaf)
        if "pos_embed" in joined and len(shape) == 3 and shape[0] == 1:
            return int(shape[1])
    for path, leaf in leaves:
        joined, group, shape, _ = _leaf_info(path, leaf)
        if len(shape) == 4 and any(
            m in group.lower() for m in _PATCH_MARKERS
        ):
            ph, pw = int(shape[0]), int(shape[1])
            if ph > 0 and pw > 0:
                grid = max(image_size // ph, 1) * max(image_size // pw, 1)
                return grid + (1 if has_cls else 0)
    return max(image_size // 16, 1) ** 2 + 1


def param_group_bytes(params: Any) -> dict[str, float]:
    """Shape-derived parameter bytes per layer group (+ ``_total``).

    The predicted side of memory forensics (obs/memdump.py): the live
    ``params``-class buffer total should match this; a gap is a
    param-shaped buffer the state no longer owns (donation leak) or a
    dtype drift. Groups are diagnostics' ``_group_of`` naming — the same
    keys as :class:`StepCost.groups` and ``grad_norm/<group>``.
    """
    import jax

    out: dict[str, float] = {}
    total = 0.0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        _, group, shape, itemsize = _leaf_info(path, leaf)
        nbytes = float(np.prod(shape)) * itemsize if shape else float(itemsize)
        out[group] = out.get(group, 0.0) + nbytes
        total += nbytes
    out = dict(sorted(out.items()))
    out["_total"] = total
    return out


def _component_of(joined: str, group: str, shape: tuple) -> str:
    top = group.lower()
    if top == "head" or top.startswith("head"):
        return COMP_HEAD
    if any(m in top for m in _PATCH_MARKERS) or (
        len(shape) == 4 and "embed" in top
    ):
        return COMP_PATCH_EMBED
    if any(m in joined for m in _ATTN_MARKERS):
        return COMP_ATTN_PROJ
    if any(m in joined for m in _FFN_MARKERS):
        return COMP_FFN
    return COMP_OTHER


def analytic_train_step_cost(
    params: Any,
    *,
    batch_size: int,
    image_size: int,
    n_devices: int = 1,
    training: bool = True,
) -> StepCost:
    """Analytic per-device FLOPs/bytes for one train step over ``params``.

    ``batch_size`` is the *global* batch; the result is divided by
    ``n_devices`` to match ``cost_analysis``'s per-device convention.
    """
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    num_tokens = infer_num_tokens(params, image_size)
    b = float(batch_size)
    by_comp: dict[str, float] = {}
    by_group: dict[str, float] = {}
    param_bytes = 0.0
    attn_seen: set[str] = set()
    for path, leaf in leaves:
        joined, group, shape, itemsize = _leaf_info(path, leaf)
        size = float(np.prod(shape)) if shape else 1.0
        param_bytes += size * itemsize
        comp = _component_of(joined, group, shape)
        if len(shape) >= 2 and shape[0] != 1:
            # Matmul kernel: 2 * tokens * prod(shape) forward FLOPs
            # (leading-dim-1 leaves are broadcast tables — cls token,
            # pos_embed — added, not contracted: skipped). The
            # head sees one pooled token per image; everything else sees
            # the full trunk sequence (patch embed included: each of the
            # L patches is one (ph*pw*C -> D) matmul, and prod(shape)
            # already equals that inner product).
            tokens = b if comp == COMP_HEAD else b * num_tokens
            flops = 2.0 * tokens * size
            by_comp[comp] = by_comp.get(comp, 0.0) + flops
            by_group[group] = by_group.get(group, 0.0) + flops
        if any(m in joined for m in _QKV_KERNEL_MARKERS) and len(shape) >= 2:
            # One attention core per qkv/query kernel: the parameter-free
            # QK^T and AV einsums cost 2 * B * L^2 * (H * Dh) each. The
            # model width H*Dh is the kernel's trailing head dims (the
            # fused (D, 3, H, Dh) layout and a separate (D, H, Dh) query
            # kernel both end in H, Dh).
            module = joined.rsplit("/", 1)[0]
            if module not in attn_seen:
                attn_seen.add(module)
                hd = float(shape[-1]) * (
                    float(shape[-2]) if len(shape) >= 3 else 1.0
                )
                qkav = 4.0 * b * float(num_tokens) ** 2 * hd
                by_comp[COMP_ATTN_QKAV] = (
                    by_comp.get(COMP_ATTN_QKAV, 0.0) + qkav
                )
                by_group[group] = by_group.get(group, 0.0) + qkav
    mult = TRAIN_STEP_MULTIPLIER if training else 1.0
    total = sum(by_comp.values()) * mult
    n = max(int(n_devices), 1)
    attribution = {
        k: (v / (total / mult) if total else 0.0)
        for k, v in sorted(by_comp.items())
    }
    groups = {
        k: (v / (total / mult) if total else 0.0)
        for k, v in sorted(by_group.items())
    }
    # Rough traffic floor: the step reads params (fwd + bwd) and writes
    # updates (~3x param bytes) and reads the input batch once. A floor,
    # not a roofline denominator — activations are excluded on purpose.
    batch_bytes = b * image_size * image_size * 3 * 4 / n
    bytes_accessed = 3.0 * param_bytes + batch_bytes
    return StepCost(
        flops=total / n,
        bytes_accessed=bytes_accessed,
        source="analytic",
        attribution=attribution,
        groups=groups,
        num_tokens=num_tokens,
        per_device_batch=b / n,
    )


def train_step_cost(
    params: Any,
    *,
    batch_size: int,
    image_size: int,
    compiled=None,
    n_devices: int = 1,
    training: bool = True,
) -> StepCost:
    """The production cost estimate: XLA totals when a compiled executable
    is at hand, the analytic walk otherwise — attribution fractions come
    from the analytic model either way (XLA's total does not decompose).
    """
    cost = analytic_train_step_cost(
        params,
        batch_size=batch_size,
        image_size=image_size,
        n_devices=n_devices,
        training=training,
    )
    if compiled is not None:
        analysis = xla_cost_analysis(compiled)
        flops = float(analysis.get("flops", 0.0) or 0.0)
        if flops > 0:
            cost = dataclasses.replace(
                cost,
                flops=flops,
                bytes_accessed=float(
                    analysis.get("bytes accessed", 0.0) or 0.0
                ) or cost.bytes_accessed,
                source="xla-cost-analysis",
            )
    return cost


def publish_cost_gauges(
    ledger,
    cost: StepCost,
    *,
    peak_flops: Optional[float] = None,
    peak_source: str = "unknown",
) -> None:
    """Fold a :class:`StepCost` into a goodput ledger as gauges.

    Gauge vocabulary (flat_metrics prefixes these with ``goodput/``):
    ``flops/step_per_device``, ``flops/<component>_frac`` (the per-group
    attribution), and ``peak_flops`` when known. The achieved-rate pair
    (``flops_per_s``, ``mfu``) is published separately by the caller once
    step timings exist — see :func:`publish_mfu_gauges`.
    """
    ledger.set_gauge("flops/step_per_device", cost.flops)
    for comp, frac in cost.attribution.items():
        ledger.set_gauge(f"flops/{comp}_frac", frac)
    if peak_flops:
        ledger.set_gauge("peak_flops", peak_flops)
        ledger.set_gauge("peak_flops_is_fake", float(peak_source == "cpu-fake"))


def publish_mfu_gauges(
    ledger,
    *,
    step_flops: float,
    peak_flops: Optional[float],
    steps: int,
    step_seconds: float,
) -> Optional[float]:
    """Publish ``flops_per_s`` + ``mfu`` gauges from aggregate step time.

    Returns the MFU (or None when unreportable). ``step_seconds`` is the
    ledger's ``step`` bucket — training-thread wall attributed to device
    compute, the honest denominator for end-of-run utilization.
    """
    if not step_flops or steps <= 0 or step_seconds <= 0:
        return None
    flops_per_s = step_flops * steps / step_seconds
    ledger.set_gauge("flops_per_s", flops_per_s)
    if not peak_flops:
        return None
    mfu = flops_per_s / peak_flops
    ledger.set_gauge("mfu", mfu)
    return mfu
