"""Anomaly-triggered on-demand profiling — bounded jax.profiler captures.

The static profile window (``TrainConfig.profile_dir`` + start/num steps)
answers "what does a healthy steady-state step look like"; it is useless
for the anomalies that actually cost goodput, because nobody knows at
launch time *when* the stall will happen. This module closes that gap:
when the run's own telemetry flags trouble — the goodput ledger's stall
anomaly, a per-window step time beyond a robust (median + MAD) spike
gate, or the hang watchdog crossing its soft (warning) stage —
:class:`AutoProfiler` arms ``jax.profiler`` for a bounded N-step trace
window, stamps the capture into the run manifest
(``notes.autoprof``), and stands down.

Budget discipline mirrors the flight recorder's ``max_incidents``: a
pathology that recurs every window must not fill the disk with traces,
so ``max_captures`` bounds the per-run total and a ``cooldown_steps``
gap separates consecutive captures. Profiling is telemetry: every
profiler call is wrapped so a failed capture (e.g. a trace already
active from the static window) counts as ``errors`` instead of taking
the run down.

No device syncs: arming/starting/stopping are host-side profiler API
calls driven from the trainer's existing loop positions (savlint SAV112
pins the ``note_window``/``request`` path sync-free alongside the fleet
heartbeat). The captured window is therefore *approximate* — it starts
at the step boundary after the trigger — which is the right trade: the
anomaly detector runs at log granularity anyway, and a sync to align
the window would itself distort the thing being measured.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from sav_tpu.obs.fleet import MAD_SCALE, _mad, _median

TRIGGERS = (
    "stall_anomaly",     # the goodput ledger flagged a stalled window
    "step_time_spike",   # per-window step time beyond the robust gate
    "watchdog_soft",     # the hang watchdog crossed its warning stage
    "serve_p99_spike",   # serving: request latency beyond the robust
                         # slow-exemplar gate (sav_tpu/serve/telemetry.py;
                         # "step" counts completed batches)
    "serve_queue_spike", # serving: queue depth beyond its robust gate
                         # (overload building faster than the drain)
    "manual",            # explicit request (tools, tests)
)


class AutoProfiler:
    """Arms a bounded ``jax.profiler`` trace window on anomaly triggers.

    Driven by three call sites in the train loop, all host-side:
    :meth:`on_step` at the top of every iteration (the state machine —
    starts an armed capture, stops a finished one), :meth:`note_window`
    at each log boundary with the window's per-step wall time (the
    internal spike gate), and :meth:`request` wherever an external
    detector fires (ledger stall anomaly, watchdog soft stage —
    any thread). ``start_fn``/``stop_fn`` are injectable for tests;
    production resolves :mod:`sav_tpu.utils.profiler` lazily so this
    module imports without jax.
    """

    def __init__(
        self,
        log_dir: str,
        *,
        trace_steps: int = 4,
        max_captures: int = 2,
        cooldown_steps: int = 16,
        spike_sigma: float = 4.0,
        spike_window: int = 32,
        spike_min_history: int = 8,
        process_index: int = 0,
        manifest=None,
        start_fn: Optional[Callable[[str], None]] = None,
        stop_fn: Optional[Callable[[], None]] = None,
        analyze: bool = True,
        op_index_fn: Optional[Callable[[], Optional[dict]]] = None,
    ):
        if trace_steps < 1:
            raise ValueError(f"trace_steps must be >= 1, got {trace_steps}")
        if max_captures < 1:
            raise ValueError(
                f"max_captures must be >= 1, got {max_captures}"
            )
        self.log_dir = log_dir
        self.trace_steps = trace_steps
        self.max_captures = max_captures
        self.cooldown_steps = cooldown_steps
        self.spike_sigma = spike_sigma
        self.spike_min_history = spike_min_history
        self.process_index = int(process_index)
        self.manifest = manifest
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        # Post-capture trace intelligence (obs/traceview.py): parse the
        # capture's own trace, attribute op time onto the cost model's
        # component keys, and ride the summary on the capture record.
        # op_index_fn lazily yields {hlo op -> metadata scope} (the
        # trainer derives it from the compiled step); predicted is the
        # cost model's attribution for the measured-vs-predicted table.
        self.analyze = analyze
        self._op_index_fn = op_index_fn
        self._predicted: Optional[dict] = None
        self._lock = threading.Lock()
        self._armed: Optional[dict] = None     # {trigger, step} pending
        self._active: Optional[dict] = None    # capture in flight
        self._last_end_step: Optional[int] = None
        self._step_history: deque = deque(maxlen=spike_window)
        self.captures: list[dict] = []
        self.denied = 0
        self.errors = 0

    # -------------------------------------------------------------- triggers

    def request(self, trigger: str, step: int) -> bool:
        """Arm a capture for ``trigger`` at ``step``; True iff armed.

        Denials (budget spent, capture already armed/active, inside the
        cooldown window) are counted, not raised — detectors fire at
        will and the budget is the backstop. Thread-safe: the watchdog's
        soft stage calls this from its own thread.
        """
        if trigger not in TRIGGERS:
            raise ValueError(f"unknown trigger {trigger!r}; use {TRIGGERS}")
        with self._lock:
            if self._armed is not None or self._active is not None:
                self.denied += 1
                return False
            if len(self.captures) >= self.max_captures:
                self.denied += 1
                return False
            if (
                self._last_end_step is not None
                and step - self._last_end_step < self.cooldown_steps
            ):
                self.denied += 1
                return False
            self._armed = {"trigger": trigger, "step": int(step)}
            return True

    def note_window(self, step: int, per_step_s: float) -> Optional[str]:
        """Feed one log window's per-step wall time through the robust
        spike gate (median + ``spike_sigma`` scaled MADs over the rolling
        healthy history, upward only — the recorder's loss-spike
        machinery applied to time). Returns the trigger name when it
        fired and armed a capture."""
        if not isinstance(per_step_s, (int, float)) or per_step_s <= 0:
            return None
        history = list(self._step_history)
        spiked = False
        if self.spike_sigma and len(history) >= self.spike_min_history:
            # fleet.py's robust helpers — one median/MAD implementation
            # for the whole fleet layer (itself the sentinel's machinery).
            med = _median(history)
            mad = _mad(history, med)
            threshold = self.spike_sigma * max(
                MAD_SCALE * mad, 0.05 * abs(med), 1e-9
            )
            spiked = per_step_s > med + threshold
        if not spiked:
            # Flagged windows stay out of the history so one spike
            # cannot poison the baseline (goodput.py's discipline).
            self._step_history.append(float(per_step_s))
            return None
        if self.request("step_time_spike", step):
            return "step_time_spike"
        return None

    def set_predicted(self, attribution: Optional[dict]) -> None:
        """Install the cost model's component attribution (the predicted
        side of every capture's measured-vs-predicted table)."""
        self._predicted = dict(attribution) if attribution else None

    # --------------------------------------------------------- state machine

    def _resolve_profiler(self):
        from sav_tpu.utils import profiler

        return profiler.start_trace, profiler.stop_trace

    def on_step(self, step: int) -> None:
        """Drive the capture window from the train loop (top of each
        iteration): stop a finished capture, then start an armed one so
        the window covers whole steps."""
        with self._lock:
            active = self._active
            armed = self._armed
        if active is not None and step >= active["stop_step"]:
            self._finish(step)
            return
        if active is None and armed is not None:
            self._begin(step, armed)

    def _begin(self, step: int, armed: dict) -> None:
        path = os.path.join(
            self.log_dir,
            "autoprof",
            f"proc{self.process_index}_step{step:08d}_{armed['trigger']}",
        )
        start_fn = self._start_fn
        try:
            if start_fn is None:
                start_fn, _ = self._resolve_profiler()
            os.makedirs(path, exist_ok=True)
            start_fn(path)
        except Exception:
            # A capture that cannot start (profiler already tracing for
            # the static window, unwritable dir) is an error to count,
            # never a run-killer; disarm so the trigger can re-fire
            # later rather than wedging the state machine.
            with self._lock:
                self.errors += 1
                self._armed = None
            return
        with self._lock:
            self._active = {
                "trigger": armed["trigger"],
                "trigger_step": armed["step"],
                "start_step": int(step),
                "stop_step": int(step) + self.trace_steps,
                "path": path,
            }
            self._armed = None

    def _finish(self, step: int) -> None:
        stop_fn = self._stop_fn
        try:
            if stop_fn is None:
                _, stop_fn = self._resolve_profiler()
            stop_fn()
        except Exception:
            with self._lock:
                self.errors += 1
                self._active = None
            return
        with self._lock:
            active = self._active
            self._active = None
            if active is None:
                return
            capture = {
                "trigger": active["trigger"],
                "trigger_step": active["trigger_step"],
                "start_step": active["start_step"],
                "end_step": int(step),
                "path": active["path"],
                "t_unix": round(time.time(), 3),
            }
            self._last_end_step = int(step)
        if self.analyze:
            # Bounded post-capture side work (at most max_captures times
            # per run, off the steady-state path): machine-read the trace
            # this capture just wrote so the sidecar/manifest carry a
            # per-layer-group summary instead of a blob pointer. Analysis
            # failure counts as an error, never unwinds the run, and the
            # capture record still lands without its summary.
            try:
                summary = self._analyze_capture(capture)
                if summary is not None:
                    capture["summary"] = summary
            except Exception:
                with self._lock:
                    self.errors += 1
        with self._lock:
            self.captures.append(capture)
            captures = list(self.captures)
        # Per-process sidecar FIRST: in a multi-host run every non-zero
        # process carries a DISABLED run manifest (process 0 owns
        # manifest.json), and the straggler's own trace is exactly the
        # capture that must not vanish — tools/fleet_status.py merges
        # these sidecars with notes.autoprof.
        try:
            sidecar = os.path.join(
                self.log_dir, "autoprof",
                f"proc{self.process_index}_captures.jsonl",
            )
            with open(sidecar, "a") as f:
                f.write(json.dumps(capture) + "\n")
        except OSError:
            pass
        if self.manifest is not None:
            try:
                self.manifest.note("autoprof", captures)
            except Exception:
                pass

    def _analyze_capture(self, capture: dict) -> Optional[dict]:
        """Run traceview over this capture's own trace files.

        Writes ``op_index.json`` + ``trace_summary.json`` into the
        capture dir (the offline tools' inputs) and returns a trimmed
        summary for the sidecar/manifest record. Stdlib-only imports —
        traceview never touches jax.
        """
        from sav_tpu.obs import traceview

        traces = traceview.find_traces(capture["path"])
        if not traces:
            return None
        op_index = None
        if self._op_index_fn is not None:
            op_index = self._op_index_fn()
            if op_index:
                traceview.save_op_index(
                    os.path.join(capture["path"], "op_index.json"), op_index
                )
        summary = traceview.summarize(
            traces[-1],
            op_index=op_index,
            predicted=self._predicted,
            # The window's step count is known exactly — the trace's own
            # step markers are a cross-check, not the source of truth.
            steps=max(capture["end_step"] - capture["start_step"], 1),
        )
        try:
            with open(
                os.path.join(capture["path"], "trace_summary.json"), "w"
            ) as f:
                json.dump(summary, f, indent=2)
        except OSError:
            pass
        trimmed = {
            "per_step_ms": summary.get("per_step_ms"),
            "idle_frac": summary.get("idle_frac"),
            "indexed_frac": summary.get("indexed_frac"),
            "device_selector": summary.get("device_selector"),
            "components_frac": summary.get("components_frac"),
            "attention_core_frac": summary.get("attention_core_frac"),
        }
        vs = summary.get("vs_predicted")
        if vs is not None:
            trimmed["disagrees"] = vs.get("disagrees", [])
        return trimmed

    def finalize(self, step: Optional[int] = None) -> None:
        """Stop an in-flight capture (fit()'s finally): a crash inside
        the window must still leave a finished, manifest-stamped trace."""
        with self._lock:
            active = self._active
        if active is not None:
            self._finish(
                step if step is not None else active["start_step"]
            )

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active is not None

    def stats(self) -> dict[str, float]:
        """Gauge view for the goodput ledger (``autoprof/*``)."""
        with self._lock:
            return {
                "captures": float(len(self.captures)),
                "denied": float(self.denied),
                "errors": float(self.errors),
            }
