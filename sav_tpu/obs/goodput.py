"""Goodput ledger — where did the run's wall time actually go.

Large-scale TPU training treats goodput accounting as first-class
infrastructure (PaLM, Chowdhery et al. 2022 reported 'hardware goodput'
per segment); this is the single-process version of that ledger. A run's
wall clock is split into buckets:

  compile     — trace + XLA compile (AOT or the first jit dispatch)
  step        — device training compute (dispatch + log-window sync)
  input_wait  — host batch fetch: time the training thread blocks waiting
                for the next batch (with the async feeder this is queue
                wait only; serial, it is the full host fetch)
  h2d         — host→device placement (sharded device_put) on the
                training thread. The async feeder moves this work to a
                background thread so it overlaps device compute; its
                overlapped share is then reported as a *gauge*
                (``feeder/h2d_s``), not a bucket — buckets partition the
                training thread's wall clock and must still sum to it
  eval        — evaluation passes
  checkpoint  — checkpoint save time on the training thread
  stall       — the *excess* of anomalous step windows over the expected
                step time (the relay's >5x transient slowdowns,
                bench.py docstring)
  other       — residual loop overhead (computed, never accounted)

Gauges (:meth:`GoodputLedger.set_gauge`) carry scalar telemetry that is
not wall time of the training thread — background-thread work, queue
depths, byte counts. They ride the summary/flat_metrics next to the
buckets without breaking the buckets-sum-to-wall invariant.

Stall detection is per *logging window* (the granularity at which the
trainer syncs with the device): a window whose per-step time exceeds
``stall_factor`` x the rolling median of healthy windows is flagged, its
expected portion counted as ``step`` and the excess as ``stall``.
Anomalous windows do not enter the rolling median, so one 100x stall
cannot poison the baseline.

Stdlib-only; ``clock`` is injectable for deterministic tests.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Optional

BUCKETS = (
    "compile", "step", "input_wait", "h2d", "eval", "checkpoint", "stall",
    "other",
)


class GoodputLedger:
    def __init__(
        self,
        *,
        stall_factor: float = 5.0,
        window_history: int = 64,
        clock: Callable[[], float] = time.perf_counter,
    ):
        self._clock = clock
        self._t0 = clock()
        self.stall_factor = stall_factor
        self.window_history = window_history
        self._buckets: dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._per_step_history: list[float] = []
        self._gauges: dict[str, float] = {}
        self.anomalies: list[dict] = []
        self.steps = 0

    # ------------------------------------------------------------- recording

    def account(self, bucket: str, seconds: float) -> None:
        """Add ``seconds`` of wall time to ``bucket``."""
        if bucket not in self._buckets:
            raise KeyError(f"unknown goodput bucket {bucket!r}; use {BUCKETS}")
        self._buckets[bucket] += max(float(seconds), 0.0)

    @contextlib.contextmanager
    def measure(self, bucket: str):
        """Account the wall time of the ``with`` body to ``bucket``."""
        start = self._clock()
        try:
            yield
        finally:
            self.account(bucket, self._clock() - start)

    def set_gauge(self, name: str, value: float) -> None:
        """Record a scalar gauge (background-thread seconds, queue depths,
        byte counts). Gauges are reported next to the buckets but are NOT
        wall-time buckets — they never enter the sum-to-wall accounting."""
        self._gauges[name] = float(value)

    def _median(self) -> Optional[float]:
        if not self._per_step_history:
            return None
        ordered = sorted(self._per_step_history)
        n = len(ordered)
        mid = ordered[n // 2]
        return mid if n % 2 else 0.5 * (ordered[n // 2 - 1] + mid)

    def note_window(self, num_steps: int, seconds: float,
                    step: Optional[int] = None) -> bool:
        """Record one logging window of ``num_steps`` steps.

        Splits the window into ``step`` (expected) + ``stall`` (excess)
        when anomalous; returns True iff the window was flagged.
        """
        if num_steps <= 0:
            return False
        self.steps += num_steps
        per_step = seconds / num_steps
        median = self._median()
        anomalous = median is not None and per_step > self.stall_factor * median
        if anomalous:
            expected = num_steps * median
            self.account("step", expected)
            self.account("stall", seconds - expected)
            self.anomalies.append({
                "step": step,
                "per_step_s": round(per_step, 6),
                "median_per_step_s": round(median, 6),
                "slowdown": round(per_step / max(median, 1e-12), 2),
            })
        else:
            self.account("step", seconds)
            self._per_step_history.append(per_step)
            if len(self._per_step_history) > self.window_history:
                self._per_step_history.pop(0)
        return anomalous

    # ------------------------------------------------------------- reporting

    @property
    def wall_s(self) -> float:
        return self._clock() - self._t0

    def bucket_seconds(self, bucket: str) -> float:
        """Accumulated seconds of one bucket (the ``step`` bucket is the
        end-of-run MFU denominator — obs/costs.py)."""
        if bucket not in self._buckets:
            raise KeyError(f"unknown goodput bucket {bucket!r}; use {BUCKETS}")
        return self._buckets[bucket]

    def summary(self) -> dict:
        """End-of-run ledger: buckets (incl. the ``other`` residual) sum to
        ``wall_s`` up to clock-read noise."""
        total = self.wall_s
        buckets = dict(self._buckets)
        accounted = sum(v for k, v in buckets.items() if k != "other")
        buckets["other"] = max(total - accounted, 0.0)
        summary = {
            "wall_s": round(total, 4),
            "steps": self.steps,
            "buckets_s": {k: round(v, 4) for k, v in buckets.items()},
            "fractions": {
                k: round(v / total, 4) if total > 0 else 0.0
                for k, v in buckets.items()
            },
            # Goodput proper: the fraction of wall time spent on training
            # compute (compile excluded — it is overhead, not progress).
            "goodput_fraction": round(
                buckets["step"] / total, 4) if total > 0 else 0.0,
            "num_anomalies": len(self.anomalies),
        }
        if self.anomalies:
            summary["anomalies"] = list(self.anomalies)
        if self._gauges:
            summary["gauges"] = {
                k: round(v, 6) for k, v in self._gauges.items()
            }
        median = self._median()
        if median is not None:
            summary["median_step_s"] = round(median, 6)
        return summary

    def flat_metrics(self, prefix: str = "goodput/") -> dict[str, float]:
        """Flat float view of :meth:`summary` for metric writers (every
        value a scalar, safe for TensorBoard/wandb sinks)."""
        s = self.summary()
        out = {prefix + "wall_s": s["wall_s"]}
        for k, v in s["buckets_s"].items():
            out[prefix + k + "_s"] = v
        out[prefix + "goodput_fraction"] = s["goodput_fraction"]
        out[prefix + "num_anomalies"] = float(s["num_anomalies"])
        for k, v in s.get("gauges", {}).items():
            out[prefix + k] = v
        return out
