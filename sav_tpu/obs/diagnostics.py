"""In-jit training diagnostics (TrainConfig.diagnostics).

Per-step optimization signals computed *inside* the jitted train step and
returned in the step-metrics dict, so they ride the trainer's existing
per-log ``device_get`` — zero extra host<->device transfers, and on the
relayed bench chip (where transfers degrade sharply mid-run,
docs/benchmarking.md) that is the difference between free diagnostics and
a 2x slower logged step.

The signal set follows the DeiT-recipe ablation practice (Touvron et al.
2021) of watching grad/update norms for recipe instability, plus the
nonfinite counters that matter under bf16 compute:

- ``param_norm`` / ``update_norm`` — global l2 norms of the parameter tree
  and of the post-optimizer update.
- ``update_to_param_ratio`` — the effective relative step size; a healthy
  Adam run sits around 1e-3, collapse/blow-up shows here first.
- ``grad_norm/<group>`` — per-layer-group grad norms (group = top-level
  parameter-tree module, e.g. ``encoder_block_3``), the per-depth view the
  global norm hides.
- ``nonfinite_grads`` / ``nonfinite_params`` — counts of NaN/Inf elements
  (complements ``utils.debug.global_norm_nonfinite``: a count localizes
  "how bad", the flag only says "bad").

Everything here is pure jnp on pytrees: safe under ``jit``, ``scan``, and
any mesh sharding (the reductions partition like any other loss term).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import optax

_EPS = 1e-12


def nonfinite_count(tree: Any) -> jax.Array:
    """In-graph count of NaN/Inf elements across a pytree's float leaves."""
    counts = [
        jnp.sum(~jnp.isfinite(x))
        for x in jax.tree.leaves(tree)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]
    if not counts:
        return jnp.zeros((), jnp.int32)
    return jnp.sum(jnp.stack([c.astype(jnp.int32) for c in counts]))


def _group_of(path) -> str:
    """Top-level module name of a parameter path (the layer group)."""
    for key in path:
        name = str(getattr(key, "key", getattr(key, "name", key)))
        if name:
            return name
    return "params"


def grad_group_norms(grads: Any, prefix: str = "grad_norm/") -> dict:
    """Per-layer-group global norms, keyed ``<prefix><group>``.

    Groups are the top-level names of the parameter tree (``patch_embed``,
    ``encoder_block_0``, ..., ``head``), matching how ViT-family models in
    this repo lay out their params — the per-depth signal the single
    global norm averages away.
    """
    groups: dict[str, list] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]:
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            continue
        groups.setdefault(_group_of(path), []).append(leaf)
    return {
        prefix + name: optax.global_norm(leaves)
        for name, leaves in sorted(groups.items())
    }


def diagnostics_metrics(
    *,
    grads: Any,
    params: Any,
    updates: Any,
    per_group: bool = True,
) -> Mapping[str, jax.Array]:
    """The diagnostics dict merged into the trainer's step metrics.

    ``grads`` are pre-clip gradients, ``updates`` the post-optimizer deltas
    (what actually moves the weights — LR, clipping and weight decay
    included), ``params`` the pre-update parameters. All reductions are
    f32 scalars regardless of compute dtype.
    """
    param_norm = optax.global_norm(params)
    update_norm = optax.global_norm(updates)
    out = {
        "param_norm": param_norm,
        "update_norm": update_norm,
        "update_to_param_ratio": update_norm / (param_norm + _EPS),
        "nonfinite_grads": nonfinite_count(grads),
        "nonfinite_params": nonfinite_count(params),
    }
    if per_group:
        out.update(grad_group_norms(grads))
    return out
