"""Prediction-quality accounting: windowed digest distributions, golden
probe bookkeeping, and shadow-replica agreement scoring.

Everything here is stdlib-only on purpose — the same structural
constraint the rest of ``sav_tpu.obs`` honours (pinned by
test_serve_fleet's no-jax/no-numpy import proof): this module is
imported by the serve telemetry thread and by the Router, neither of
which may drag an array library into a process that only routes bytes.
All array math (the in-graph digests themselves, probe fingerprints)
lives in ``sav_tpu.serve.quality``; this module only *folds* the scalar
streams those produce.

Three folds, one per tentpole leg (docs/quality.md):

- :class:`QualityTracker` — windowed distributions of the per-row
  output digests (top-1 index, top-1 margin, predictive entropy) with
  robust median+MAD drift gates against a frozen reference window:
  prediction churn (total-variation distance of the top-1 class
  histogram), entropy shift (robust z of the entropy median), and PSI
  (population stability index) of the class histogram.
- :class:`ProbeLedger` — golden-probe run accounting: ok/mismatch/shed
  counters, the expected and last-observed fingerprints, and
  ``probe_ok_frac`` (None until a probe ran — skip, never zero-fill).
- :class:`AgreementScorer` — shadow-replica agreement keyed by
  (primary_dtype, shadow_dtype) so an int8 replica shadowing a bf16
  primary is judged against the int8 tolerance envelope (PR-17's
  test_quant contract: same argmax, rel max-abs-diff <= 0.1) and never
  flagged by the same-dtype rule.

The breach and mismatch counters are CUMULATIVE MONOTONIC by design:
the default alert rules (``obs.alerts.quality_rules``) gate on them
with ``for_s=0`` so a planted fault fires exactly one episode that
resolves at finalize — the same exactly-once shape the straggler
battery pins for latency alerts.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Optional

from sav_tpu.obs.fleet import MAD_SCALE, _mad, _median

# Frozen-reference size: the tracker needs enough rows for a stable
# class histogram before judging drift against it. Small on purpose so
# short benches still freeze a reference.
REFERENCE_MIN = 256

# Smoothing mass for PSI: empty histogram cells would otherwise make
# ln(p/q) blow up on any class the reference never saw.
_PSI_EPS = 1e-4

# Per-(primary_dtype, shadow_dtype) tolerance envelopes for shadow
# scoring: relative logit max-abs-diff ceilings, ``rel`` meaning
# relative to the primary's logit max-abs. Same-dtype replicas with
# identical weights produce bit-identical logits under a fixed
# executable, so the same-dtype envelope is tight; any pair involving
# int8 against a float dtype inherits PR-17's quantization envelope
# (test_quant: |f - q|.max() <= 0.1 * |f|.max(), same argmax).
_SAME_DTYPE_REL = 1e-2
_INT8_MIXED_REL = 0.1


def pair_key(primary_dtype: str, shadow_dtype: str) -> str:
    return f"{primary_dtype or '?'}->{shadow_dtype or '?'}"


def envelope_rel(primary_dtype: str, shadow_dtype: str) -> float:
    """The logit rel-diff ceiling for a dtype pair (docs/quality.md,
    "Per-dtype envelopes")."""
    a, b = (primary_dtype or ""), (shadow_dtype or "")
    if a != b and ("int8" in (a, b)):
        return _INT8_MIXED_REL
    return _SAME_DTYPE_REL


class QualityTracker:
    """Windowed output-digest distributions with drift gates vs a
    frozen reference window.

    ``observe_digests`` is hot-path-safe by construction: it only
    appends to bounded deques under a lock (the SlidingWindow idiom).
    All gate math — medians, MADs, histograms, PSI — runs in
    :meth:`snapshot`, which only the telemetry beat thread calls
    (SAV126's scoping contract). The distinctive method names
    (``observe_digests`` / ``score_shadow``) are load-bearing: savlint
    SAV126 audits functions with exactly these names for device syncs
    and flags calls to them from serving hot paths."""

    def __init__(self, window: int = 512, reference_min: int = REFERENCE_MIN):
        self._lock = threading.Lock()
        self._window = int(window)
        self._top1 = collections.deque(maxlen=self._window)
        self._margin = collections.deque(maxlen=self._window)
        self._entropy = collections.deque(maxlen=self._window)
        self._reference_min = int(reference_min)
        self._seen = 0
        self._num_classes = 0
        # Frozen once _seen crosses reference_min: (class hist fracs,
        # entropy median, entropy MAD). Drift is judged against this,
        # not against a sliding baseline that would absorb the drift.
        self._ref: Optional[tuple] = None

    def observe_digests(self, top1, margin, entropy, num_classes: int = 0) -> None:
        """Append one batch of per-row digests (parallel lists of
        int/float scalars — already host-side, already past the single
        result fetch)."""
        with self._lock:
            self._top1.extend(int(t) for t in top1)
            self._margin.extend(float(m) for m in margin)
            self._entropy.extend(float(e) for e in entropy)
            self._seen += len(top1)
            if num_classes:
                self._num_classes = max(self._num_classes, int(num_classes))
            if self._ref is None and self._seen >= self._reference_min:
                self._ref = (
                    self._hist_locked(),
                    _median(list(self._entropy)),
                    _mad(list(self._entropy), _median(list(self._entropy)) or 0.0),
                )

    def _hist_locked(self) -> dict:
        counts: dict = {}
        for t in self._top1:
            counts[t] = counts.get(t, 0) + 1
        n = max(1, len(self._top1))
        return {k: v / n for k, v in counts.items()}

    def snapshot(self) -> dict:
        """The quality fields one heartbeat carries. Gate math happens
        here, at beat cadence — never per request."""
        with self._lock:
            n = len(self._top1)
            if not n:
                return {"n": 0}
            hist = self._hist_locked()
            ent = list(self._entropy)
            mar = list(self._margin)
            ref = self._ref
        ent_med = _median(ent) or 0.0
        out = {
            "n": n,
            "seen": self._seen,
            "entropy_med": round(ent_med, 6),
            "margin_med": round(_median(mar) or 0.0, 6),
        }
        if ref is None:
            return out
        ref_hist, ref_med, ref_mad = ref
        classes = set(hist) | set(ref_hist)
        # Prediction churn: total-variation distance of top-1 class
        # histograms — 0 when the class mix matches the reference, 1
        # when disjoint.
        churn = 0.5 * sum(
            abs(hist.get(c, 0.0) - ref_hist.get(c, 0.0)) for c in classes
        )
        # PSI over the same bins, epsilon-smoothed.
        psi = 0.0
        for c in classes:
            p = hist.get(c, 0.0) + _PSI_EPS
            q = ref_hist.get(c, 0.0) + _PSI_EPS
            psi += (p - q) * math.log(p / q)
        # Entropy shift: robust z of the current entropy median against
        # the frozen reference (MAD-scaled, the obs.fleet convention).
        denom = max(MAD_SCALE * (ref_mad or 0.0), 1e-6)
        out.update(
            {
                "churn": round(churn, 6),
                "psi": round(psi, 6),
                "entropy_shift": round(abs(ent_med - (ref_med or 0.0)) / denom, 4),
                "ref_n": self._reference_min,
            }
        )
        return out


class ProbeLedger:
    """Golden-probe run accounting. The probe itself (batch synthesis,
    fingerprinting, reference persistence) lives device-side in
    ``serve.quality``; this ledger only counts outcomes so heartbeats
    and the final close() beat can carry them."""

    def __init__(self):
        self._lock = threading.Lock()
        self.runs = 0
        self.ok = 0
        self.mismatch = 0
        self.shed = 0
        self.probe_id: Optional[str] = None
        self.expected: Optional[str] = None
        self.last: Optional[str] = None

    def record(self, *, fingerprint: str, expected: str, probe_id: str) -> bool:
        matched = fingerprint == expected
        with self._lock:
            self.runs += 1
            self.probe_id = probe_id
            self.expected = expected
            self.last = fingerprint
            if matched:
                self.ok += 1
            else:
                self.mismatch += 1
        return matched

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "probe_runs": self.runs,
                "probe_ok": self.ok,
                # Cumulative monotonic: the probe-mismatch alert rule
                # gates on > 0 with for_s=0 — exactly one episode per
                # faulty executable, resolved only at finalize.
                "probe_mismatch": self.mismatch,
                "probe_shed": self.shed,
            }
            if self.runs:
                out["probe_ok_frac"] = round(self.ok / self.runs, 6)
            if self.probe_id:
                out["probe_id"] = self.probe_id
            if self.last:
                out["probe_fingerprint"] = self.last
            if self.expected and self.expected != self.last:
                out["probe_expected"] = self.expected
            return out


class AgreementScorer:
    """Shadow-replica agreement, keyed by (primary_dtype,
    shadow_dtype). ``score_shadow`` runs on the router's dedicated
    shadow worker thread — never in admit/route/_dispatch (SAV126)."""

    def __init__(self, window: int = 256):
        self._lock = threading.Lock()
        self._window = int(window)
        # pair key -> deque of (agree: bool, rel_diff: float|None)
        self._pairs: dict = {}
        self._scored = 0
        self._breach = 0
        self._shed = 0

    def score_shadow(
        self,
        primary_dtype: str,
        shadow_dtype: str,
        primary_top1: int,
        shadow_top1: int,
        primary_logits=None,
        shadow_logits=None,
    ) -> dict:
        """Score one mirrored request. Returns the per-sample verdict
        (mostly for tests); counters and windows update in place."""
        key = pair_key(primary_dtype, shadow_dtype)
        agree = int(primary_top1) == int(shadow_top1)
        rel = None
        if primary_logits and shadow_logits and len(primary_logits) == len(shadow_logits):
            scale = max(max(abs(float(x)) for x in primary_logits), 1e-6)
            diff = max(
                abs(float(a) - float(b))
                for a, b in zip(primary_logits, shadow_logits)
            )
            rel = diff / scale
        ceiling = envelope_rel(primary_dtype, shadow_dtype)
        # A sample breaches its pair envelope when the predictions
        # disagree outright, or the logits drifted past the pair's
        # ceiling. An int8 arm inside PR-17's envelope (same argmax,
        # rel <= 0.1) never breaches — the per-dtype-baselines
        # satellite.
        breach = (not agree) or (rel is not None and rel > ceiling)
        with self._lock:
            dq = self._pairs.get(key)
            if dq is None:
                dq = self._pairs[key] = collections.deque(maxlen=self._window)
            dq.append((agree, rel))
            self._scored += 1
            if breach:
                self._breach += 1
        return {"pair": key, "agree": agree, "rel_diff": rel, "breach": breach}

    def record_shed(self) -> None:
        with self._lock:
            self._shed += 1

    def snapshot(self) -> dict:
        with self._lock:
            pairs = {}
            agreements = []
            for key, dq in self._pairs.items():
                if not dq:
                    continue
                agreement = sum(1 for a, _ in dq if a) / len(dq)
                rels = [r for _, r in dq if r is not None]
                pairs[key] = {
                    "n": len(dq),
                    "agreement": round(agreement, 6),
                    "envelope_rel": envelope_rel(*key.split("->", 1)),
                }
                if rels:
                    pairs[key]["rel_diff_max"] = round(max(rels), 6)
                agreements.append(agreement)
            out = {
                "scored": self._scored,
                # Cumulative monotonic, the ProbeLedger.mismatch shape:
                # the shadow-agreement rule gates on > 0.
                "breach": self._breach,
                "shed": self._shed,
            }
            if pairs:
                out["pairs"] = pairs
                # Fleet-level agreement is the WORST pair — a healthy
                # bf16 pair must not mask a drifting int8 pair.
                out["agreement"] = round(min(agreements), 6)
            return out
