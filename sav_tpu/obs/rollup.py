"""Heartbeat rollups — incremental time-series aggregation (ISSUE 19).

Every consumer of the fleet substrate before this module re-parses the
raw append-only heartbeat streams (``fleet/proc_<i>.jsonl`` +
``fleet/router.jsonl``) on every query: the router tail-bounds its
reads, but ``serve_status``/``fleet_status``/``run_report`` walk full
history, and nothing retains a *windowed* view a console can render
cheaply. This module is the metrics pipeline between the raw streams
and their readers: a :class:`Roller` consumes each stream exactly once
(byte-offset cursor, O(new bytes) per refresh — test-pinned via the
``bytes_read`` gauge), buckets every numeric heartbeat metric onto a
fixed resolution ladder (:data:`RESOLUTIONS`, 10s -> 60s -> 600s), and
appends closed buckets as one JSON line each to
``fleet/rollup_<res>.jsonl``::

    {"v": 1, "res": 10, "bucket": 1722000300, "proc": 1,
     "metric": "p99_ms", "n": 12, "min": ..., "max": ...,
     "mean": ..., "p50": ..., "p99": ...}

``proc`` is the replica's process index for ``proc_<i>.jsonl`` streams
and the string ``"router"`` for the router stream (its metrics are also
``router_``-prefixed, so merged views cannot confuse a router queue
with a replica queue).

Crash discipline (the substrate's, extended):

- **Torn tails.** Only byte ranges ending in a newline are consumed; a
  SIGKILLed writer's partial last line stays un-consumed until the next
  roll sees its terminator (or a restarted writer glues a fresh line
  onto it — then the glued garbage line is skipped like every torn
  line, ``read_heartbeats``'s discipline).
- **Torn/missing/stale cursor.** The cursor (``fleet/rollup.cursor.json``)
  is written atomically (tmp + ``os.replace``) *after* the rollup
  appends. An unreadable/missing cursor, or a stream shorter than its
  recorded offset (truncation), triggers a full **rebuild**: streams
  re-read from byte 0 and every ``rollup_<res>.jsonl`` atomically
  rewritten — no double-count, no gap.
- **Crash between append and cursor write.** The next roll re-reads the
  un-cursored bytes and re-appends the same closed buckets; readers
  (:func:`read_rollup`) deduplicate by ``(bucket, proc, metric)``
  keeping the NEWEST line, so replayed appends are idempotent.

Retention is bounded per tier (:data:`RETENTION_BUCKETS` buckets): when
a tier's file outgrows its budget the Roller compacts it in place
(atomic rewrite keeping the newest buckets), so a week-long fleet never
grows an unbounded 10s tier.

Single-writer by contract: ONE roller per log dir at a time (the fleet
router's heartbeat thread in-run, the bench parent post-run, a console
``--roll`` offline) — the cursor file is the handoff, not a lock.

Stdlib-only (no jax, no numpy): rollups must be readable/writable from
a laptop over rsynced logs, and savlint SAV125 statically pins rollup
writes out of the serving hot paths (rolling happens at heartbeat
cadence or offline, never per request).
"""

from __future__ import annotations

import json
import os
from typing import Optional

ROLLUP_SCHEMA = 1

#: The resolution ladder (seconds per bucket), finest first.
RESOLUTIONS = (10, 60, 600)

#: Per-tier retention budget, in buckets (not seconds): the 10s tier
#: keeps ~1h, the 60s tier ~6h, the 600s tier ~2.5 days at the default.
RETENTION_BUCKETS = 360

#: Compaction hysteresis: rewrite a tier only when its line count
#: exceeds the retained-line estimate by this factor (an append-heavy
#: roller must not rewrite the file on every roll).
_COMPACT_SLACK = 2.0

#: Numeric top-level keys worth rolling from each heartbeat kind. The
#: windowed snapshot (``w``) is rolled wholesale (every numeric value).
_SERVE_KEYS = ("capacity_rps", "queued", "inflight", "shed", "rejected")
_ROUTER_KEYS = (
    "completed", "throughput_rps", "inflight", "shed", "rerouted",
    "transport_failures", "view_age_s", "router_overhead_ms",
)
_HB_KEYS = ("images_per_sec", "loss", "step")

#: Read-side instrumentation: bumped once per :func:`read_rollup` call.
#: The ops console's zero-raw-reparse proof asserts its renders move
#: THIS counter while the raw-stream readers stay untouched.
READS = {"read_rollup": 0}


def rollup_path(log_dir: str, res: int) -> str:
    return os.path.join(log_dir, "fleet", f"rollup_{int(res)}.jsonl")


def cursor_path(log_dir: str) -> str:
    return os.path.join(log_dir, "fleet", "rollup.cursor.json")


def metrics_from(record: dict) -> dict:
    """The rollable numeric metrics of one heartbeat record.

    ``kind=serve``: the windowed snapshot (``w.*`` flattened, e.g.
    ``p99_ms``/``throughput_rps``/``queue_depth_last`` ->
    ``queue_depth``) plus the capacity/queue counters.
    ``kind=router``: the same shape, ``router_``-prefixed.
    ``kind=hb`` (training): throughput/loss/step frontier.
    Unknown kinds roll nothing (forward-compat: a future stream kind
    must not crash an old roller).
    """
    kind = record.get("kind")
    out: dict = {}
    if kind == "serve" or kind == "router":
        prefix = "router_" if kind == "router" else ""
        w = record.get("w")
        if isinstance(w, dict):
            for key, value in w.items():
                if key == "window_s" or not isinstance(
                    value, (int, float)
                ) or isinstance(value, bool):
                    continue
                name = "queue_depth" if key == "queue_depth_last" else key
                out[prefix + name] = float(value)
        keys = _ROUTER_KEYS if kind == "router" else _SERVE_KEYS
        for key in keys:
            value = record.get(key)
            if isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                # No double prefix: router_overhead_ms stays itself.
                name = (
                    key if prefix and key.startswith(prefix)
                    else prefix + key
                )
                out[name] = float(value)
        slo = record.get("slo")
        if kind == "serve" and isinstance(slo, dict):
            burn = slo.get("burn_rate")
            if isinstance(burn, (int, float)):
                out["burn_rate"] = float(burn)
        # Prediction-quality snapshots roll under distinct prefixes so
        # the console/alert fold reads them from rollups alone
        # (docs/quality.md). Nested dicts (per-pair stats) stay in the
        # raw beats — rollups carry only the scalar headline.
        quality = record.get("quality")
        if kind == "serve" and isinstance(quality, dict):
            for key, value in quality.items():
                if isinstance(value, (int, float)) and not isinstance(
                    value, bool
                ):
                    out["quality_" + key] = float(value)
        shadow = record.get("shadow")
        if kind == "router" and isinstance(shadow, dict):
            for key, value in shadow.items():
                if isinstance(value, (int, float)) and not isinstance(
                    value, bool
                ):
                    out["router_shadow_" + key] = float(value)
    elif kind == "hb":
        for key in _HB_KEYS:
            value = record.get(key)
            if isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                out[key] = float(value)
    return out


def _percentile(ordered: list, q: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (the latency
    ledger's convention, inlined so rollups import nothing from serve)."""
    if len(ordered) == 1:
        return ordered[0]
    rank = q / 100.0 * (len(ordered) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(ordered) - 1)
    frac = rank - lo
    return ordered[lo] * (1.0 - frac) + ordered[hi] * frac


def _fold(values: list) -> dict:
    ordered = sorted(values)
    n = len(ordered)
    return {
        "n": n,
        "min": round(ordered[0], 6),
        "max": round(ordered[-1], 6),
        "mean": round(sum(ordered) / n, 6),
        "p50": round(_percentile(ordered, 50.0), 6),
        "p99": round(_percentile(ordered, 99.0), 6),
    }


class Roller:
    """Incremental roller over one log dir's heartbeat streams.

    ``roll_once()`` consumes the streams' new complete lines and
    appends every *closed* bucket (a bucket closes when its own stream's
    newest timestamp has moved past the bucket's end — per-stream
    watermarks, so a lagging replica cannot have its open bucket closed
    by a faster sibling's clock). ``flush()`` force-closes the pending
    buckets at end of run. Single-writer by contract (module docstring).
    """

    def __init__(
        self,
        log_dir: str,
        *,
        resolutions: tuple = RESOLUTIONS,
        retention_buckets: int = RETENTION_BUCKETS,
    ):
        self.log_dir = log_dir
        self.resolutions = tuple(int(r) for r in resolutions)
        self.retention_buckets = int(retention_buckets)
        self.bytes_read = 0
        self.buckets_closed = 0
        self.rolls = 0

    # ------------------------------------------------------------- cursor

    def _load_cursor(self) -> Optional[dict]:
        """The cursor doc, or None when a full rebuild is required
        (missing / torn / wrong schema)."""
        try:
            with open(cursor_path(self.log_dir)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None
        if not isinstance(doc, dict) or doc.get("v") != ROLLUP_SCHEMA:
            return None
        for key in ("streams", "pending", "lines"):
            if not isinstance(doc.get(key), dict):
                return None
        return doc

    def _save_cursor(self, doc: dict) -> None:
        path = cursor_path(self.log_dir)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            pass

    # ------------------------------------------------------------- streams

    def _streams(self) -> list:
        """``(name, proc, path)`` for every rollable stream on disk."""
        root = os.path.join(self.log_dir, "fleet")
        out = []
        if not os.path.isdir(root):
            return out
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if name.startswith("proc_") and name.endswith(".jsonl"):
                try:
                    proc = int(name[len("proc_"):-len(".jsonl")])
                except ValueError:
                    continue
                out.append((name, proc, path))
            elif name == "router.jsonl":
                out.append((name, "router", path))
        return out

    def _read_new(self, path: str, offset: int) -> tuple:
        """``(records, new_offset, stale)``: the complete JSON lines
        past ``offset``. ``stale`` flags a truncated stream (size below
        the cursor's offset) — the caller rebuilds."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return [], offset, False
        if size < offset:
            return [], offset, True
        if size == offset:
            return [], offset, False
        records = []
        consumed = offset
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read(size - offset)
        except OSError:
            return [], offset, False
        self.bytes_read += len(data)
        end = data.rfind(b"\n")
        if end < 0:
            return [], offset, False  # torn tail only: consume nothing
        for raw in data[: end + 1].split(b"\n"):
            line = raw.decode("utf-8", "replace").strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn/glued line (SIGKILLed writer restart)
            if isinstance(doc, dict):
                records.append(doc)
        consumed = offset + end + 1
        return records, consumed, False

    # ------------------------------------------------------------- rolling

    def roll_once(self) -> dict:
        """One incremental pass; returns :meth:`stats`. Never raises on
        stream I/O (telemetry must not take its owner down)."""
        cursor = self._load_cursor()
        rebuild = cursor is None
        if cursor is None:
            cursor = {
                "v": ROLLUP_SCHEMA, "streams": {}, "pending": {},
                "lines": {},
            }
        closed: dict = {}
        for name, proc, path in self._streams():
            state = cursor["streams"].get(name) or {"offset": 0}
            records, offset, stale = self._read_new(
                path, int(state.get("offset", 0))
            )
            if stale:
                # Truncated stream: one stream lying about its past
                # invalidates every tier it fed.
                return self._rebuild()
            watermark = float(state.get("watermark", 0.0))
            pending = cursor["pending"]
            for record in records:
                t = record.get("t")
                if not isinstance(t, (int, float)):
                    continue
                watermark = max(watermark, float(t))
                metrics = metrics_from(record)
                for res in self.resolutions:
                    bucket = int(t // res) * res
                    for metric, value in metrics.items():
                        key = f"{res}|{name}|{metric}|{bucket}"
                        entry = pending.get(key)
                        if entry is None:
                            entry = {
                                "res": res, "proc": proc,
                                "metric": metric, "bucket": bucket,
                                "vals": [],
                            }
                            pending[key] = entry
                        entry["vals"].append(value)
            # Close this stream's buckets its own clock has passed.
            for key in list(cursor["pending"]):
                entry = cursor["pending"][key]
                res_s, stream_name, _, _ = key.split("|", 3)
                if stream_name != name:
                    continue
                if watermark >= entry["bucket"] + entry["res"]:
                    closed.setdefault(entry["res"], []).append(
                        cursor["pending"].pop(key)
                    )
            cursor["streams"][name] = {
                "offset": offset, "watermark": watermark,
            }
        self._append_closed(cursor, closed)
        if rebuild:
            # A fresh cursor over possibly pre-existing rollup files:
            # rewrite the tiers so replayed history cannot double-count.
            return self._rebuild_from(cursor, closed)
        self._compact(cursor)
        self._save_cursor(cursor)
        self.rolls += 1
        return self.stats()

    def flush(self) -> dict:
        """Force-close every pending bucket (end of run: the streams
        are final, nothing more is coming). Appends + cursor like
        :meth:`roll_once`."""
        cursor = self._load_cursor()
        if cursor is None:
            self.roll_once()
            cursor = self._load_cursor()
            if cursor is None:
                return self.stats()
        closed: dict = {}
        for key in list(cursor["pending"]):
            entry = cursor["pending"].pop(key)
            closed.setdefault(entry["res"], []).append(entry)
        self._append_closed(cursor, closed)
        self._compact(cursor)
        self._save_cursor(cursor)
        return self.stats()

    def _append_closed(self, cursor: dict, closed: dict) -> None:
        for res, entries in sorted(closed.items()):
            path = rollup_path(self.log_dir, res)
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "a") as f:
                    for entry in sorted(
                        entries,
                        key=lambda e: (e["bucket"], str(e["proc"])),
                    ):
                        f.write(json.dumps(self._line(entry)) + "\n")
                    f.flush()
            except OSError:
                continue
            self.buckets_closed += len(entries)
            cursor["lines"][str(res)] = (
                int(cursor["lines"].get(str(res), 0)) + len(entries)
            )

    def _line(self, entry: dict) -> dict:
        line = {
            "v": ROLLUP_SCHEMA,
            "res": entry["res"],
            "bucket": entry["bucket"],
            "proc": entry["proc"],
            "metric": entry["metric"],
        }
        line.update(_fold(entry["vals"]))
        return line

    # ------------------------------------------------------ rebuild/compact

    def _rebuild(self) -> dict:
        """Full re-roll after a truncation: drop the cursor and take
        roll_once's rebuild branch (read from byte 0, rewrite tiers).
        No recursion risk: a fresh cursor's offsets are 0, so the stale
        check cannot re-trigger."""
        try:
            os.remove(cursor_path(self.log_dir))
        except OSError:
            pass
        return self.roll_once()

    def _rebuild_from(self, cursor: dict, closed: dict) -> dict:
        """Atomic tier rewrite from one full pass's closed buckets
        (``_append_closed`` already wrote them; rewrite = dedup +
        drop pre-crash lines that the replayed pass did not produce)."""
        for res in self.resolutions:
            path = rollup_path(self.log_dir, res)
            entries = closed.get(res, [])
            lines = [self._line(e) for e in sorted(
                entries, key=lambda e: (e["bucket"], str(e["proc"]))
            )]
            try:
                os.makedirs(os.path.dirname(path), exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    for line in lines:
                        f.write(json.dumps(line) + "\n")
                os.replace(tmp, path)
            except OSError:
                continue
            cursor["lines"][str(res)] = len(lines)
        self._compact(cursor)
        self._save_cursor(cursor)
        self.rolls += 1
        return self.stats()

    def _compact(self, cursor: dict) -> None:
        """Bound each tier to the retention budget (newest buckets win).
        Rewrites only past the hysteresis factor — appends stay cheap."""
        for res in self.resolutions:
            path = rollup_path(self.log_dir, res)
            count = int(cursor["lines"].get(str(res), 0))
            # Budget in LINES: retention_buckets buckets x however many
            # (proc, metric) series exist; estimate from the live file
            # only when the raw line count crosses the slack threshold.
            if count <= self.retention_buckets * _COMPACT_SLACK:
                continue
            lines = read_rollup(self.log_dir, res)
            if not lines:
                cursor["lines"][str(res)] = 0
                continue
            newest = max(line["bucket"] for line in lines)
            horizon = newest - self.retention_buckets * res
            kept = [line for line in lines if line["bucket"] >= horizon]
            series = {
                (line["proc"], line["metric"]) for line in kept
            }
            budget = self.retention_buckets * max(len(series), 1)
            if len(kept) > budget:
                kept.sort(key=lambda e: e["bucket"])
                kept = kept[-budget:]
            try:
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    for line in kept:
                        f.write(json.dumps(line) + "\n")
                os.replace(tmp, path)
            except OSError:
                continue
            cursor["lines"][str(res)] = len(kept)

    def stats(self) -> dict:
        return {
            "bytes_read": self.bytes_read,
            "buckets_closed": self.buckets_closed,
            "rolls": self.rolls,
        }


def roll(log_dir: str, *, flush: bool = False) -> dict:
    """One-shot convenience: roll a log dir's new bytes (and optionally
    force-close the pending tail buckets). Returns the roller stats."""
    roller = Roller(log_dir)
    stats = roller.roll_once()
    if flush:
        stats = roller.flush()
    return stats


# ---------------------------------------------------------------- readers


def read_rollup(
    log_dir: str,
    res: int,
    *,
    metric: Optional[str] = None,
    proc=None,
) -> list:
    """One tier's deduplicated bucket lines, sorted by bucket.

    Replayed appends (a roller crash between append and cursor write)
    produce duplicate ``(bucket, proc, metric)`` lines; the NEWEST line
    wins. Torn tails and unknown-version lines are skipped (readers
    tolerate future rollers). ``metric``/``proc`` filter the result.
    """
    READS["read_rollup"] += 1
    path = rollup_path(log_dir, res)
    dedup: dict = {}
    try:
        with open(path, "rb") as f:
            for raw in f:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed roller
                if not isinstance(doc, dict):
                    continue
                key = (doc.get("bucket"), str(doc.get("proc")),
                       doc.get("metric"))
                if None in key:
                    continue
                dedup[key] = doc
    except OSError:
        return []
    out = [
        doc for doc in dedup.values()
        if (metric is None or doc.get("metric") == metric)
        and (proc is None or str(doc.get("proc")) == str(proc))
    ]
    out.sort(key=lambda e: (e["bucket"], str(e["proc"]), e["metric"]))
    return out


def finest_rollup(log_dir: str) -> tuple:
    """``(res, lines)`` for the finest tier with data (the console's
    default view), or ``(None, [])`` when nothing has been rolled."""
    for res in RESOLUTIONS:
        lines = read_rollup(log_dir, res)
        if lines:
            return res, lines
    return None, []


def series(lines: list, metric: str, *, proc=None) -> list:
    """``[(bucket, value)]`` for one metric: per-bucket mean, summed
    across procs by default (fleet view), filtered to one proc when
    given. The fleet-capacity/projected-load folds read THIS."""
    per_bucket: dict = {}
    for line in lines:
        if line.get("metric") != metric:
            continue
        if proc is not None and str(line.get("proc")) != str(proc):
            continue
        mean = line.get("mean")
        if not isinstance(mean, (int, float)):
            continue
        per_bucket[line["bucket"]] = (
            per_bucket.get(line["bucket"], 0.0) + float(mean)
        )
    return sorted(per_bucket.items())


# ----------------------------------------------------------- projections


def robust_slope(points: list) -> Optional[float]:
    """Theil–Sen slope (median of pairwise slopes) over ``[(t, v)]`` —
    one straggling bucket cannot bend the projection the way a
    least-squares fit would. None below 2 distinct timestamps. Pairs
    are capped (stride sampling) so a long series stays cheap."""
    pts = sorted(
        (float(t), float(v)) for t, v in points
        if isinstance(t, (int, float)) and isinstance(v, (int, float))
    )
    if len(pts) > 60:
        stride = -(-len(pts) // 60)
        pts = pts[::stride] + pts[-1:]
    slopes = []
    for i in range(len(pts)):
        for j in range(i + 1, len(pts)):
            dt = pts[j][0] - pts[i][0]
            if dt > 0:
                slopes.append((pts[j][1] - pts[i][1]) / dt)
    if not slopes:
        return None
    slopes.sort()
    n = len(slopes)
    mid = slopes[n // 2]
    return mid if n % 2 else 0.5 * (slopes[n // 2 - 1] + mid)


def project_load(points: list, *, horizon_s: float = 60.0) -> Optional[dict]:
    """Projected fleet load ``horizon_s`` ahead of the newest bucket:
    newest value + robust slope x horizon, floored at 0 (a draining
    fleet projects to idle, not to negative traffic). None without at
    least one point; slope None (single bucket) projects flat."""
    pts = [
        (float(t), float(v)) for t, v in points
        if isinstance(t, (int, float)) and isinstance(v, (int, float))
    ]
    if not pts:
        return None
    pts.sort()
    last_t, last_v = pts[-1]
    slope = robust_slope(pts)
    projected = last_v + (slope or 0.0) * float(horizon_s)
    return {
        "now_rps": round(last_v, 3),
        "slope_rps_per_s": round(slope, 6) if slope is not None else None,
        "horizon_s": float(horizon_s),
        "projected_rps": round(max(projected, 0.0), 3),
    }
