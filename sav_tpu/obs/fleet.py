"""Fleet telemetry — cross-process heartbeats, skew, and straggler attribution.

Every obs capability before this module (goodput ledger, manifests,
flight recorder, sentinel) is single-process, but the failures that
actually killed runs were fleet-shaped: two of five bench rounds died
``backend_unreachable`` with no per-process evidence of *which* host went
dark or when, and multi-host runs emit warnings nobody aggregates. This
module is the substrate MegaScale-style straggler diagnosis and
PaLM-style goodput accounting presuppose: each process writes an
append-only heartbeat stream, and an aggregator (process 0 in-run, or
any laptop offline) turns the streams into step skew, a per-process
straggler ranking, and missing-heartbeat dead-host suspicion.

Artifact layout (everything under ``<log_dir>/fleet/``)::

    fleet/proc_<i>.jsonl       one JSON line per heartbeat (per process)
    fleet/fleet.json           merged fleet manifest (process 0, atomic)
    fleet/backend_probe.jsonl  startup probe timeline (bench.py give-up)

Heartbeat discipline — the same contract savlint SAV111 enforces for the
flight recorder, here enforced as SAV112: the per-beat path
(:meth:`HeartbeatWriter.beat`) adds **no device syncs**. Every value a
heartbeat carries is already host-side at the trainer's log boundary —
the goodput ledger's wall-clock buckets, the metrics dict fit() already
``device_get``'d, the recorder's last incident pointer. The cost is one
small buffered+flushed file append per logging window, accounted in the
``fleet/write_s`` gauge so the <1% overhead contract is assertable.

Why the ledger *buckets* ride every heartbeat: in a collective
(multi-host SPMD) run the processes step in lockstep, so a straggling
host does not show up as a slow *step* on its own clock — it shows up as
``input_wait``/host time on the straggler and as ``step`` (blocked in
the all-reduce) on every victim. The aggregator therefore ranks
stragglers on the **host-stall share** (Δ(input_wait+h2d+stall)/Δwall)
first and on raw per-step wall time second, each scored against a
leave-one-out median+MAD baseline (the regression sentinel's machinery,
tools/regression_sentinel.py) so one bad process cannot poison its own
baseline. A collective hang is then attributed to the process that
stalled *before* the all-reduce instead of reported as a symmetric
timeout.

Stdlib-only (no jax import): readers must work on rsynced logs from a
laptop, and the writer must work in the backend-unreachable path where
importing jax is exactly what hangs.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Callable, Optional

FLEET_SCHEMA = 1

# Forward-compat version stamp (ISSUE 19): every heartbeat line carries
# ``schema_version`` alongside the frozen legacy ``schema`` field, and
# the readers in this module tolerate unknown versions and unknown keys
# (they filter on ``kind`` only, never on version) — so rollup-era and
# PR-7-era streams coexist in one log dir, and a FUTURE writer's lines
# still aggregate on today's readers. Bump when a line's meaning (not
# just its key set) changes.
FLEET_SCHEMA_VERSION = 2

# Ledger buckets carried by each heartbeat (a subset of goodput.BUCKETS;
# inlined so this module stays importable without sav_tpu.obs.goodput in
# odd partial-rsync situations — the names are a stable contract).
HEARTBEAT_BUCKETS = (
    "compile", "step", "input_wait", "h2d", "eval", "checkpoint", "stall",
)

# Host-stall buckets: wall time the *host* spent not feeding the device.
# In a lockstep collective run this is what distinguishes the straggler
# (who stalls before the all-reduce) from its victims (whose time lands
# in 'step', blocked inside it).
HOST_STALL_BUCKETS = ("input_wait", "h2d", "stall")

# Robust-statistics constants shared with tools/regression_sentinel.py
# (duplicated by value: fleet reading must stay importable stdlib-only).
MAD_SCALE = 1.4826


def fleet_dir(log_dir: str) -> str:
    return os.path.join(log_dir, "fleet")


def resolve_identity(
    default_index: int = 0, default_count: int = 1
) -> tuple[int, int]:
    """(process index, process count) for fleet telemetry.

    Defaults to the caller's view (the trainer passes
    ``jax.process_index()/process_count()``), overridable via
    ``SAV_FLEET_PROC`` / ``SAV_FLEET_PROCS`` for fleets that are NOT
    coordinated through ``jax.distributed`` — independent workers
    sharing a log dir (parameter sweeps, the two-process smoke on CPU
    backends without multiprocess computation support, supervisor-
    restarted ranks). Malformed overrides fall back to the defaults:
    identity resolution must never take a run down.
    """
    try:
        index = int(os.environ.get("SAV_FLEET_PROC", default_index))
        count = int(os.environ.get("SAV_FLEET_PROCS", default_count))
    except ValueError:
        return default_index, default_count
    if index < 0 or count < 1:
        return default_index, default_count
    return index, max(count, index + 1)


def heartbeat_path(log_dir: str, process_index: int) -> str:
    return os.path.join(fleet_dir(log_dir), f"proc_{process_index}.jsonl")


class HeartbeatWriter:
    """Append-only per-process heartbeat stream.

    One writer per process, file ``fleet/proc_<i>.jsonl`` — processes
    never share a file, so multi-host runs need no cross-process locking
    (the same shared-log-dir discipline as the manifest/goodput writers,
    minus the process-0-only restriction: heartbeats are per-process *by
    design*). Each :meth:`beat` appends one JSON line and flushes, so a
    watchdog ``os._exit`` or SIGKILL loses at most the in-flight line
    (readers skip torn tails). The per-beat path is host-only — savlint
    SAV112 statically enforces it, and the ``write_s``/``beats`` gauges
    feed the tier-1 <1% overhead guard.
    """

    # Bound on any lock wait (seconds): telemetry drops, never blocks.
    LOCK_TIMEOUT_S = 1.0

    def __init__(
        self,
        log_dir: str,
        *,
        process_index: int = 0,
        process_count: int = 1,
        clock: Callable[[], float] = time.time,
        perf: Callable[[], float] = time.perf_counter,
        stream: Optional[str] = None,
    ):
        self.log_dir = log_dir
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        # ``stream`` writes a NON-process stream (``fleet/<stream>.jsonl``
        # — e.g. the fleet router's ``router`` stream, ISSUE 16) instead
        # of ``proc_<i>.jsonl``. read_heartbeats globs only proc_* so a
        # named stream can never collide with the replica aggregation.
        self.path = (
            os.path.join(fleet_dir(log_dir), f"{stream}.jsonl")
            if stream else heartbeat_path(log_dir, self.process_index)
        )
        self._clock = clock
        self._perf = perf
        # Training thread (beat/close) vs watchdog-side events share the
        # file; acquisition is BOUNDED (LOCK_TIMEOUT_S) everywhere: the
        # watchdog's soft stage deliberately abandons a dump thread that
        # wedges on a hung log-dir filesystem, and an abandoned writer
        # stuck inside this lock must not deadlock the training thread's
        # next beat — a recovered stall would then be converted into a
        # hard watchdog abort by its own telemetry. A timed-out record
        # is dropped and counted (``dropped`` stat), never waited for.
        self._lock = threading.Lock()
        self._dropped = 0
        self._file = None
        # Eager open: directory creation + file open are one-time setup
        # paid at construction (before the train loop), so the per-beat
        # write_s gauge measures only the steady-state append+flush —
        # that is what the <1%-of-step-time contract bounds. _append
        # retries lazily if this failed (degraded FS ≠ dead telemetry).
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._file = open(self.path, "a")
        except OSError:
            pass
        self._beats = 0
        self._events = 0
        self._write_s = 0.0
        self._closed = False
        self.last_step: Optional[int] = None
        self._host = socket.gethostname()
        self._pid = os.getpid()

    # ------------------------------------------------------------- recording

    def _append(self, record: dict) -> None:
        """One line out; open lazily, flush eagerly, never raise
        (telemetry must not take the run down)."""
        try:
            if self._file is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._file = open(self.path, "a")
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        except OSError:
            pass

    def beat(
        self,
        step: int,
        *,
        ledger=None,
        metrics: Optional[dict] = None,
        incident: Optional[str] = None,
    ) -> None:
        """Append one heartbeat at the trainer's log boundary.

        ``ledger``: the fit's GoodputLedger — wall-clock aggregates, all
        host-side. ``metrics``: the already-``device_get``'d log-window
        dict (host floats by contract); a small subset rides along.
        ``incident``: last flight-recorder bundle path, if any. No value
        touched here is a device array (SAV112).
        """
        t0 = self._perf()
        record: dict = {
            "schema": FLEET_SCHEMA,
            "schema_version": FLEET_SCHEMA_VERSION,
            "kind": "hb",
            "proc": self.process_index,
            "procs": self.process_count,
            "step": int(step),
            "t": round(float(self._clock()), 3),
            "host": self._host,
            "pid": self._pid,
        }
        if ledger is not None:
            record["wall_s"] = round(ledger.wall_s, 4)
            record["steps"] = ledger.steps
            record["b"] = {
                name: round(ledger.bucket_seconds(name), 4)
                for name in HEARTBEAT_BUCKETS
            }
            record["anomalies"] = len(ledger.anomalies)
        if metrics:
            loss = metrics.get("loss")
            if isinstance(loss, (int, float)):
                record["loss"] = round(float(loss), 6)
            rate = metrics.get("images_per_sec")
            if isinstance(rate, (int, float)):
                record["images_per_sec"] = round(float(rate), 1)
            retraces = metrics.get("retraces")
            if isinstance(retraces, (int, float)):
                record["retraces"] = int(retraces)
            hbm = metrics.get("hbm_bytes_in_use")
            if isinstance(hbm, (int, float)):
                record["hbm_bytes_in_use"] = float(hbm)
            hbm_peak = metrics.get("hbm_peak_bytes")
            if isinstance(hbm_peak, (int, float)):
                record["hbm_peak_bytes"] = float(hbm_peak)
        if incident:
            record["incident"] = incident
        if not self._lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            self._dropped += 1  # a wedged writer must not block training
            return
        try:
            if self._closed:
                return
            self._append(record)
            self._beats += 1
            self.last_step = int(step)
            self._write_s += self._perf() - t0
        finally:
            self._lock.release()

    def serve_beat(self, payload: dict, *, kind: str = "serve") -> bool:
        """Append one ``kind=serve`` heartbeat line (the serving
        engine's time-cadenced stream, sav_tpu/serve/telemetry.py —
        serving has no step boundary, so these carry a windowed
        metrics snapshot instead of a step number). Host-only like
        ``beat()`` (savlint SAV116 owns the serve-telemetry callers);
        same bounded-lock discipline — a wedged writer drops the beat,
        never blocks serving. Returns True iff the line was appended,
        so callers' beat counters match the lines actually on disk
        (a dropped or post-close beat must not inflate them).
        ``kind`` widens the stream vocabulary: the fleet router beats
        with ``kind="router"`` on its own ``fleet/router.jsonl`` stream
        (ISSUE 16) through this same bounded-lock body."""
        t0 = self._perf()
        record: dict = {
            "schema": FLEET_SCHEMA,
            "schema_version": FLEET_SCHEMA_VERSION,
            "kind": kind,
            "proc": self.process_index,
            "procs": self.process_count,
            "t": round(float(self._clock()), 3),
            "host": self._host,
            "pid": self._pid,
        }
        record.update(payload)
        if not self._lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            self._dropped += 1
            return False
        try:
            if self._closed:
                return False
            self._append(record)
            self._beats += 1
            self._write_s += self._perf() - t0
            return True
        finally:
            self._lock.release()

    def fleet_event(self, event: str, **fields) -> None:
        """Append an out-of-band event line (watchdog soft stage, probe
        outcomes). Callable from any thread; host-only like beat()."""
        t0 = self._perf()
        record = {
            "schema": FLEET_SCHEMA,
            "schema_version": FLEET_SCHEMA_VERSION,
            "kind": "event",
            "event": event,
            "proc": self.process_index,
            "step": self.last_step,
            "t": round(float(self._clock()), 3),
        }
        record.update(fields)
        if not self._lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            self._dropped += 1
            return
        try:
            if self._closed:
                return
            self._append(record)
            self._events += 1
            self._write_s += self._perf() - t0
        finally:
            self._lock.release()

    def close(self, outcome: str = "ok") -> None:
        """Final record + file close. A process that never reaches this
        (killed, wedged) is exactly what the aggregator's
        missing-heartbeat suspicion exists to notice."""
        if not self._lock.acquire(timeout=self.LOCK_TIMEOUT_S):
            self._dropped += 1  # wedged writer: the daemon file handle
            return              # dies with the process; no final record
        try:
            if self._closed:
                return
            self._append({
                "schema": FLEET_SCHEMA,
                "schema_version": FLEET_SCHEMA_VERSION,
                "kind": "final",
                "proc": self.process_index,
                "step": self.last_step,
                "outcome": outcome,
                "t": round(float(self._clock()), 3),
            })
            self._closed = True
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
        finally:
            self._lock.release()

    def stats(self) -> dict[str, float]:
        """Gauge view for the goodput ledger (``fleet/*``)."""
        # Lock-free snapshot: each counter read is GIL-atomic, and a
        # slightly torn multi-counter view is fine for gauges.
        return {
            "beats": float(self._beats),
            "events": float(self._events),
            "write_s": self._write_s,
            "dropped": float(self._dropped),
        }


def write_probe_timeline(
    log_dir: str, probe_log: list, *, deadline_s: float, tag: str
) -> Optional[str]:
    """Write the backend-probe timeline into ``fleet/backend_probe.jsonl``.

    The give-up path's post-mortem contract: the manifest says the run
    never started (``outcome: backend_unreachable``), and the fleet dir
    holds the per-probe timeline in the SAME artifact layout heartbeats
    use — so "backend never came up" (probe lines, no ``proc_*.jsonl``)
    and "backend died mid-run" (heartbeats that stop) are distinguishable
    from one directory. Never raises; returns the path or None.
    """
    path = os.path.join(fleet_dir(log_dir), "backend_probe.jsonl")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            now = round(time.time(), 3)
            for probe in probe_log:
                record = {
                    "schema": FLEET_SCHEMA,
                    "kind": "probe",
                    "tag": tag,
                    "t": now,
                }
                record.update(probe)
                f.write(json.dumps(record) + "\n")
            f.write(json.dumps({
                "schema": FLEET_SCHEMA,
                "kind": "probe_giveup",
                "tag": tag,
                "deadline_s": deadline_s,
                "attempts": len(probe_log),
                "t": now,
            }) + "\n")
        return path
    except OSError:
        return None


# ------------------------------------------------------------- aggregation


def read_heartbeats(
    log_dir: str, *, tail_bytes: Optional[int] = None
) -> dict[int, list[dict]]:
    """Load every ``fleet/proc_*.jsonl`` stream; torn tail lines (a killed
    writer) are skipped, like metrics.jsonl readers do.

    ``tail_bytes`` bounds the read to each file's trailing bytes — the
    LIVE consumers' mode (the serve fleet router refreshes its view up
    to every half second, and re-parsing a long run's full history on
    each refresh would grow routing cost without bound). The partial
    first line of a mid-file seek is dropped by the same torn-line
    discipline. ``None`` (offline default) reads everything.
    """
    root = fleet_dir(log_dir)
    out: dict[int, list[dict]] = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if not (name.startswith("proc_") and name.endswith(".jsonl")):
            continue
        try:
            proc = int(name[len("proc_"):-len(".jsonl")])
        except ValueError:
            continue
        records = []
        try:
            with open(os.path.join(root, name), "rb") as f:
                if tail_bytes is not None:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    start = max(size - int(tail_bytes), 0)
                    f.seek(start)
                    if start > 0:
                        f.readline()  # drop the partial first line
                for raw in f:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail of a killed process
        except OSError:
            continue
        out[proc] = records
    return out


def read_router_beats(
    log_dir: str, *, tail_bytes: Optional[int] = None
) -> list[dict]:
    """Load the fleet router's ``fleet/router.jsonl`` heartbeat stream
    (``kind=router`` lines, ISSUE 16) with the same torn-line and
    tail-bound discipline as :func:`read_heartbeats`. The router is one
    process per fleet, so this returns a flat list, newest last."""
    path = os.path.join(fleet_dir(log_dir), "router.jsonl")
    records: list[dict] = []
    try:
        with open(path, "rb") as f:
            if tail_bytes is not None:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                start = max(size - int(tail_bytes), 0)
                f.seek(start)
                if start > 0:
                    f.readline()  # drop the partial first line
            for raw in f:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed router
                if isinstance(doc, dict) and doc.get("kind") == "router":
                    records.append(doc)
    except OSError:
        pass
    return records


def read_probe_timeline(log_dir: str) -> list[dict]:
    path = os.path.join(fleet_dir(log_dir), "backend_probe.jsonl")
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return records


def iter_manifests(log_dir: str):
    """Yield ``(path, doc)`` for every parseable ``manifest*.json``
    directly under ``log_dir`` (sorted by name; torn/unreadable/non-dict
    files skipped) — the ONE manifest-discovery loop behind the offline
    readers (``read_autoprof_captures``, serve telemetry's
    ``find_serve_manifests``)."""
    import glob as _glob

    for path in sorted(
        _glob.glob(os.path.join(log_dir, "manifest*.json"))
    ):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(doc, dict):
            yield path, doc


def read_autoprof_captures(log_dir: str) -> list:
    """Anomaly-profiler capture records for a log dir: every manifest's
    ``notes.autoprof`` (training runs stamp ``manifest.json``, serve
    runs ``manifest*-serve-*.json``) merged with every process's
    sidecar (``autoprof/proc*_captures.jsonl`` — non-zero processes run
    with a disabled manifest, so the straggler's own trace only exists
    in its sidecar). Deduplicated by trace path. The ONE reader behind
    ``fleet_status``/``serve_status`` — stdlib-only, laptop-safe."""
    import glob as _glob

    captures: list = []
    for _, doc in iter_manifests(log_dir):
        noted = (doc.get("notes") or {}).get("autoprof")
        if isinstance(noted, list):
            captures.extend(c for c in noted if isinstance(c, dict))
    for sidecar in sorted(
        _glob.glob(os.path.join(log_dir, "autoprof", "proc*_captures.jsonl"))
    ):
        try:
            with open(sidecar) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        captures.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    seen: set = set()
    unique = []
    for c in captures:
        key = c.get("path")
        if key is not None:
            if key in seen:
                continue
            seen.add(key)
        unique.append(c)
    return unique


def format_unix(t) -> str:
    """``HH:MM:SS`` for a unix stamp, ``?`` on anything else — the
    offline renderers' shared time formatter."""
    if not isinstance(t, (int, float)):
        return "?"
    import datetime

    return datetime.datetime.fromtimestamp(t).strftime("%H:%M:%S")


def _median(values: list) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    n = len(ordered)
    mid = ordered[n // 2]
    return mid if n % 2 else 0.5 * (ordered[n // 2 - 1] + mid)


def _mad(values: list, med: float) -> float:
    return _median([abs(v - med) for v in values]) or 0.0


def _intervals(beats: list[dict]) -> list[dict]:
    """Per consecutive-heartbeat deltas for one process: wall seconds,
    steps advanced, and the host-stall share of the interval."""
    out = []
    for prev, cur in zip(beats, beats[1:]):
        dt = float(cur.get("t", 0.0)) - float(prev.get("t", 0.0))
        dsteps = int(cur.get("step", 0)) - int(prev.get("step", 0))
        if dt <= 0 or dsteps <= 0:
            continue
        interval = {
            "dt": dt,
            "dsteps": dsteps,
            "per_step_s": dt / dsteps,
        }
        pb, cb = prev.get("b"), cur.get("b")
        if isinstance(pb, dict) and isinstance(cb, dict):
            stall = sum(
                float(cb.get(k, 0.0)) - float(pb.get(k, 0.0))
                for k in HOST_STALL_BUCKETS
            )
            interval["host_stall_frac"] = max(min(stall / dt, 1.0), 0.0)
        out.append(interval)
    return out


_UNSET = object()


def silence_suspects(
    beat_times: dict[int, list],
    finals: dict[int, bool],
    *,
    now: float,
    suspect_factor: float = 3.0,
    median_interval=_UNSET,
) -> list[dict]:
    """Missing-heartbeat dead-host suspicion, shared by the training
    aggregator (:func:`aggregate_fleet`), the serving aggregator
    (:func:`sav_tpu.serve.telemetry.aggregate_serve`) and the fleet
    router's live view: a process silent for more than
    ``suspect_factor`` x the fleet's median beat interval, without a
    final record, likely went dark — "replica 1 stopped heartbeating",
    not a symmetric timeout. One implementation so the router routes on
    EXACTLY the flag the offline tools render.

    ``beat_times``: per-process heartbeat unix stamps (ascending).
    ``finals``: per-process "a final record exists" (an orderly close is
    not a death). ``median_interval`` overrides the fleet-median
    computed from ``beat_times`` — a caller that PASSES it owns the
    baseline outright, including passing None for "no valid baseline
    yet, flag nothing" (aggregate_fleet passes the median of its
    step-filtered intervals: beats that advanced no step, e.g. through
    a long first compile, carry no interval signal and must not
    manufacture suspicion). Returns ``[{proc, last_unix, silent_s,
    median_interval_s}]``, empty when no interval baseline exists yet.
    """
    med = median_interval
    if med is _UNSET:
        intervals = [
            b - a
            for times in beat_times.values()
            for a, b in zip(times, times[1:])
            if b > a
        ]
        med = _median(intervals)
    if not med:
        return []
    suspects = []
    for proc, times in sorted(beat_times.items()):
        if not times or finals.get(proc):
            continue
        silent = float(now) - float(times[-1])
        if silent > suspect_factor * med:
            suspects.append({
                "proc": proc,
                "last_unix": times[-1],
                "silent_s": round(silent, 3),
                "median_interval_s": round(med, 3),
            })
    return suspects


def _loo_scores(
    per_proc: dict[int, float], *, k: float, rel_floor: float
) -> dict[int, dict]:
    """Leave-one-out median+MAD score per process.

    For each process, the baseline is every OTHER process's value —
    the sentinel's robust-outlier machinery applied across the fleet, so
    the straggler's own slowness cannot inflate the threshold it is
    judged against. ``score`` is deviations-above-baseline in threshold
    units; ``flagged`` when score > 1 (i.e. beyond
    ``median + max(k·1.4826·MAD, rel_floor·|median|)``).
    """
    out: dict[int, dict] = {}
    for proc, value in per_proc.items():
        baseline = [v for p, v in per_proc.items() if p != proc]
        if not baseline:
            out[proc] = {"value": value, "score": 0.0, "flagged": False}
            continue
        med = _median(baseline)
        mad = _mad(baseline, med)
        threshold = max(
            k * MAD_SCALE * mad, rel_floor * abs(med), 1e-9
        )
        score = (value - med) / threshold
        out[proc] = {
            "value": value,
            "baseline_median": med,
            "baseline_mad": mad,
            "threshold": threshold,
            "score": round(score, 3),
            "flagged": score > 1.0,
        }
    return out


def aggregate_fleet(
    log_dir: str,
    *,
    straggler_k: float = 3.5,
    rel_floor: float = 0.25,
    suspect_factor: float = 3.0,
    now: Optional[float] = None,
    max_timeline: int = 200,
) -> dict:
    """Fold the per-process heartbeat streams into one fleet summary.

    Runs anywhere (stdlib-only): process 0 calls it at the end of fit(),
    ``tools/fleet_status.py`` / ``run_report.py --fleet`` recompute it
    offline over rsynced logs. ``now`` defaults to the newest heartbeat
    across the fleet (offline semantics — wall clock would flag every
    process of a finished run as silent).

    Summary keys: ``processes`` (per-process view), ``step_skew``,
    ``skew_timeline``, ``straggler`` (leave-one-out MAD ranking on
    host-stall share and per-step wall time), ``suspects``
    (missing-heartbeat dead-host suspicion), ``events``.
    """
    streams = read_heartbeats(log_dir)
    summary: dict = {
        "schema": FLEET_SCHEMA,
        "log_dir": log_dir,
        "processes": {},
        "events": [],
    }
    if not streams:
        return summary
    beats: dict[int, list[dict]] = {}
    for proc, records in streams.items():
        beats[proc] = [r for r in records if r.get("kind") == "hb"]
        for r in records:
            if r.get("kind") == "event":
                summary["events"].append(r)
    finals = {
        proc: next(
            (r for r in reversed(records) if r.get("kind") == "final"), None
        )
        for proc, records in streams.items()
    }
    latest = 0.0
    intervals: dict[int, list[dict]] = {}
    for proc, hb in beats.items():
        final = finals.get(proc)
        last = hb[-1] if hb else None
        intervals[proc] = _intervals(hb)
        per_step = [i["per_step_s"] for i in intervals[proc]]
        stalls = [
            i["host_stall_frac"] for i in intervals[proc]
            if "host_stall_frac" in i
        ]
        view = {
            "heartbeats": len(hb),
            "first_step": hb[0].get("step") if hb else None,
            "last_step": last.get("step") if last else None,
            "last_unix": last.get("t") if last else None,
            "host": last.get("host") if last else None,
            "median_step_s": (
                round(_median(per_step), 6) if per_step else None
            ),
            "median_host_stall_frac": (
                round(_median(stalls), 4) if stalls else None
            ),
            "anomalies": last.get("anomalies") if last else None,
            "incident": next(
                (r["incident"] for r in reversed(hb) if r.get("incident")),
                None,
            ),
            "final": bool(final),
            "outcome": final.get("outcome") if final else None,
        }
        summary["processes"][str(proc)] = view
        for r in hb + ([final] if final else []):
            latest = max(latest, float(r.get("t", 0.0)))
    now = latest if now is None else float(now)

    # Step skew: how far apart the processes' frontiers are.
    frontiers = {
        proc: hb[-1].get("step") for proc, hb in beats.items() if hb
    }
    if frontiers:
        lo_proc = min(frontiers, key=lambda p: frontiers[p])
        hi_proc = max(frontiers, key=lambda p: frontiers[p])
        summary["step_skew"] = {
            "min_step": frontiers[lo_proc],
            "max_step": frontiers[hi_proc],
            "skew": frontiers[hi_proc] - frontiers[lo_proc],
            "laggard": lo_proc,
        }

    # Skew timeline: the merged (t, proc, step) trail, downsampled.
    trail = sorted(
        (
            {"t": r.get("t"), "proc": proc, "step": r.get("step")}
            for proc, hb in beats.items() for r in hb
        ),
        key=lambda e: (e["t"], e["proc"]),
    )
    if len(trail) > max_timeline:
        stride = -(-len(trail) // max_timeline)
        trail = trail[::stride] + trail[-1:]
    summary["skew_timeline"] = trail

    # Straggler ranking: host-stall share first (attributes the process
    # that stalls BEFORE the collective in lockstep runs), per-step wall
    # second (covers non-lockstep / independent-process fleets).
    stall_medians = {
        proc: _median([
            i["host_stall_frac"] for i in iv if "host_stall_frac" in i
        ])
        for proc, iv in intervals.items()
    }
    stall_medians = {
        p: v for p, v in stall_medians.items() if v is not None
    }
    step_medians = {
        proc: _median([i["per_step_s"] for i in iv])
        for proc, iv in intervals.items()
    }
    step_medians = {p: v for p, v in step_medians.items() if v is not None}
    stall_scores = _loo_scores(
        stall_medians, k=straggler_k, rel_floor=rel_floor
    )
    step_scores = _loo_scores(
        step_medians, k=straggler_k, rel_floor=rel_floor
    )
    ranking = []
    procs = sorted(set(stall_scores) | set(step_scores))
    for proc in procs:
        entry = {"proc": proc}
        if proc in stall_scores:
            entry["host_stall"] = stall_scores[proc]
        if proc in step_scores:
            entry["step_time"] = step_scores[proc]
        entry["score"] = max(
            stall_scores.get(proc, {}).get("score", 0.0),
            step_scores.get(proc, {}).get("score", 0.0),
        )
        entry["flagged"] = bool(
            stall_scores.get(proc, {}).get("flagged")
            or step_scores.get(proc, {}).get("flagged")
        )
        ranking.append(entry)
    ranking.sort(key=lambda e: -e["score"])
    straggler = next((e["proc"] for e in ranking if e["flagged"]), None)
    summary["straggler"] = {
        "ranking": ranking,
        "straggler": straggler,
        "k": straggler_k,
        "rel_floor": rel_floor,
    }

    # Missing-heartbeat dead-host suspicion: a process silent for more
    # than suspect_factor x the fleet's median heartbeat interval (and
    # without a final record) likely went dark — "process 5 stopped
    # heartbeating at step 1240", not a symmetric timeout. The detection
    # body is silence_suspects(), shared with the serving aggregator and
    # the fleet router; the median interval passed in is this
    # aggregator's step-filtered one (beats that advanced no step carry
    # no interval signal for training streams).
    all_intervals = [i["dt"] for iv in intervals.values() for i in iv]
    suspects = silence_suspects(
        {
            proc: [float(r.get("t", 0.0)) for r in hb]
            for proc, hb in beats.items()
        },
        {proc: bool(finals.get(proc)) for proc in beats},
        now=now,
        suspect_factor=suspect_factor,
        median_interval=_median(all_intervals),
    )
    for s in suspects:
        hb = beats.get(s["proc"]) or []
        s["last_step"] = hb[-1].get("step") if hb else None
    summary["suspects"] = suspects
    return summary


def write_fleet_manifest(log_dir: str, summary: dict) -> Optional[str]:
    """Write the merged fleet manifest (``fleet/fleet.json``), atomically
    (tmp + ``os.replace`` — the manifest writer's discipline). Process 0
    owns the file in-run; offline tools recompute rather than overwrite.
    Returns the path, or None on I/O failure (telemetry never takes the
    run down)."""
    path = os.path.join(fleet_dir(log_dir), "fleet.json")
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(summary, f, indent=2, default=str)
        os.replace(tmp, path)
        return path
    except OSError:
        return None
