from sav_tpu.train.checkpoint import Checkpointer
from sav_tpu.train.config import TrainConfig
from sav_tpu.train.optimizer import (
    make_optimizer,
    warmup_cosine_schedule,
    weight_decay_mask,
)
from sav_tpu.train.presets import get_preset, preset_names, register_preset
from sav_tpu.train.state import TrainState
from sav_tpu.train.trainer import Trainer

__all__ = [
    "Checkpointer",
    "TrainConfig",
    "TrainState",
    "Trainer",
    "make_optimizer",
    "warmup_cosine_schedule",
    "weight_decay_mask",
    "get_preset",
    "preset_names",
    "register_preset",
]
