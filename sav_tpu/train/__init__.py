"""Training stack — pjit trainer, config, schedules, checkpointing,
and the elastic-training supervisor.

Re-exports are lazy (PEP 562 via :mod:`sav_tpu._lazy`, the same pattern
as :mod:`sav_tpu.obs` / :mod:`sav_tpu.utils`):
:mod:`sav_tpu.train.supervisor` is stdlib-only by contract (it runs in
the parent of on-chip jobs, where importing the backend is exactly what
hangs — see ``utils.backend_probe``), so the package import must not
drag jax/orbax in eagerly.
"""

from __future__ import annotations

from sav_tpu._lazy import install_lazy_exports

_EXPORTS = {
    "Checkpointer": "sav_tpu.train.checkpoint",
    "TrainConfig": "sav_tpu.train.config",
    "TrainState": "sav_tpu.train.state",
    "Trainer": "sav_tpu.train.trainer",
    "make_optimizer": "sav_tpu.train.optimizer",
    "warmup_cosine_schedule": "sav_tpu.train.optimizer",
    "weight_decay_mask": "sav_tpu.train.optimizer",
    "get_preset": "sav_tpu.train.presets",
    "preset_names": "sav_tpu.train.presets",
    "register_preset": "sav_tpu.train.presets",
    "Supervisor": "sav_tpu.train.supervisor",
}

__all__ = list(_EXPORTS)

__getattr__, __dir__ = install_lazy_exports(
    globals(),
    _EXPORTS,
    {"checkpoint", "config", "optimizer", "presets", "state", "supervisor",
     "trainer"},
)
