"""pjit SPMD trainer.

The TPU-native replacement for both reference trainers (the pmap click CLI,
/root/reference/train.py:191-255, and the jaxline Experiment,
experiments/base.py:30-239): one jitted train step over a
``jax.sharding.Mesh``. There are no hand-written ``psum``/``pmean`` calls —
the batch is sharded over the ``data`` axis, parameters are replicated (or
TP-sharded via :mod:`sav_tpu.parallel.sharding` rules), and XLA's
partitioner emits the gradient AllReduce over ICI/DCN. One trainer covers
both stateless and BatchNorm models (collapsing base.py/base_with_state.py),
state is donated for in-place buffer reuse (base.py:64-68), logging happens
on the host outside the compiled step (fixing train.py:102-107's
wandb-inside-pmap tracer leak), and restore actually works (train.py never
called it).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
import warnings
from collections import deque
from typing import Any, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sav_tpu.models import create_model
from sav_tpu.obs.diagnostics import diagnostics_metrics
from sav_tpu.obs.goodput import GoodputLedger
from sav_tpu.obs.memory import RetraceCounter, hbm_stats
from sav_tpu.obs.spans import SpanTracer
from sav_tpu.parallel.layout import (
    BoundLayout,
    layout_from_mesh,
    resolve_layout,
)
from sav_tpu.parallel.mesh import batch_axes, create_mesh
from sav_tpu.train.checkpoint import Checkpointer
from sav_tpu.train.config import TrainConfig
from sav_tpu.train.optimizer import (
    EmaState,
    ema_params,
    make_optimizer,
    warmup_cosine_schedule,
)
from sav_tpu.train.state import TrainState
from sav_tpu.utils import profiler
from sav_tpu.utils.debug import assert_all_finite
from sav_tpu.utils.metrics import cross_entropy, topk_correct


def _cost_note(cost, peak_flops, peak_source) -> dict:
    """Manifest note for the step cost model (obs/costs.py) — the
    machine-readable twin of the goodput flops/* gauges."""
    return {
        "source": cost.source,
        "flops_per_device": cost.flops,
        "bytes_accessed": cost.bytes_accessed,
        "attribution": cost.attribution,
        "groups": cost.groups,
        "num_tokens": cost.num_tokens,
        "peak_flops": peak_flops,
        "peak_flops_source": peak_source,
    }


class Trainer:
    def __init__(
        self,
        config: TrainConfig,
        *,
        mesh=None,
        model=None,
        layout=None,
        checkpointer: Optional[Checkpointer] = None,
    ):
        self.config = config
        if config.compilation_cache_dir:
            # Before any jit dispatch, so this trainer's own compiles are
            # covered (a relay reconnection or process restart then reads
            # the multi-minute compile from disk — PERF.md §12).
            from sav_tpu.utils.compile_cache import enable_persistent_cache

            enable_persistent_cache(config.compilation_cache_dir)
        if config.attention_tune_cache:
            # Trace-time-only process state: the 'auto' dispatcher reads
            # the shape→config table while tracing (sav_tpu/ops/
            # attn_tuning.py); no jitted path ever consults it.
            from sav_tpu.ops.attn_tuning import set_cache_path

            set_cache_path(config.attention_tune_cache)
        # Declarative layout (sav_tpu/parallel/layout.py): an explicit
        # layout object or config.layout_preset states the mesh AND every
        # param/activation spec; otherwise the layout is inferred from
        # mesh_axes (exactly the pre-layout rule selection, so existing
        # configs behave identically). ONE source of truth: a preset
        # composing with an explicit mesh_axes would be two, so it is
        # rejected, and an explicit mesh must satisfy the layout.
        explicit_layout = (
            layout if layout is not None
            else resolve_layout(config.layout_preset)
        )
        if explicit_layout is not None and config.mesh_axes:
            raise ValueError(
                "config.layout_preset / Trainer(layout=...) and "
                "config.mesh_axes are two sources of layout truth; set "
                "one (the layout states its own mesh axes)"
            )
        if mesh is not None:
            self.mesh = mesh
        elif explicit_layout is not None:
            self.mesh = explicit_layout.create_mesh()
        else:
            self.mesh = create_mesh(config.mesh_axes)
        self.layout = (
            explicit_layout if explicit_layout is not None
            else layout_from_mesh(self.mesh)
        )
        # Raises on axis/size mismatch between an explicit layout and an
        # explicit mesh; binds the specs for the placements below.
        self._blayout = BoundLayout(self.layout, self.mesh)
        self.compute_dtype = (
            jnp.bfloat16 if config.compute_dtype == "bfloat16" else jnp.float32
        )
        if config.device_preprocess:
            # Host ships post-augment uint8; normalize + the augment
            # string's mixes run inside the jitted steps
            # (sav_tpu/ops/preprocess.py). Parsed once — the spec is
            # static, baked into the trace.
            from sav_tpu.data.augment_spec import parse_augment_spec

            self._mix_spec = parse_augment_spec(config.augment)
        else:
            self._mix_spec = None
        # The softmax dtype is a *model attribute*, not process state:
        # attention blocks resolve ``logits_dtype or dtype`` themselves, so
        # two trainers with different settings coexist structurally (no
        # re-pinning around lazy traces). None inherits the compute dtype —
        # exactly the reference's semantics (its logits einsum runs in the
        # model dtype, attention.py:41-48, so a bf16 reference run has bf16
        # logits). Accuracy-gated both ways (tools/logits_dtype_gate.py:
        # identical final top-1 under f32 and bf16 compute) and measured
        # −15% step time on v5e (PERF.md §6). Force 'float32' for f32
        # softmax under bf16 compute. An externally passed ``model``
        # carries its own attributes; config.attention_logits_dtype does
        # not apply to it.
        if config.sequence_parallel:
            from sav_tpu.parallel.mesh import SEQ_AXIS

            if SEQ_AXIS not in self.mesh.axis_names:
                raise ValueError(
                    f"sequence_parallel={config.sequence_parallel!r} needs a "
                    f"'{SEQ_AXIS}' mesh axis; got {self.mesh.axis_names} "
                    "(set mesh_axes={'data': -1, 'seq': N} or train.py --sp N)"
                )
        pp = config.pipeline_parallel
        if pp is not None and pp > 1 and model is None:
            if config.sequence_parallel:
                raise ValueError(
                    "pipeline_parallel does not compose with "
                    "sequence_parallel (the pipelined stages run the dense "
                    "attention core); pick one"
                )
            if config.quant is not None:
                raise ValueError(
                    "pipeline_parallel does not compose with the int8 quant "
                    "arm yet (the pipelined stage wrappers do not thread the "
                    "'quant' field); drop --quant or --pp"
                )
            from sav_tpu.models.pipelined import create_pipelined_model

            self.model = create_pipelined_model(
                config.model_name,
                num_stages=pp,
                num_microbatches=config.pipeline_microbatches,
                mesh=self.mesh,
                num_classes=config.num_classes,
                dtype=self.compute_dtype,
                backend=config.attention_backend,
                logits_dtype=config.attention_logits_dtype,
                **(config.model_overrides or {}),
            )
        else:
            self.model = (
                model
                if model is not None
                else create_model(
                    config.model_name,
                    num_classes=config.num_classes,
                    dtype=self.compute_dtype,
                    backend=config.attention_backend,
                    logits_dtype=config.attention_logits_dtype,
                    # int8 QAT arm: projection/FFN dots via
                    # sav_tpu/ops/quant.py (attention core stays bf16).
                    quant=config.quant,
                    # SP threads the trainer's mesh into every attention
                    # block (the blocks shard_map q/k/v over its 'seq' axis).
                    seq_parallel=config.sequence_parallel,
                    seq_mesh=self.mesh if config.sequence_parallel else None,
                    # 2D-TP layouts thread the bound layout so encoder
                    # blocks pin activations to P(batch, None, 'y')
                    # between blocks; 1D TP propagates from the param
                    # specs alone, and SP's shard_map owns its own specs.
                    layout=(
                        self._blayout
                        if self.layout.tp_feature_axis
                        and not config.sequence_parallel
                        else None
                    ),
                    **(config.model_overrides or {}),
                )
            )
        if model is not None:
            # These config fields are model *attributes* now; an external
            # model carries its own. Silent divergence would train with
            # different softmax numerics / without SP than the config
            # says (the old process-global pinning DID apply them), so
            # mismatches fail loudly.
            def _canon(d):
                return None if d is None else jnp.dtype(d).name

            want = config.attention_logits_dtype
            have = getattr(model, "logits_dtype", None)
            if want is not None and _canon(have) != _canon(want):
                raise ValueError(
                    f"config.attention_logits_dtype={want!r} but the "
                    f"externally built model has logits_dtype={have!r}; "
                    "pass create_model(..., logits_dtype=...) to match, or "
                    "leave the config field None"
                )
            if config.quant is not None and (
                getattr(model, "quant", None) != config.quant
            ):
                raise ValueError(
                    f"config.quant={config.quant!r} but the externally "
                    "built model does not carry it; pass "
                    "create_model(..., quant=...) to match, or leave the "
                    "config field None"
                )
            if config.sequence_parallel is not None and (
                getattr(model, "seq_parallel", None) != config.sequence_parallel
            ):
                raise ValueError(
                    f"config.sequence_parallel={config.sequence_parallel!r} "
                    "but the externally built model does not carry it; pass "
                    "create_model(..., seq_parallel=..., seq_mesh=...) to "
                    "match, or leave the config field None"
                )
            if (config.pipeline_parallel or 1) > 1 and (
                getattr(model, "num_stages", None) != config.pipeline_parallel
            ):
                raise ValueError(
                    f"config.pipeline_parallel={config.pipeline_parallel} "
                    "but the externally built model is not a pipelined model "
                    "with that stage count; build it via "
                    "create_pipelined_model(...) or leave the field None"
                )
        self.schedule = warmup_cosine_schedule(
            config.learning_rate,
            steps_per_epoch=config.steps_per_epoch,
            warmup_epochs=config.warmup_epochs,
            num_epochs=config.num_epochs,
            end_lr=config.end_lr,
        )
        fused_opt = config.fused_optimizer
        if fused_opt is None:
            # Flat Adam moments can't be sharded like their parameters —
            # auto-enable only when params are replicated (no non-data axis).
            fused_opt = all(name == "data" for name in self.mesh.axis_names)
        self._build_optimizer(fused_opt)
        self.checkpointer = checkpointer
        if checkpointer is None and config.checkpoint_dir:
            self.checkpointer = Checkpointer(
                config.checkpoint_dir, keep=config.checkpoint_keep
            )
        self._eval_step = jax.jit(self._eval_step_impl)
        # Goodput ledger summary of the most recent fit() (sav_tpu.obs).
        self.last_goodput: Optional[dict] = None

    def _build_optimizer(self, fused: bool) -> None:
        """(Re)build the optax chain + the jitted step programs.

        Split out of ``__init__`` so :meth:`restore_or_init` can swap the
        optimizer *layout* (per-leaf vs flat Adam moments) to match a
        probed checkpoint before building the restore template — the
        numerics are identical (``optax.flatten`` is a reshape), only the
        opt-state pytree structure changes.
        """
        self.fused_optimizer = fused
        self.tx = make_optimizer(
            self.schedule,
            weight_decay=self.config.weight_decay,
            clip_grad_norm=self.config.clip_grad_norm,
            fused=fused,
            ema_decay=self.config.ema_decay,
        )
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=(0,))
        self._train_many = jax.jit(self._train_many_impl, donate_argnums=(0,))

    # ------------------------------------------------------------------ init

    def _dummy_shape(self) -> tuple:
        s = self.config.image_size
        # Batch sized to the mesh's batch-axes product: init traces the
        # model once, and under sequence parallelism a batch that does
        # not divide the data axes takes the replication fallback — the
        # MULTICHIP_r05 warning came from exactly this dummy (batch 2 vs
        # a data axis of 4 in the talking-heads SP leg), not from any
        # real training batch. Shape only: the zeros materialize inside
        # the jitted init_fn (traced, never a host buffer), so a 256-way
        # data axis does not cost a concrete global-batch-sized array.
        b = max(
            2,
            int(np.prod([self.mesh.shape[a] for a in batch_axes(self.mesh)])),
        )
        return (b, s, s, 3)

    def init_state(self, seed: Optional[int] = None) -> TrainState:
        """Build a sharded TrainState directly on the mesh.

        The state is created *inside* jit with explicit out_shardings, so
        large models materialize sharded — parameters never pass through a
        single host buffer.
        """
        rng = jax.random.PRNGKey(self.config.seed if seed is None else seed)
        dummy_shape = self._dummy_shape()

        def init_fn(rng):
            dummy = jnp.zeros(dummy_shape, self.compute_dtype)
            variables = self.model.init({"params": rng}, dummy, is_training=False)
            variables = dict(variables)
            params = variables.pop("params")
            batch_stats = variables.pop("batch_stats", {})
            opt_state = self.tx.init(params)
            return TrainState.create(params, opt_state, batch_stats)

        abstract = jax.eval_shape(init_fn, rng)
        # Rules match on path *suffixes*, so optimizer-state mirrors of the
        # param tree (mu/nu) pick up the same TP shardings automatically.
        shardings = self._blayout.param_shardings(abstract)
        state = jax.jit(init_fn, out_shardings=shardings)(rng)
        return state

    def warm_start_from(self, directory: str) -> TrainState:
        """Fresh state (step 0, fresh optimizer) with params/batch_stats
        loaded from another run's checkpoint — the finetune path the
        reference lacked entirely (its restore was never wired,
        /root/reference/train.py:123-127, SURVEY.md §5).

        Cross-resolution transfers follow the standard ViT recipe
        (DeiT/CaiT 224-pretrain → 384-finetune): ``pos_embed`` tables are
        bicubic-resampled to the new token count
        (:mod:`sav_tpu.models.surgery`). Any other shape mismatch (e.g. a
        different-width head for a new label space) keeps the fresh
        initialization for that leaf, logged — classic warm-start
        semantics.
        """
        import logging

        from sav_tpu.models.surgery import adapt_pos_embeds

        source = Checkpointer(directory, read_only=True)
        try:
            raw = source.restore_raw()
        finally:
            source.close()
        if raw is None:
            raise FileNotFoundError(f"no checkpoint found in {directory!r}")
        src_params = raw["params"] if isinstance(raw, dict) else raw.params
        src_stats = (
            raw.get("batch_stats", {}) if isinstance(raw, dict)
            else raw.batch_stats
        )
        fresh = self.init_state()
        src_params = adapt_pos_embeds(src_params, fresh.params)
        counts = {"transferred": 0, "fresh": 0}

        def merge(tree_src, tree_fresh, collection):
            flat_src = {
                tuple(p): l
                for p, l in jax.tree_util.tree_flatten_with_path(tree_src)[0]
            }

            def pick(path, fresh_leaf):
                src = flat_src.get(tuple(path))
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                if src is None or src.shape != fresh_leaf.shape:
                    # warning level: the default unconfigured logger drops
                    # info, and a silently-fresh "warm start" (e.g. wrong
                    # model_overrides failing every shape check) must be
                    # visible.
                    logging.warning(
                        "warm start: %s %s %s; keeping fresh init",
                        collection, name,
                        "not in source" if src is None
                        else f"shape {src.shape} != {fresh_leaf.shape}",
                    )
                    counts["fresh"] += 1
                    return fresh_leaf
                counts["transferred"] += 1
                return jax.device_put(
                    jnp.asarray(src, dtype=fresh_leaf.dtype),
                    fresh_leaf.sharding,
                )

            return jax.tree_util.tree_map_with_path(pick, tree_fresh)

        params = merge(src_params, fresh.params, "params")
        stats = (
            merge(src_stats, fresh.batch_stats, "batch_stats")
            if fresh.batch_stats else fresh.batch_stats
        )
        logging.warning(
            "warm start from %s: %d leaves transferred, %d fresh",
            directory, counts["transferred"], counts["fresh"],
        )
        # Reseed the parameter EMA (if configured) from the TRANSFERRED
        # weights: tx.init built it from the random init, and eval-on-EMA
        # would otherwise spend ~1/(1-decay) steps converging back from
        # garbage on exactly the short finetunes EMA is meant to help.
        # jnp.array(copy=True): the EMA leaf must be a DISTINCT buffer — a
        # no-copy device_put of the (already-f32, already-placed) param
        # leaf would alias it, and the donated train step then donates the
        # same buffer twice (runtime crash on the first finetune step).
        opt_state = jax.tree_util.tree_map(
            lambda s: (
                EmaState(
                    ema=jax.tree.map(
                        lambda e, p: jax.device_put(
                            jnp.array(p, dtype=e.dtype, copy=True), e.sharding
                        ),
                        s.ema,
                        params,
                    )
                )
                if isinstance(s, EmaState)
                else s
            ),
            fresh.opt_state,
            is_leaf=lambda x: isinstance(x, EmaState),
        )
        return fresh.replace(
            params=params, batch_stats=stats, opt_state=opt_state
        )

    def _match_checkpoint_layout(self) -> None:
        """Probe the saved opt-state layout and pick the matching
        optimizer build (docs/elasticity.md).

        Resuming a pre-round-3 checkpoint used to require a hand-passed
        ``--no-fused-optimizer``; the checkpoint itself already knows its
        layout, so when ``config.fused_optimizer`` is None (auto) the
        probe's answer wins and the optimizer is rebuilt to match. An
        *explicit* config that contradicts the checkpoint is kept — the
        user overrode auto on purpose — but warned about, because the
        restore is then going to fail with a structure mismatch.
        """
        import logging

        layout = self.checkpointer.opt_layout()
        detected = layout.get("fused")
        if detected is not None and detected != self.fused_optimizer:
            pure_data = all(name == "data" for name in self.mesh.axis_names)
            if self.config.fused_optimizer is None:
                if detected and not pure_data:
                    # Auto-detect must not override the __init__ mesh
                    # guard: flat Adam moments cannot take non-data
                    # parameter shardings, so a fused-layout checkpoint
                    # cannot be resumed onto this mesh either way —
                    # keep per-leaf and let the restore fail loudly.
                    logging.warning(
                        "checkpoint uses the flat-buffer optimizer-state "
                        "layout but the mesh has non-data axes %s (flat "
                        "moments cannot shard like their parameters); "
                        "keeping the per-leaf build — restore will fail; "
                        "resume on the checkpoint's original mesh layout",
                        list(self.mesh.axis_names),
                    )
                    return
                logging.warning(
                    "checkpoint uses the %s optimizer-state layout; "
                    "rebuilding the optimizer to match (auto-detected — "
                    "pass --%sfused-optimizer to silence)",
                    "flat-buffer" if detected else "per-leaf",
                    "" if detected else "no-",
                )
                self._build_optimizer(detected)
            else:
                logging.warning(
                    "config.fused_optimizer=%s but the checkpoint's "
                    "opt-state layout is %s — restore will fail with a "
                    "structure mismatch unless the flag matches the "
                    "checkpoint",
                    self.config.fused_optimizer,
                    "flat-buffer" if detected else "per-leaf",
                )
        if layout.get("ema") is not None and bool(layout.get("ema")) != (
            self.config.ema_decay is not None
        ):
            logging.warning(
                "checkpoint %s a parameter-EMA tree but config.ema_decay "
                "is %s — restore will fail with a structure mismatch "
                "unless --ema-decay matches the checkpointed run",
                "carries" if layout.get("ema") else "lacks",
                self.config.ema_decay,
            )

    def restore_or_init(self) -> TrainState:
        if self.checkpointer is not None and self.checkpointer.latest_step() is not None:
            # Layout probe BEFORE the template is built: the template's
            # opt-state structure must match the saved one.
            self._match_checkpoint_layout()
        state = self.init_state()
        if self.checkpointer is not None:
            try:
                restored = self.checkpointer.restore_latest(state)
            except Exception as e:
                # Only attribute tree/structure mismatches to the optimizer
                # layout switch (per-leaf vs optax.flatten'd Adam state —
                # TrainConfig.fused_optimizer); other failures (corrupt
                # checkpoint, I/O errors) re-raise untouched. Match the
                # exception type AND an anchored phrase — a bare substring
                # would false-positive on paths containing 'tree'.
                msg = str(e).lower()
                mismatch = isinstance(e, (ValueError, TypeError, KeyError)) and any(
                    phrase in msg
                    for phrase in ("tree structure", "pytree", "same structure")
                )
                if mismatch:
                    raise RuntimeError(
                        "checkpoint restore failed with a state-structure "
                        "mismatch; two config knobs change the opt-state "
                        "layout and must match the checkpoint: (a) "
                        "--ema-decay (TrainConfig.ema_decay) adds an EMA "
                        "tree — set it iff the checkpointed run had it; "
                        "(b) checkpoints predating the flat-buffer "
                        "optimizer (round 3) need --no-fused-optimizer "
                        "(TrainConfig.fused_optimizer=False) for the "
                        "per-leaf Adam state layout"
                    ) from e
                raise
            if restored is not None:
                return restored
        return state

    # ----------------------------------------------------------------- steps

    def _prep_images(self, images: jax.Array) -> jax.Array:
        if images.dtype == jnp.uint8:
            # uint8 batches belong to device_preprocess=True (which
            # normalizes on device); a plain astype here would silently
            # train on unnormalized 0..255 values (ADVICE r3). Trace-time
            # check — dtypes are static under jit.
            raise ValueError(
                "got uint8 images with device_preprocess=False; either set "
                "TrainConfig.device_preprocess=True or feed normalized "
                "float batches (load(device_preprocess=...) must match the "
                "trainer)"
            )
        if self.config.transpose_images and images.ndim == 4:
            # HWCN → NHWC (the reference's double-transpose trick lands the
            # device-side transpose here, train.py:80).
            images = jnp.transpose(images, (3, 0, 1, 2))
        return images.astype(self.compute_dtype)

    def _label_probs(self, batch: dict) -> jax.Array:
        labels = batch["labels"]
        onehot = jax.nn.one_hot(labels, self.config.num_classes, dtype=jnp.float32)
        if "mix_labels" in batch:
            ratio = batch["ratio"].astype(jnp.float32)[:, None]
            mix = jax.nn.one_hot(
                batch["mix_labels"], self.config.num_classes, dtype=jnp.float32
            )
            onehot = ratio * onehot + (1.0 - ratio) * mix
        if self.config.label_smoothing > 0.0:
            onehot = optax.smooth_labels(onehot, self.config.label_smoothing)
        return onehot

    def _device_preprocess(self, batch: dict, rng, training: bool) -> dict:
        """uint8 host batch → mixed (train) + normalized compute-dtype
        images, on device (TrainConfig.device_preprocess; see
        sav_tpu/ops/preprocess.py for the host-parity contract)."""
        from sav_tpu.ops import preprocess as pp

        images = batch["images"]
        if images.dtype != jnp.uint8:
            # The device_preprocess contract ships post-augment 0..255
            # uint8 (load(device_preprocess=True) / savrec
            # normalize=False); an already-normalized float batch here
            # would be normalized twice — silently wrong training
            # (ADVICE r3). Trace-time check: dtypes are static under jit.
            raise ValueError(
                "device_preprocess=True expects uint8 batches from the "
                f"matching pipeline mode, got {images.dtype}; feed "
                "load(device_preprocess=True) / "
                "savrec_train_iterator(normalize=False) batches, or turn "
                "device_preprocess off"
            )
        if self.config.transpose_images and images.ndim == 4:
            images = jnp.transpose(images, (3, 0, 1, 2))  # HWCN → NHWC
        batch = dict(batch)
        if training and self._mix_spec is not None and self._mix_spec.mixes:
            images, mix_labels, ratio = pp.apply_mixes(
                rng, images, batch["labels"], self._mix_spec
            )
            if mix_labels is not None:
                batch["mix_labels"] = mix_labels
                batch["ratio"] = ratio
        batch["images"] = pp.normalize_images(images, self.compute_dtype)
        return batch

    def _train_step_impl(self, state: TrainState, batch: dict, rng: jax.Array):
        step_rng = jax.random.fold_in(rng, state.step)
        if self.config.device_preprocess:
            # Dedicated fold so the mix draws are independent of the
            # dropout/stochastic-depth streams split from step_rng below.
            batch = self._device_preprocess(
                batch, jax.random.fold_in(step_rng, 0x6D69), training=True
            )
            images = batch["images"]  # already NHWC, compute dtype
        else:
            images = self._prep_images(batch["images"])
        label_probs = self._label_probs(batch)
        has_bn = bool(state.batch_stats)

        def loss_fn(
            params, batch_stats, images, label_probs, dropout_rng, sd_rng,
            quant_rng=None,
        ):
            variables = {"params": params}
            if has_bn:
                variables["batch_stats"] = batch_stats
            rngs = {"dropout": dropout_rng, "stochastic_depth": sd_rng}
            if quant_rng is not None:
                # int8 QAT: stochastic rounding of the backward gradient
                # dots (sav_tpu/ops/quant.py); flax's make_rng folds the
                # module path in, so every quantized dot draws independent
                # rounding bits from this one stream.
                rngs["quant"] = quant_rng
            # 'losses' collects auxiliary objectives modules sow (e.g. the
            # MoE load-balancing loss); empty for most models.
            mutable = ["batch_stats", "losses"] if has_bn else ["losses"]
            logits, new_vars = self.model.apply(
                variables,
                images,
                is_training=True,
                rngs=rngs,
                mutable=mutable,
            )
            new_batch_stats = new_vars["batch_stats"] if has_bn else batch_stats
            # Sown 'losses' are ready-to-sum penalties at their relative
            # scales (see MoEFFBlock's convention note); aux_loss_weight is
            # the single relative→loss-units conversion, and the logged
            # aux_loss metric is the relative-units sum.
            aux = sum(
                jnp.sum(leaf)
                for leaf in jax.tree.leaves(new_vars.get("losses", {}))
            )
            aux = jnp.asarray(aux, jnp.float32)
            loss = (
                cross_entropy(logits, label_probs)
                + self.config.aux_loss_weight * aux
            )
            return loss, (logits, new_batch_stats, aux)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        accum = self.config.grad_accum_steps
        if accum < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got {accum}")
        # The quant stream only exists on the int8 arm, and splits 3-way
        # instead of 2-way there — float runs keep their exact historical
        # dropout/stochastic-depth streams (pinned tests depend on them).
        quantized = self.config.quant is not None
        if accum == 1:
            if quantized:
                dropout_rng, sd_rng, quant_rng = jax.random.split(step_rng, 3)
            else:
                dropout_rng, sd_rng = jax.random.split(step_rng)
                quant_rng = None
            (loss, (logits, new_batch_stats, aux_loss)), grads = grad_fn(
                state.params, state.batch_stats, images, label_probs,
                dropout_rng, sd_rng, quant_rng,
            )
        else:
            # Gradient accumulation: scan over micro-batches, averaging
            # grads/losses; one optimizer update. BatchNorm statistics
            # thread through the scan carry (each micro-batch sees the
            # previous micro-batch's running stats, like sequential steps).
            b = images.shape[0]
            if b % accum:
                raise ValueError(
                    f"batch size {b} not divisible by grad_accum_steps {accum}"
                )

            def split(x):
                return x.reshape(accum, b // accum, *x.shape[1:])

            def micro(carry, xs):
                bs, gsum, lsum, asum, i = carry
                im, lp = xs
                micro_rng = jax.random.fold_in(step_rng, i)
                if quantized:
                    dr, sr, qr = jax.random.split(micro_rng, 3)
                else:
                    dr, sr = jax.random.split(micro_rng)
                    qr = None
                (l, (lg, nbs, ax)), g = grad_fn(
                    state.params, bs, im, lp, dr, sr, qr
                )
                gsum = jax.tree.map(jnp.add, gsum, g)
                return (nbs, gsum, lsum + l, asum + ax, i + 1), lg

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            carry0 = (
                state.batch_stats, zeros, jnp.float32(0.0), jnp.float32(0.0),
                jnp.int32(0),
            )
            (new_batch_stats, gsum, lsum, asum, _), logits_stack = jax.lax.scan(
                micro, carry0, (split(images), split(label_probs))
            )
            grads = jax.tree.map(lambda g: g / accum, gsum)
            loss = lsum / accum
            aux_loss = asum / accum
            logits = logits_stack.reshape(b, *logits_stack.shape[2:])
        updates, new_opt_state = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_batch_stats,
        )
        acc = topk_correct(logits.astype(jnp.float32), batch["labels"])
        metrics = {
            "loss": loss,
            "top_1_acc": jnp.mean(acc["top_1_acc"]),
            "top_5_acc": jnp.mean(acc["top_5_acc"]),
            "learning_rate": self.schedule(state.step),
            "grad_norm": optax.global_norm(grads),
            "aux_loss": aux_loss,
        }
        if self.config.diagnostics:
            # In-jit diagnostics (sav_tpu.obs.diagnostics): computed on
            # device, returned with the step metrics, so they ride the
            # per-log device_get with zero extra transfers.
            metrics.update(
                diagnostics_metrics(
                    grads=grads, params=state.params, updates=updates
                )
            )
        return new_state, metrics

    def _train_many_impl(self, state: TrainState, batches: dict, rng: jax.Array):
        """K train steps in one compiled program via ``lax.scan``.

        ``batches`` leaves carry a leading steps axis ``[K, ...]``. Keeping
        the step loop on-device removes the per-step host dispatch round
        trip — on TPU pods that overhead is µs, but the pattern also hides
        host jitter and lets XLA overlap the inter-step boundary. Metrics
        come back stacked ``[K]``.
        """

        def body(state, batch):
            return self._train_step_impl(state, batch, rng)

        return jax.lax.scan(body, state, batches)

    def train_many_steps(self, state: TrainState, batches: dict, rng: jax.Array):
        """Run ``K`` steps fused on-device; see ``_train_many_impl``."""

        def sharding_for(key, leaf):
            # Leading [K, ...] steps axis shifts the batch dim to 1; the
            # HWCN transpose puts it last. Specs come from the layout
            # (batch_sharding(dim) — savlint SAV117 keeps ad-hoc
            # PartitionSpec construction out of this file).
            if key == "images" and self.config.transpose_images and leaf.ndim == 5:
                return self._blayout.batch_sharding(dim=4)
            return self._blayout.batch_sharding(dim=1)

        placed = {k: jax.device_put(v, sharding_for(k, v)) for k, v in batches.items()}
        return self._train_many(state, placed, rng)

    def _eval_step_impl(self, state: TrainState, batch: dict):
        if self.config.device_preprocess:
            batch = self._device_preprocess(batch, None, training=False)
            images = batch["images"]
        else:
            images = self._prep_images(batch["images"])
        # Eval on the parameter EMA when configured (the DeiT/CaiT-recipe
        # standard: the averaged weights generalize better than the last
        # step's). The EMA tree lives in opt_state (optimizer.py
        # track_params_ema) and mirrors the params' shardings.
        params = state.params
        if self.config.ema_decay is not None:
            ema = ema_params(state.opt_state)
            if ema is not None:
                params = ema
        variables = {"params": params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        logits = self.model.apply(variables, images, is_training=False)
        logits = logits.astype(jnp.float32)
        labels = batch["labels"]
        onehot = jax.nn.one_hot(labels, self.config.num_classes, dtype=jnp.float32)
        n = labels.shape[0]
        # 'valid' marks real rows in a padded final batch (evaluate() pads
        # remainders so every batch has one static, mesh-divisible shape).
        valid = batch.get("valid")
        if valid is None:
            valid = jnp.ones((n,), jnp.float32)
        acc = topk_correct(logits, labels)
        logp = jax.nn.log_softmax(logits, axis=-1)
        per_example_loss = -jnp.sum(onehot * logp, axis=-1)
        return {
            "loss_sum": jnp.sum(per_example_loss * valid),
            "top_1_sum": jnp.sum(acc["top_1_acc"] * valid),
            "top_5_sum": jnp.sum(acc["top_5_acc"] * valid),
            "count": jnp.sum(valid),
        }

    # ------------------------------------------------------------- data flow

    def shard_batch(self, batch: dict) -> dict:
        """Place a host batch onto the mesh, batch dim over the data axis.

        Single-process: a plain ``device_put``. Multi-process (SPMD over
        hosts — the reference's implicit TPU-VM setup,
        input_pipeline.py:102): each process passes its *per-host* shard
        (the data pipeline already yields per-host batches) and the global
        array is assembled process-locally — no host gathers any other
        host's data.
        """

        multiprocess = jax.process_count() > 1

        def sharding_for(key, leaf):
            if key == "images" and self.config.transpose_images and leaf.ndim == 4:
                return self._blayout.batch_sharding(dim=3)
            return self._blayout.batch_sharding()

        def place(key, leaf):
            sharding = sharding_for(key, leaf)
            if multiprocess:
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(leaf)
                )
            return jax.device_put(leaf, sharding)

        return {k: place(k, v) for k, v in batch.items()}

    # ------------------------------------------------------------------ loop

    def _resolve_peak(self) -> tuple[Optional[float], str]:
        """(per-chip peak FLOP/s, source) for MFU accounting — the
        config override, the device table, or CPU's deterministic fake
        (sav_tpu/obs/costs.py)."""
        from sav_tpu.obs.costs import resolve_peak_flops

        return resolve_peak_flops(self.config.peak_flops)

    def train_step(self, state: TrainState, batch: dict, rng: jax.Array):
        return self._train_step(state, self.shard_batch(batch), rng)

    def train_step_placed(self, state: TrainState, placed: dict, rng: jax.Array):
        """One jitted update on an already-placed (sharded) batch.

        The step the feeder path consumes: public surface for harnesses
        that drive placement themselves (bench.py fed modes,
        tools/feed_micro.py pair it with :meth:`shard_batch` /
        :class:`~sav_tpu.data.feeder.DeviceFeeder`). :meth:`train_step`
        is the shard-inline convenience wrapper over the same program.
        """
        return self._train_step(state, placed, rng)

    def compile_train_step(self, state: TrainState, placed: dict, rng):
        """AOT-lower + compile the train step for an already-placed batch.

        Public surface for harnesses that run the compiled executable
        directly and read its artifacts — XLA cost analysis (bench.py's
        MFU), HLO metadata for trace attribution
        (tools/profile_step.py's op index) — instead of poking the
        private ``_train_step``. Same program as
        :meth:`train_step_placed`; note AOT compilation does not
        populate the jit dispatch cache, so mixing the two pays a second
        compile.
        """
        return self._train_step.lower(state, placed, rng).compile()

    def eval_step(self, state: TrainState, batch: dict):
        return self._eval_step(state, self.shard_batch(batch))

    def _pad_eval_batch(self, batch: dict, target: int) -> dict:
        """Zero-pad a partial final batch to ``target`` rows + 'valid' mask.

        Keeps eval at one compiled shape and makes any eval size work on any
        mesh (the reference hard-errored on non-divisible eval batches,
        input_pipeline.py:150-152)."""
        n = len(batch["labels"])
        pad = target - n
        transposed = self.config.transpose_images

        def pad_leaf(key, x):
            x = np.asarray(x)
            axis = x.ndim - 1 if (key == "images" and transposed) else 0
            widths = [(0, 0)] * x.ndim
            widths[axis] = (0, pad)
            return np.pad(x, widths)

        out = {k: pad_leaf(k, v) for k, v in batch.items()}
        out["valid"] = np.concatenate(
            [np.ones(n, np.float32), np.zeros(pad, np.float32)]
        )
        return out

    def evaluate(
        self,
        state: TrainState,
        eval_iter: Iterator[dict],
        *,
        recorder=None,
    ) -> dict:
        """Run one evaluation pass over ``eval_iter``.

        The loop is pipelined like fit()'s (config.async_feed): pad+place
        run on the feeder's background thread so transfer of batch N+1
        overlaps the device's batch N, and the per-batch sums stay on
        device until one ``device_get`` at the end — the old per-batch
        synchronous fetch + sync serialized every stage and inflated eval
        windows on slow-transfer rigs (PERF.md §7).

        Numerics guards mirror fit()'s: a nonfinite eval metric dumps a
        flight-recorder incident bundle (``recorder`` — fit() passes its
        own so mid-run evals share the training ring; standalone evals
        build a fresh one when ``config.record``) and, under
        ``config.debug_nans``, raises ``FloatingPointError`` naming the
        bad keys.
        """
        batch_size: Optional[int] = None
        data_div = int(np.prod([self.mesh.shape[a] for a in batch_axes(self.mesh)]))

        def place(batch: dict):
            # Runs on the feeder thread in async mode: pad the (host)
            # batch to the compiled shape, then shard onto the mesh. The
            # single feeder worker processes batches in order, so the
            # first-batch shape fixing is race-free.
            nonlocal batch_size
            n = len(batch["labels"])
            if batch_size is None:
                # First batch fixes the compiled shape: its size rounded up
                # to a mesh-divisible multiple (so a tiny eval set shards).
                batch_size = -(-n // data_div) * data_div
            if n < batch_size:
                batch = self._pad_eval_batch(batch, batch_size)
            return self.shard_batch(batch)

        cfg = self.config
        feeder = None
        if cfg.async_feed:
            from sav_tpu.data.feeder import DeviceFeeder

            feeder = DeviceFeeder(
                iter(eval_iter), place, depth=cfg.feed_depth,
                name="eval-feeder",
            )
            placed_iter = feeder
        else:
            placed_iter = map(place, eval_iter)
        device_sums = []
        # Dispatches stay async so the device pipelines batches, but
        # run-ahead must be bounded: every dispatched-not-retired step
        # holds its input batch in HBM, and a long eval set on a
        # compute-bound device would otherwise accumulate them all. Once
        # batch K's sums are ready its inputs are free, so blocking on
        # the (N - max_inflight)-th sums caps live batches at
        # feed_depth (queued) + max_inflight (dispatched).
        max_inflight = cfg.feed_depth + 1
        retired = 0
        try:
            for placed in placed_iter:
                device_sums.append(self._eval_step(state, placed))
                if len(device_sums) - retired >= max_inflight:
                    jax.block_until_ready(  # savlint: disable=SAV101 -- run-ahead cap: retiring step N-max_inflight bounds placed-batch HBM
                        device_sums[retired]
                    )
                    retired += 1
        finally:
            if feeder is not None:
                feeder.close()
        totals: dict[str, float] = {}
        for sums in jax.device_get(device_sums):  # savlint: disable=SAV101 -- the one end-of-pass sync the whole eval loop deferred to
            for k, v in sums.items():
                totals[k] = totals.get(k, 0.0) + float(v)
        n = max(totals.get("count", 0.0), 1.0)
        results = {
            "eval_loss": totals.get("loss_sum", 0.0) / n,
            "eval_top_1_acc": totals.get("top_1_sum", 0.0) / n,
            "eval_top_5_acc": totals.get("top_5_sum", 0.0) / n,
            "eval_count": n,
        }
        bad = sorted(k for k, v in results.items() if not math.isfinite(v))
        if bad:
            if (
                recorder is None
                and cfg.record
                and jax.process_index() == 0
            ):
                # Standalone eval (train.py --eval-only): no training ring
                # exists, but a nonfinite eval loss still gets a bundle
                # (trigger + metrics + config) for the record.
                from sav_tpu.obs.recorder import FlightRecorder

                recorder = FlightRecorder.from_config(
                    cfg, cfg.log_dir or cfg.checkpoint_dir or "."
                )
            if recorder is not None:
                recorder.dump_incident(
                    "eval_nonfinite",
                    extra={"eval": results, "bad_keys": bad},
                )
            if cfg.debug_nans:
                raise FloatingPointError(
                    f"non-finite values in eval metrics: {bad}"
                )
        return results

    def _save_with_stamp(self, step: int, state: TrainState) -> None:
        """One checkpoint save + the resume stamp (docs/elasticity.md).

        ``resume.json`` persists the full mid-epoch resume recipe next to
        the checkpoints — ``(epoch, step-in-epoch, rng derivation, feeder
        position)`` — as auditable provenance: the checkpoint's own
        ``state.step`` stays authoritative (the resumable data stream and
        the rng are both pure functions of ``(seed, step)``), and the
        stamp lets supervisors/post-mortems read the resume point without
        orbax. Advisory by design: the stamp is written when the async
        save is *requested*; a preemption between request and commit
        leaves a stamp one save ahead, which readers must treat as an
        upper bound.
        """
        self.checkpointer.save(step, state)
        cfg = self.config
        spe = max(cfg.steps_per_epoch, 1)
        stamp = {
            "schema": 1,
            "step": int(step),
            "epoch": int(step // spe),
            "step_in_epoch": int(step % spe),
            "steps_per_epoch": spe,
            "seed": cfg.seed,
            # Batches consumed == steps on the EFFECTIVE schedule;
            # rewind-and-skip shifts the original-schedule position
            # (train.py's resume_schedule_position + notes.rewind_skip
            # carry the audit).
            "feeder_position": int(step),
            "rng": {
                "derivation":
                    "jax.random.fold_in(jax.random.PRNGKey(seed), 1), "
                    "then fold_in(rng, state.step) inside the step",
            },
            "saved_unix": round(time.time(), 3),
        }
        path = os.path.join(self.checkpointer.directory, "resume.json")
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(stamp, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            pass  # provenance, never fatal

    def fit(
        self,
        train_iter: Iterator[dict],
        *,
        num_steps: Optional[int] = None,
        eval_iter_fn=None,
        state: Optional[TrainState] = None,
        log_fn=None,
        manifest=None,
    ) -> tuple[TrainState, list[dict]]:
        """Run the training loop.

        Args:
          train_iter: yields batches (dicts with 'images', 'labels', optional
            'mix_labels'/'ratio').
          num_steps: total steps (default: config.total_steps).
          eval_iter_fn: zero-arg callable returning a fresh eval iterator
            (fixes the reference's exhausted-generator eval bug,
            train.py:239-250 / SURVEY.md §2.9 #21).
          log_fn: callable(dict) for metrics (host-side, outside jit).
          manifest: optional :class:`~sav_tpu.obs.manifest.RunManifest`.
            fit() accretes facts onto it (backend, cost model, goodput
            metrics — on crash paths too, via the finally below) and hands
            it to the hang watchdog (which finalizes ``outcome: "hang"``
            before exit 4); the *caller* owns terminal ok/error
            finalization, since a run may continue past fit().

        Input feed (docs/input_pipeline.md): with ``config.async_feed``
        (the default) batches are fetched and placed on device by a
        background :class:`~sav_tpu.data.feeder.DeviceFeeder` — host fetch
        and the sharded ``device_put`` of batch N+1 overlap the device's
        step N, and the loop only blocks on the bounded queue.
        ``config.async_feed=False`` restores the serial
        fetch → put → dispatch loop.

        Run telemetry (sav_tpu.obs, docs/observability.md): every run keeps
        a goodput ledger (compile/step/input-wait/h2d/eval/checkpoint/stall
        buckets plus ``feeder/*`` gauges, written to <log_dir>/goodput.json
        and exposed as
        ``self.last_goodput``); ``config.trace_spans`` additionally records
        host-side spans around each phase into a Perfetto-loadable
        <log_dir>/spans.trace.json, and ``config.watchdog_secs`` arms a
        hang watchdog that aborts with exit 4 + stack dump when no step
        completes in time.
        """
        cfg = self.config
        num_steps = num_steps if num_steps is not None else cfg.total_steps
        state = state if state is not None else self.restore_or_init()
        # The fit() stream is derived from the run key with an explicit
        # tag, not by perturbing the seed (savlint SAV110): seed+1 could
        # collide with another run's seed, and fold_in is auditable.
        rng = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 1)
        history: list[dict] = []
        obs_dir = cfg.log_dir or cfg.checkpoint_dir
        # Telemetry files are written by FLEET process 0 only — runs
        # share --log-dir (the rsync/report workflow) and concurrent
        # writers would clobber each other. Identity defaults to jax's
        # process index; the SAV_FLEET_PROC/_PROCS override covers
        # fleets not coordinated through jax.distributed (independent
        # workers sharing a log dir), where every worker is jax process
        # 0 and would otherwise clobber goodput.json/spans — only the
        # per-process heartbeat streams below are written by everyone.
        from sav_tpu.obs.fleet import resolve_identity as _fleet_identity

        fleet_proc, fleet_procs = _fleet_identity(
            jax.process_index(), jax.process_count()
        )
        obs_writer = fleet_proc == 0
        tracer = SpanTracer(
            os.path.join(obs_dir or ".", "spans.trace.json")
            if cfg.trace_spans and obs_writer else None
        )
        ledger = GoodputLedger()
        retraces = RetraceCounter(self._train_step) if cfg.diagnostics else None
        sanitizer = None
        if cfg.sanitize:
            # Runtime sanitizers (sav_tpu.analysis.sanitize): armed after
            # the first completed step (compile + setup transfers exempt),
            # torn down in the finally below. The sanitizer keeps its OWN
            # RetraceCounter so diagnostics' delta() accounting above is
            # undisturbed when both are on.
            from sav_tpu.analysis.sanitize import StepSanitizer

            sanitizer = StepSanitizer(self._train_step, tag="train-sanitize")
        recorder = None
        if cfg.record and obs_writer:
            # Flight recorder (sav_tpu.obs.recorder; docs/incident_replay.md):
            # host-side ring of step context + raw batches + periodic
            # pre-step snapshots, dumped as a replayable incident bundle
            # on nonfinite metrics / loss spikes / hangs / crashes. The
            # per-step path is sync-free (SAV111); the periodic snapshot
            # below is the one pipeline drain recording adds.
            from sav_tpu.obs.recorder import FlightRecorder

            recorder = FlightRecorder.from_config(
                cfg, obs_dir or ".", manifest=manifest
            )
        fleet_hb = None
        if cfg.fleet and obs_dir is not None:
            # Fleet heartbeats (sav_tpu.obs.fleet; docs/fleet.md): EVERY
            # process appends to its own fleet/proc_<i>.jsonl — unlike the
            # other telemetry writers this is deliberately not process-0
            # gated, because per-process streams ARE the product (the
            # aggregator attributes stragglers/dead hosts across them).
            # The per-beat path is host-only (savlint SAV112) and rides
            # the existing log boundary.
            from sav_tpu.obs.fleet import HeartbeatWriter

            fleet_hb = HeartbeatWriter(
                obs_dir,
                process_index=fleet_proc,
                process_count=fleet_procs,
            )
        autoprof = None
        # Abstract (ShapeDtypeStruct) mirror of the step's arguments,
        # captured once the first real shapes are known — the lazy HLO
        # op-index source for post-capture trace attribution below.
        autoprof_abstract = None
        if cfg.autoprof and obs_dir is not None:
            # Anomaly-triggered bounded jax.profiler windows
            # (sav_tpu.obs.autoprof): armed by the ledger's stall
            # anomaly, the per-window step-time spike gate, or the
            # watchdog's soft stage; per-process (a straggler diagnosis
            # needs the straggler's own trace), capture-budgeted like
            # the recorder's incidents. Each finished capture is
            # machine-read on the spot (obs/traceview.py): op time
            # attributed onto the cost model's component keys via the
            # compiled step's HLO metadata, summary onto the sidecar +
            # manifest (docs/profiling.md).
            from sav_tpu.obs.autoprof import AutoProfiler

            _op_index_memo: list = []

            def _autoprof_op_index():
                # {hlo op -> metadata scope} from the step's compiled
                # HLO. The AOT executable's text is free; the jit path
                # lowers+compiles once from the abstract shapes —
                # bounded post-capture side work (runs at most once per
                # fit, only after an anomaly capture actually finished),
                # never steady-state. Memoized including failure: a
                # backend that cannot re-lower should not retry per
                # capture.
                if _op_index_memo:
                    return _op_index_memo[0]
                index = None
                try:
                    from sav_tpu.obs.traceview import parse_hlo_op_index

                    if compiled_step is not None:
                        text = compiled_step.as_text()
                    elif autoprof_abstract is not None:
                        a_state, a_batch, a_rng = autoprof_abstract
                        text = self._train_step.lower(
                            a_state, a_batch, a_rng
                        ).compile().as_text()
                    else:
                        text = None
                    if text:
                        index = parse_hlo_op_index(text)
                except Exception:
                    index = None
                _op_index_memo.append(index)
                return index

            autoprof = AutoProfiler(
                obs_dir,
                trace_steps=cfg.autoprof_steps,
                max_captures=cfg.autoprof_max,
                process_index=fleet_proc,
                manifest=manifest,
                op_index_fn=_autoprof_op_index,
            )
        watchdog = None
        if cfg.watchdog_secs:
            from sav_tpu.obs.watchdog import HangWatchdog

            def _on_watchdog_soft(silent_s, _hb=fleet_hb, _ap=autoprof):
                # Warning-stage evidence (watchdog thread, host-only):
                # a fleet event marks WHEN this process stalled in the
                # shared artifact layout, and the profiler arms so a
                # stall that resumes slowly gets captured.
                at_step = start_step + ledger.steps
                if _hb is not None:
                    _hb.fleet_event(
                        "watchdog_soft", silent_s=round(silent_s, 1),
                        at_step=at_step,
                    )
                if _ap is not None:
                    _ap.request("watchdog_soft", at_step)

            # NOTE: the deadline must exceed the longest legitimate gap
            # between completed steps — an eval pass or checkpoint save
            # counts one beat at its end, so size watchdog_secs above the
            # slowest of those, not just above the step time. The soft
            # stage (cfg.watchdog_soft_secs) warns + snapshots below it
            # without aborting.
            watchdog = HangWatchdog(
                cfg.watchdog_secs, ledger=ledger, tag="train-watchdog",
                manifest=manifest, recorder=recorder,
                # Pre-exit drain of any in-flight async save (bounded):
                # os._exit skips fit()'s finally, and a save abandoned
                # mid-commit is work the next attempt re-pays.
                checkpointer=self.checkpointer,
                soft_deadline_s=cfg.watchdog_soft_secs,
                on_soft=_on_watchdog_soft,
            )
        # Cost model (sav_tpu/obs/costs.py): an analytic per-layer-group
        # FLOPs estimate exists up front on any backend; the total is
        # upgraded to XLA's exact cost-analysis count when the AOT path
        # compiles. Attribution gauges publish immediately so even a
        # crashed run's manifest says where the FLOPs were going.
        from sav_tpu.obs.costs import (
            publish_cost_gauges,
            publish_mfu_gauges,
            train_step_cost,
        )

        peak_flops, peak_source = self._resolve_peak()
        cost = train_step_cost(
            state.params,
            batch_size=cfg.global_batch_size,
            image_size=cfg.image_size,
            n_devices=len(jax.devices()),
        )
        step_flops: Optional[float] = cost.flops or None
        publish_cost_gauges(
            ledger, cost, peak_flops=peak_flops, peak_source=peak_source
        )
        if autoprof is not None:
            # The predicted side of every capture's measured-vs-predicted
            # attribution table (attribution stays analytic even when the
            # AOT path upgrades the total — same keys either way).
            autoprof.set_predicted(cost.attribution)
        # HBM watermark (sav_tpu.obs.memdump): peak device occupancy,
        # observed at log boundaries (host-side counter read, no sync)
        # and stamped into the manifest as a first-class field in the
        # finally — OOM post-mortems and the sentinel read it without
        # the goodput file.
        from sav_tpu.obs.memdump import HbmWatermark

        watermark = HbmWatermark()
        if manifest is not None:
            device0 = jax.devices()[0]
            manifest.note("backend", {
                "platform": device0.platform,
                "device_kind": getattr(device0, "device_kind", None),
                "n_devices": len(jax.devices()),
                "process_count": jax.process_count(),
            })
            # Layout provenance: "which layout was this run" reads from
            # this one note (rendered by run_report/fleet_status).
            manifest.note("layout", self.layout.describe(self.mesh))
            manifest.note(
                "cost_model", _cost_note(cost, peak_flops, peak_source)
            )
        # The step is compiled ahead-of-time ONCE (and the loop calls the
        # compiled executable — cost analysis comes from the same
        # compilation, not a second one; AOT .compile() does not populate
        # the jit dispatch cache) only when the peak is a real hardware
        # number: under the CPU fake peak the loop keeps the plain jit
        # dispatch path, whose retrace behavior the sanitizer/diagnostics
        # contracts (and their tests) rely on.
        use_aot = bool(peak_flops) and peak_source in (
            "device-table", "override"
        )
        compiled_step = None
        # Sequence-parallel batch-replication fallback: surface the
        # trace-time event ONCE per fit — a warning, a span-trace instant,
        # a ledger gauge, and a manifest note — instead of a per-call
        # UserWarning (degraded parallelism must be machine-visible, not
        # log spam).
        unsub_replication = None
        if cfg.sequence_parallel:
            from sav_tpu.parallel import seq_parallel as _seq_parallel

            _replication_seen: list = []

            def _on_replication(info):
                if _replication_seen:
                    return
                _replication_seen.append(info)
                warnings.warn(
                    "sequence-parallel batch-replication fallback: batch "
                    f"{info['batch']} does not divide the mesh's data-axis "
                    f"product {info['data_axis_product']}; attention "
                    "memory/compute is multiplied by that product for the "
                    "whole fit (reported once; see manifest "
                    "notes.seq_replication_fallback)",
                    stacklevel=2,
                )
                tracer.instant("seq_replication_fallback", **info)
                # set_gauge coerces to float itself (info is a plain host
                # dict — no device value anywhere near this path).
                ledger.set_gauge("seq/replicated_batch", info["batch"])
                if manifest is not None:
                    manifest.note("seq_replication_fallback", info)

            unsub_replication = _seq_parallel.on_batch_replication(
                _on_replication
            )
        start_step = int(jax.device_get(state.step))  # savlint: disable=SAV101 -- one-time read before the loop, not per-step
        t_last = time.time()
        last_logged_step = start_step
        last_saved_step = None
        # Wall anchor for the checkpoint_every_secs cadence; reset on
        # every save so epoch/step-cadence saves push the timer out.
        t_last_ckpt = time.time()
        # jax.profiler trace window (SURVEY.md §5): capture a few steady-state
        # steps, skipping compile/warmup. Relative to start_step so resumed
        # runs still profile.
        prof_start = start_step + cfg.profile_start_step
        prof_stop = prof_start + max(cfg.profile_num_steps, 1)
        profiling = False
        # Wall time of the current logging window attributable to training
        # compute (dispatch + log sync); attributed to the ledger's step /
        # stall buckets at each log boundary (per-window anomaly flags).
        window_s = 0.0
        data_iter = iter(train_iter)
        feeder = None
        if cfg.async_feed:
            # Async double-buffered device feed (sav_tpu/data/feeder.py):
            # a background thread fetches host batches and issues the
            # sharded device_put, so transfer of batch N+1 overlaps the
            # device's step N instead of preceding it. The loop below then
            # only ever blocks on the bounded queue (booked as
            # input_wait); the training thread issues no device_put.
            # NOTE the feeder runs up to feed_depth + 1 batches ahead of
            # the consumed step; on preemption the prefetched batches are
            # dropped and re-produced by the resumable iterator (which
            # replays from the checkpointed step, not iterator position).
            from sav_tpu.data.feeder import DeviceFeeder

            # With the recorder on, the place callback additionally
            # fingerprints + retains the host batch on the feeder's
            # thread — hashing overlaps device compute like the placement
            # itself does.
            place_fn = (
                recorder.wrap_place(self.shard_batch)
                if recorder is not None else self.shard_batch
            )
            feeder = DeviceFeeder(
                data_iter, place_fn, depth=cfg.feed_depth,
                name="train-feeder",
            )
        # Dispatch run-ahead bound (see the step_dispatch block below);
        # metrics are tiny device scalars, so the deque itself is free.
        max_inflight = cfg.feed_depth + 1
        inflight_metrics: deque = deque()
        try:
            for step in range(start_step, num_steps):
                if autoprof is not None:
                    # Host-side state machine: starts an armed anomaly
                    # capture at this step boundary, stops one whose
                    # bounded window is over. No device syncs — the
                    # window is approximate by design.
                    autoprof.on_step(step)
                if cfg.profile_dir is not None:
                    # Steps dispatch asynchronously: sync the device at both
                    # window edges so the trace covers exactly the intended
                    # steps, not a few ms of host dispatch.
                    if not profiling and prof_start <= step < prof_stop:
                        jax.block_until_ready(state)  # savlint: disable=SAV101 -- profiler window edge: trace must cover exactly the intended steps
                        profiler.start_trace(cfg.profile_dir)  # savlint: disable=SAV113 -- THE armed static window opening (profile_dir), gated to its configured edge
                        profiling = True
                    elif profiling and step >= prof_stop:
                        jax.block_until_ready(state)  # savlint: disable=SAV101 -- profiler window edge: trace must cover exactly the intended steps
                        profiler.stop_trace()  # savlint: disable=SAV113 -- THE armed static window closing at its configured edge
                        profiling = False
                if feeder is not None:
                    # Placed batches arrive ready; the only critical-path
                    # cost left is the residual queue wait.
                    with tracer.span("batch_wait", step=step + 1), \
                            ledger.measure("input_wait"):
                        try:
                            sharded = next(feeder)
                        except StopIteration:
                            break
                else:
                    with tracer.span("batch_fetch", step=step + 1), \
                            ledger.measure("input_wait"):
                        try:
                            batch = next(data_iter)
                        except StopIteration:
                            break
                    if recorder is not None:
                        recorder.observe_batch(batch)
                    with tracer.span("shard_batch", step=step + 1), \
                            ledger.measure("h2d"):
                        sharded = self.shard_batch(batch)  # savlint: disable=SAV106 -- the sanctioned serial fallback (async_feed=False)
                if recorder is not None and recorder.wants_snapshot(step):
                    # The one sync recording adds: a periodic pre-step state
                    # copy (every record_snapshot_every steps) so an
                    # incident bundle can replay from a nearby step.
                    recorder.snapshot(step, jax.device_get(state))  # savlint: disable=SAV101 -- periodic pre-step recorder snapshot at the configured cadence, not a per-step sync
                if use_aot and compiled_step is None:
                    from sav_tpu.utils.flops import compiled_flops

                    with tracer.span("compile"), ledger.measure("compile"):
                        compiled_step = self._train_step.lower(
                            state, sharded, rng
                        ).compile()
                        aot_flops = compiled_flops(compiled_step)
                    if aot_flops:
                        # Upgrade the analytic total to XLA's exact
                        # per-device count; attribution fractions stay
                        # analytic (the XLA total does not decompose).
                        import dataclasses as _dc

                        step_flops = aot_flops
                        cost = _dc.replace(
                            cost, flops=aot_flops,
                            source="xla-cost-analysis",
                        )
                        publish_cost_gauges(
                            ledger, cost, peak_flops=peak_flops,
                            peak_source=peak_source,
                        )
                        if manifest is not None:
                            manifest.note(
                                "cost_model",
                                _cost_note(cost, peak_flops, peak_source),
                            )
                    # Don't let compile time pollute the first throughput
                    # and MFU window.
                    t_last = time.time()
                step_fn = compiled_step if compiled_step is not None else self._train_step
                t_step = time.perf_counter()
                with tracer.span("step_dispatch", step=step + 1):
                    state, metrics = step_fn(state, sharded, rng)
                # Cap dispatch run-ahead the same way evaluate() does:
                # every dispatched-not-retired step holds its placed input
                # batch in HBM, and with the feeder keeping the host fast
                # nothing else blocks before the log boundary (up to
                # log_every_steps batches live). Waiting on the metrics of
                # the step max_inflight back retires its inputs while the
                # queue ahead stays full, so placed-batch exposure is
                # feed_depth (queued) + max_inflight (dispatched). Booked
                # into the step window: it is device-compute wait.
                inflight_metrics.append(metrics)
                if len(inflight_metrics) > max_inflight:
                    jax.block_until_ready(  # savlint: disable=SAV101 -- run-ahead cap: device-compute wait that retires placed inputs
                        inflight_metrics.popleft()
                    )
                if recorder is not None:
                    # Host-only bookkeeping (pairs the dispatched step with
                    # its observed batch); never touches device values.
                    recorder.on_step(step + 1)
                dispatch_s = time.perf_counter() - t_step
                if step == start_step and compiled_step is None:
                    # The first jit dispatch blocks through trace+compile;
                    # bucket it as compile (it carries one step of device
                    # time too — noise next to a multi-minute relay
                    # compile).
                    ledger.account("compile", dispatch_s)
                else:
                    window_s += dispatch_s
                if step == start_step:
                    if autoprof is not None and autoprof_abstract is None:
                        # Shapes of the step's arguments (host metadata
                        # only — no buffer retention of the donated
                        # state): the lazy HLO op-index source when the
                        # jit path has no AOT executable to read.
                        autoprof_abstract = jax.tree.map(
                            lambda x: jax.ShapeDtypeStruct(
                                x.shape, x.dtype,
                                sharding=getattr(x, "sharding", None),
                            ),
                            (state, sharded, rng),
                        )
                    if retraces is not None:
                        # The first dispatch's trace is expected
                        # compilation, not a re-trace; swallow it so
                        # retraces=0 on a healthy run's first log window.
                        retraces.delta()
                    if sanitizer is not None:
                        # Steady state starts now: implicit host->device
                        # transfers and step retraces are hard errors
                        # from the next iteration on.
                        sanitizer.arm()
                elif sanitizer is not None:
                    # Tracing happens synchronously at dispatch, so a
                    # retrace is attributable to exactly this step.
                    sanitizer.check(step + 1)
                if cfg.debug_nans:
                    assert_all_finite(metrics, f"metrics at step {step + 1}")
                if (step + 1) % cfg.log_every_steps == 0 or step + 1 == num_steps:
                    t_sync = time.perf_counter()
                    with tracer.span("log_sync", step=step + 1):
                        m = {
                            k: float(v)
                            for k, v in jax.device_get(metrics).items()  # savlint: disable=SAV101 -- the per-log-window metrics sync; priced into the step bucket
                        }
                    window_s += time.perf_counter() - t_sync
                    now = time.time()
                    m["step"] = step + 1
                    steps_since = step + 1 - last_logged_step
                    if ledger.note_window(steps_since, window_s, step=step + 1):
                        tracer.instant("stall_anomaly", step=step + 1)
                        if autoprof is not None:
                            autoprof.request("stall_anomaly", step + 1)
                    if autoprof is not None:
                        # Wall per-step (host view: includes input wait +
                        # collective wait, unlike the ledger's dispatch
                        # window) through the robust spike gate.
                        autoprof.note_window(
                            step + 1,
                            (now - t_last) / max(steps_since, 1),
                        )
                    window_s = 0.0
                    m["images_per_sec"] = (
                        cfg.global_batch_size * steps_since / max(now - t_last, 1e-9)
                    )
                    if step_flops and peak_flops:
                        # Model-FLOPs utilization, per chip: cost_analysis
                        # FLOPs are per-device (sav_tpu/utils/flops.py) —
                        # the north star in its own unit (BASELINE.md).
                        step_s = max(now - t_last, 1e-9) / max(steps_since, 1)
                        m["mfu"] = step_flops / step_s / peak_flops
                    if cfg.diagnostics:
                        # Host-side telemetry sampled only at log boundaries:
                        # HBM occupancy ({} on backends without memory_stats)
                        # and silent-recompilation detection.
                        hbm = hbm_stats()
                        m.update(hbm)
                        watermark.observe(hbm)
                        if retraces is not None:
                            m["retraces"] = float(retraces.delta())
                    else:
                        # The watermark samples regardless of diagnostics
                        # (a host-side counter read — no device sync; {}
                        # on CPU, backfilled once at finalize).
                        watermark.observe()
                    t_last = now
                    last_logged_step = step + 1
                    history.append(m)
                    if log_fn is not None:
                        log_fn(m)
                    if recorder is not None:
                        # Incident detection piggybacks on the metrics this
                        # window already synced: nonfinite values or a loss
                        # beyond the robust spike gate dump a bundle.
                        trigger = recorder.note_metrics(step + 1, m)
                        if trigger:
                            incident = recorder.dump_incident(
                                trigger, step + 1
                            )
                            if incident is not None:
                                tracer.instant(
                                    "incident", step=step + 1,
                                    trigger=trigger,
                                )
                    if fleet_hb is not None:
                        # Fleet heartbeat: one appended line from values
                        # this window already holds on the host (the
                        # synced metrics dict + the ledger's wall-clock
                        # aggregates) — SAV112 pins the path sync-free.
                        fleet_hb.beat(
                            step + 1, ledger=ledger, metrics=m,
                            incident=(
                                recorder.incidents[-1]["path"]
                                if recorder is not None
                                and recorder.incidents else None
                            ),
                        )
                    if self.checkpointer is not None and (
                        step + 1
                    ) != last_saved_step:
                        # Step-granular cadences (docs/elasticity.md):
                        # piggyback on the log boundary — the metrics
                        # sync above already drained the pipeline, and
                        # Orbax's async path writes on the side, so the
                        # cadence adds no step-time pause of its own.
                        # Steps-since-last-save (NOT a step-number
                        # modulo, which would only ever fire at
                        # lcm(N, log_every_steps) when the cadences
                        # misalign): the save lands at the first log
                        # boundary >= N steps after the previous save.
                        since_save = (step + 1) - (
                            last_saved_step
                            if last_saved_step is not None else start_step
                        )
                        due = (
                            cfg.checkpoint_every_steps
                            and since_save >= cfg.checkpoint_every_steps
                        ) or (
                            cfg.checkpoint_every_secs is not None
                            and now - t_last_ckpt
                            >= cfg.checkpoint_every_secs
                        )
                        if due:
                            with tracer.span("checkpoint", step=step + 1), \
                                    ledger.measure("checkpoint"):
                                self._save_with_stamp(step + 1, state)
                            last_saved_step = step + 1
                            t_last_ckpt = time.time()
                epoch_done = (step + 1) % cfg.steps_per_epoch == 0
                if epoch_done:
                    epoch = (step + 1) // cfg.steps_per_epoch
                    if eval_iter_fn is not None and epoch % cfg.eval_every_epochs == 0:
                        with tracer.span("eval", epoch=epoch), \
                                ledger.measure("eval"):
                            em = self.evaluate(
                                state, eval_iter_fn(), recorder=recorder
                            )
                        em["step"] = step + 1
                        history.append(em)
                        if log_fn is not None:
                            log_fn(em)
                    if (
                        self.checkpointer is not None
                        and epoch % cfg.checkpoint_every_epochs == 0
                        and (step + 1) != last_saved_step
                    ):
                        with tracer.span("checkpoint", step=step + 1), \
                                ledger.measure("checkpoint"):
                            self._save_with_stamp(step + 1, state)
                        last_saved_step = step + 1
                        t_last_ckpt = time.time()
                    # Reset the throughput window so eval/checkpoint wall time
                    # doesn't deflate the next logged images_per_sec.
                    t_last = time.time()
                    if step + 1 != last_logged_step:
                        # Steps since the last log boundary haven't been
                        # noted yet (steps_per_epoch not a multiple of
                        # log_every_steps): book their window now so the
                        # ledger's per-step medians stay honest.
                        if ledger.note_window(
                            step + 1 - last_logged_step, window_s,
                            step=step + 1,
                        ):
                            tracer.instant("stall_anomaly", step=step + 1)
                            if autoprof is not None:
                                autoprof.request("stall_anomaly", step + 1)
                        window_s = 0.0
                        last_logged_step = step + 1
                if watchdog is not None:
                    # Armed only after the first completed step: compile
                    # belongs to backend_probe's startup regime, steady
                    # state is the watchdog's.
                    if step == start_step:
                        watchdog.start()
                    else:
                        watchdog.beat()
            if window_s:
                # StopIteration cut the run between log boundaries.
                ledger.account("step", window_s)
            if watchdog is not None:
                # The step loop is done; the final save/wait below can
                # legitimately exceed the steady-state deadline on a slow
                # relay, and firing there would corrupt the checkpoint.
                watchdog.stop()
            if self.checkpointer is not None:
                if last_saved_step != num_steps:
                    with tracer.span("checkpoint", step=num_steps), \
                            ledger.measure("checkpoint"):
                        self._save_with_stamp(num_steps, state)
                with ledger.measure("checkpoint"):
                    # The watchdog was stopped above precisely so this
                    # final flush can take as long as the relay needs.
                    self.checkpointer.wait()  # savlint: disable=SAV123 -- bounding the final checkpoint flush would truncate the save; watchdog already stopped
        finally:
            if recorder is not None:
                exc = sys.exc_info()[1]
                # Skip when the failure already dumped on the way out (a
                # nonfinite mid-fit eval dumps 'eval_nonfinite' and THEN
                # raises under debug_nans) — a second bundle at the same
                # step would just burn the incident budget on a copy.
                already_dumped = bool(recorder.incidents) and (
                    recorder.incidents[-1]["step"]
                    == (recorder.last_step or 0)
                )
                if (
                    exc is not None
                    and not isinstance(exc, StopIteration)
                    and not already_dumped
                ):
                    # The crash path: dump whatever context the ring holds
                    # so the failing step is reproducible even when nothing
                    # upstream detected it (debug_nans raises per-step,
                    # before the log-boundary detection ever sees it).
                    recorder.dump_incident(
                        "nonfinite"
                        if isinstance(exc, FloatingPointError)
                        else "exception",
                        error=repr(exc),
                    )
                for k, v in recorder.stats().items():
                    ledger.set_gauge(f"recorder/{k}", v)
            if cfg.memdump and obs_dir is not None:
                # Memory forensics on allocator exhaustion
                # (sav_tpu.obs.memdump, docs/profiling.md): the state is
                # still live HERE — by the time train.py's handler
                # classifies the exception the buffers are gone, so the
                # live-buffer ranking must be taken on the way out.
                exc = sys.exc_info()[1]
                if exc is not None and not isinstance(exc, StopIteration):
                    from sav_tpu.obs.manifest import classify_exception
                    from sav_tpu.obs.memdump import dump_memory_incident

                    if classify_exception(exc) == "oom":
                        dump_memory_incident(  # savlint: disable=SAV113 -- OOM incident path: the run is already dead, forensics cannot cost it anything
                            obs_dir,
                            step=start_step + ledger.steps,
                            error=repr(exc),
                            state=state,
                            watermark=watermark,
                            cost=cost,
                            manifest=manifest,
                        )
            if feeder is not None:
                # Publish the worker-side counters as ledger gauges (they
                # are overlapped background time + queue depths, not
                # training-thread wall time — see obs/goodput.py), then
                # stop the worker so a mid-run exception can't leave it
                # blocked holding placed device buffers.
                for k, v in feeder.stats().items():
                    ledger.set_gauge(f"feeder/{k}", v)
                feeder.close()
            if watchdog is not None:
                watchdog.stop()
            if self.checkpointer is not None:
                # Abnormal exits must not abandon an in-flight async
                # save: Orbax commits by atomic rename, so an un-awaited
                # save is *lost* (re-paid by the next attempt), never
                # torn — but draining it here keeps the newest step. The
                # wait is BOUNDED (a crash escaping a wedged filesystem
                # must not inherit the very hang it is escaping) and runs
                # AFTER the watchdog disarms, so a slow drain on a crash
                # path cannot be misclassified as a steady-state hang.
                with ledger.measure("checkpoint"):
                    if not self.checkpointer.wait(timeout_s=120.0):
                        print(
                            "trainer: in-flight checkpoint save still "
                            "unfinished after 120s; abandoning it (the "
                            "previous committed step remains restorable)",
                            file=sys.stderr,
                        )
            if autoprof is not None:
                # A crash (or normal exit) inside a capture window still
                # leaves a finished, manifest-stamped trace behind — at
                # the CURRENT step, so the capture's step span (and the
                # per_step_ms the analysis divides by) stays honest.
                autoprof.finalize(start_step + ledger.steps)
                for k, v in autoprof.stats().items():
                    ledger.set_gauge(f"autoprof/{k}", v)
            if fleet_hb is not None:
                for k, v in fleet_hb.stats().items():
                    ledger.set_gauge(f"fleet/{k}", v)
                exc = sys.exc_info()[1]
                fleet_hb.close(
                    outcome="ok"
                    if exc is None or isinstance(exc, StopIteration)
                    else "error"
                )
                if fleet_hb.process_index == 0:
                    # Merged fleet manifest (FLEET process 0's in-run
                    # view — offline tools recompute over the final
                    # streams): step skew, straggler ranking, dead-host
                    # suspicion. Gated on the fleet identity, not
                    # obs_writer, so identity-overridden fleets still
                    # get exactly one writer.
                    from sav_tpu.obs.fleet import (
                        aggregate_fleet,
                        write_fleet_manifest,
                    )

                    try:
                        fleet_summary = aggregate_fleet(obs_dir)
                        fleet_path = write_fleet_manifest(
                            obs_dir, fleet_summary
                        )
                        if manifest is not None and fleet_path is not None:
                            manifest.note("fleet", {
                                "path": fleet_path,
                                "processes": {
                                    p: {
                                        "heartbeats": v.get("heartbeats"),
                                        "last_step": v.get("last_step"),
                                        "outcome": v.get("outcome"),
                                    }
                                    for p, v in fleet_summary.get(
                                        "processes", {}
                                    ).items()
                                },
                                "step_skew": fleet_summary.get("step_skew"),
                                "straggler": (
                                    fleet_summary.get("straggler") or {}
                                ).get("straggler"),
                                "suspects": [
                                    s.get("proc")
                                    for s in fleet_summary.get(
                                        "suspects", []
                                    )
                                ],
                            })
                    except Exception:
                        pass  # fleet aggregation is telemetry, never fatal
            if sanitizer is not None:
                # Thread-local config context: must unwind on this (the
                # entering) thread before fit returns.
                sanitizer.close()
            if unsub_replication is not None:
                unsub_replication()
            if profiling:
                profiler.stop_trace()  # savlint: disable=SAV113 -- crash inside the armed static window: close it so the trace survives
            # End-of-run roofline gauges (goodput/mfu, goodput/flops_per_s)
            # from the ledger's own aggregates — no device sync involved.
            # In the finally so crashed runs report too, and the manifest
            # carries whatever telemetry exists at the point of death.
            publish_mfu_gauges(
                ledger,
                step_flops=step_flops or 0.0,
                peak_flops=peak_flops,
                steps=ledger.steps,
                step_seconds=ledger.bucket_seconds("step"),
            )
            # HBM watermark: one final sample (+ the live-arrays backfill
            # on backends without memory stats) stamped as a first-class
            # manifest field on every exit path — the sentinel and OOM
            # post-mortems read it without the goodput file.
            wm = watermark.finalize()
            if wm["peak_bytes"]:
                ledger.set_gauge("hbm/peak_bytes", wm["peak_bytes"])
            if manifest is not None:
                manifest.note("hbm", wm)
                manifest.set_metrics({
                    **ledger.flat_metrics(),
                    "hbm_peak_bytes": wm["peak_bytes"],
                })
                # Attention-dispatch provenance: which backend + block
                # config every traced attention shape resolved to (filled
                # at trace time, so it exists once the step compiled —
                # including on crash paths after the first trace).
                from sav_tpu.ops.attention import snapshot_dispatch_log

                dispatch = snapshot_dispatch_log()
                if dispatch:
                    manifest.note("attention_dispatch", dispatch)
            tracer.write()
        self.last_goodput = ledger.summary()
        if obs_dir is not None and obs_writer:
            os.makedirs(obs_dir, exist_ok=True)
            with open(os.path.join(obs_dir, "goodput.json"), "w") as f:
                json.dump(self.last_goodput, f, indent=2)
        goodput_record = {
            "step": int(jax.device_get(state.step)),  # savlint: disable=SAV101 -- post-loop summary read
            **ledger.flat_metrics(),
        }
        history.append(goodput_record)
        if log_fn is not None:
            log_fn(goodput_record)
        return state, history
