"""Orbax checkpointing with restore (the reference only ever saved —
/root/reference/train.py:123-127; restore was never wired, SURVEY.md §5).

Async, sharded-aware saves via ``orbax.checkpoint.CheckpointManager``;
``restore_latest`` makes runs preemption-safe: on restart the trainer
resumes from the last step automatically, falling back to the previous
step when the newest checkpoint is unreadable (a preemption can land
anywhere; one torn artifact must not strand the whole run). ``wait``
takes an optional bound so crash paths can drain an in-flight async save
without inheriting the hang they are escaping (docs/elasticity.md).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


def _tree_paths(tree: Any, prefix: tuple = ()) -> list:
    """Flatten any nested dict/list/tuple metadata tree into path tuples
    (leaves = anything non-container). Orbax item metadata arrives as
    plain containers, so no pytree registry is needed."""
    if isinstance(tree, dict):
        out = []
        for key, value in tree.items():
            out.extend(_tree_paths(value, prefix + (str(key),)))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for i, value in enumerate(tree):
            out.extend(_tree_paths(value, prefix + (str(i),)))
        return out
    return [prefix]


def detect_opt_layout(paths: list) -> dict:
    """Classify a checkpoint's optimizer-state layout from its tree paths.

    Two config knobs change the opt-state pytree structure and must match
    the checkpoint at restore (the mismatch otherwise surfaces as an
    opaque tree-structure error):

    - ``fused_optimizer``: ``optax.flatten`` stores the Adam moments as
      ONE flat array per moment — the ``mu``/``nu`` segments are leaves.
      The per-leaf layout mirrors the parameter tree below them.
    - ``ema_decay``: ``track_params_ema`` adds an ``ema`` subtree.

    Returns ``{"fused": bool|None, "ema": bool}`` — ``None`` when the
    checkpoint has no recognizable Adam moments (nothing to detect).
    """
    fused: Optional[bool] = None
    ema = False
    for path in paths:
        for i, seg in enumerate(path):
            if seg == "ema":
                ema = True
            if seg in ("mu", "nu"):
                # Leaf directly at mu/nu → flat buffer; anything nested
                # below it → per-leaf moment tree.
                fused = (i == len(path) - 1) if fused is None else (
                    fused and i == len(path) - 1
                )
    return {"fused": fused, "ema": ema}


class Checkpointer:
    def __init__(self, directory: str, *, keep: int = 3, read_only: bool = False):
        """``read_only`` opens an existing checkpoint dir for restore-only
        use (warm starts): no directory creation — a typo'd path raises
        instead of materializing an empty dir — and no retention policy."""
        self._dir = os.path.abspath(directory)
        if read_only:
            if not os.path.isdir(self._dir):
                raise FileNotFoundError(
                    f"checkpoint directory does not exist: {self._dir!r}"
                )
            try:
                options = ocp.CheckpointManagerOptions(read_only=True)
            except TypeError:  # older orbax without the flag
                options = ocp.CheckpointManagerOptions(create=False)
        else:
            os.makedirs(self._dir, exist_ok=True)
            options = ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            )
        # The item handler is registered up front so ``item_metadata``
        # (the opt-state layout probe) works on a FRESH manager — a
        # restarted process probes before its first save/restore, and
        # without the registration orbax returns a placeholder.
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=options,
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    @property
    def directory(self) -> str:
        return self._dir

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        """Committed checkpoint steps, ascending."""
        return sorted(self._mgr.all_steps())

    def opt_layout(self, step: Optional[int] = None) -> dict:
        """Probe the saved opt-state layout without loading any arrays
        (:func:`detect_opt_layout` over the checkpoint's metadata tree).
        ``{}`` when there is no checkpoint or the probe fails — callers
        treat that as "nothing to detect", never as an error."""
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return {}
        try:
            meta = self._mgr.item_metadata(step)
            # CompositeArgs-style wrappers hold the real tree under the
            # item name; unwrap defensively across orbax versions.
            for attr in ("tree", "item_metadata"):
                meta = getattr(meta, attr, meta)
            paths = [
                p for p in _tree_paths(_plain(meta)) if "opt_state" in p
            ]
            if not paths:
                return {}
            return detect_opt_layout(paths)
        except Exception:
            return {}

    def restore_latest(self, template: Any) -> Optional[Any]:
        """Restore the newest loadable checkpoint into ``template``'s
        structure/shardings.

        Returns None when no checkpoint exists. When the newest step
        fails to load (torn by a preemption mid-save, bit rot), older
        steps are tried in turn — a warning names the fallback — and the
        *newest* step's error is re-raised only when every retained step
        fails (so structural mismatches keep their original diagnosis).
        """
        steps = self.all_steps()
        if not steps:
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        first_error: Optional[Exception] = None
        for step in reversed(steps):
            try:
                restored = self._mgr.restore(
                    step, args=ocp.args.StandardRestore(abstract)
                )
            except Exception as e:  # noqa: BLE001 — every orbax failure
                if first_error is None:
                    first_error = e
                else:
                    logging.warning(
                        "checkpoint step %d also failed to restore: %r",
                        step, e,
                    )
                continue
            if first_error is not None:
                logging.warning(
                    "newest checkpoint failed to restore (%r); resumed "
                    "from the older step %d instead",
                    first_error, step,
                )
            return restored
        raise first_error

    def restore_params_only(
        self, template: Any, step: Optional[int] = None
    ) -> Optional[Any]:
        """Restore ``params``/``batch_stats``/``step`` WITHOUT reading
        opt_state — the serving path (docs/serving.md).

        A training checkpoint's optimizer state is 2-3x the parameter
        bytes (Adam moments, optionally EMA); an inference engine that
        restored the full TrainState would spend most of its HBM on
        buffers it immediately drops. This restores through orbax's
        partial-tree path (``PyTreeRestore(item=subset, transforms={})``)
        so the opt_state arrays are never read off disk, let alone
        materialized on device — and because opt_state is skipped
        entirely, flat-buffer and per-leaf moment layouts (the PR-9
        auto-detect distinction, :func:`detect_opt_layout`) are both
        accepted without an optimizer rebuild; the probed layout is only
        logged for provenance.

        Args:
          template: ``{"params": ..., "batch_stats": ..., "step": ...}``
            of concrete arrays or ``jax.ShapeDtypeStruct`` leaves;
            leaves carrying a ``sharding`` restore directly onto it.
          step: checkpoint step (default: newest).

        Returns the restored template-structured dict, or None when the
        directory holds no checkpoint.
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        layout = self.opt_layout(step)
        if layout:
            logging.info(
                "params-only restore from step %d (skipping %s opt-state "
                "layout%s)",
                step,
                "flat-buffer" if layout.get("fused") else "per-leaf",
                " + EMA" if layout.get("ema") else "",
            )
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        restore_args = jax.tree.map(
            lambda s: ocp.ArrayRestoreArgs(
                dtype=s.dtype, sharding=getattr(s, "sharding", None)
            ),
            abstract,
        )
        # A read-only PyTree-handler manager over the same directory:
        # StandardSave writes through PyTreeCheckpointHandler, so the
        # on-disk layout is shared; only PyTreeRestore exposes the
        # partial-tree ``transforms`` path.
        try:
            options = ocp.CheckpointManagerOptions(read_only=True)
        except TypeError:  # pragma: no cover - older orbax
            options = ocp.CheckpointManagerOptions(create=False)
        reader = ocp.CheckpointManager(
            self._dir,
            options=options,
            item_handlers=ocp.PyTreeCheckpointHandler(),
        )
        try:
            return reader.restore(
                step,
                args=ocp.args.PyTreeRestore(
                    item=abstract, transforms={}, restore_args=restore_args
                ),
            )
        finally:
            reader.close()

    def restore_raw(self, step: Optional[int] = None) -> Optional[Any]:
        """Restore a checkpoint in its *saved* structure (no template).

        For warm starts across architectures/resolutions, where the saved
        shapes deliberately differ from the current state's (e.g. the
        224-pretrain position table loaded into a 384 finetune —
        ``sav_tpu.models.surgery`` resamples it afterwards).
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block until in-flight async saves commit.

        ``timeout_s`` bounds the wait (crash paths and the watchdog's
        pre-exit drain must not inherit the hang they are escaping —
        docs/elasticity.md); returns False when the bound expired with a
        save still in flight. Orbax commits each step by atomic rename,
        so an abandoned wait can leave a *missing* newest step, never a
        torn one — ``restore_latest``'s fallback covers the rest.
        """
        if timeout_s is None:
            self._mgr.wait_until_finished()
            return True
        done = threading.Event()

        def _wait():
            try:
                self._mgr.wait_until_finished()
            finally:
                done.set()

        threading.Thread(
            target=_wait, name="checkpoint-wait", daemon=True
        ).start()
        return done.wait(timeout_s)

    def close(self) -> None:
        self._mgr.close()


def _plain(meta: Any) -> Any:
    """Orbax metadata tree → plain containers (best effort): metadata
    objects occasionally wrap dicts in Mapping views."""
    if isinstance(meta, dict):
        return {k: _plain(v) for k, v in meta.items()}
    if isinstance(meta, (list, tuple)):
        # Lists ARE the result (namedtuple-saved nodes come back as
        # sequences whose constructors don't take an iterable).
        return [_plain(v) for v in meta]
    try:  # Mapping-like (orbax CompositeResults)
        items = dict(meta.items())
    except (AttributeError, TypeError):
        return meta
    return {k: _plain(v) for k, v in items.items()}
