"""Orbax checkpointing with restore (the reference only ever saved —
/root/reference/train.py:123-127; restore was never wired, SURVEY.md §5).

Async, sharded-aware saves via ``orbax.checkpoint.CheckpointManager``;
``restore_latest`` makes runs preemption-safe: on restart the trainer
resumes from the last step automatically.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(self, directory: str, *, keep: int = 3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )

    @property
    def directory(self) -> str:
        return self._dir

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, template: Any) -> Optional[Any]:
        """Restore the newest checkpoint into ``template``'s structure/shardings.

        Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
