"""Orbax checkpointing with restore (the reference only ever saved —
/root/reference/train.py:123-127; restore was never wired, SURVEY.md §5).

Async, sharded-aware saves via ``orbax.checkpoint.CheckpointManager``;
``restore_latest`` makes runs preemption-safe: on restart the trainer
resumes from the last step automatically.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class Checkpointer:
    def __init__(self, directory: str, *, keep: int = 3, read_only: bool = False):
        """``read_only`` opens an existing checkpoint dir for restore-only
        use (warm starts): no directory creation — a typo'd path raises
        instead of materializing an empty dir — and no retention policy."""
        self._dir = os.path.abspath(directory)
        if read_only:
            if not os.path.isdir(self._dir):
                raise FileNotFoundError(
                    f"checkpoint directory does not exist: {self._dir!r}"
                )
            try:
                options = ocp.CheckpointManagerOptions(read_only=True)
            except TypeError:  # older orbax without the flag
                options = ocp.CheckpointManagerOptions(create=False)
        else:
            os.makedirs(self._dir, exist_ok=True)
            options = ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    @property
    def directory(self) -> str:
        return self._dir

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, template: Any) -> Optional[Any]:
        """Restore the newest checkpoint into ``template``'s structure/shardings.

        Returns None when no checkpoint exists.
        """
        step = self._mgr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))

    def restore_raw(self, step: Optional[int] = None) -> Optional[Any]:
        """Restore a checkpoint in its *saved* structure (no template).

        For warm starts across architectures/resolutions, where the saved
        shapes deliberately differ from the current state's (e.g. the
        224-pretrain position table loaded into a 384 finetune —
        ``sav_tpu.models.surgery`` resamples it afterwards).
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            return None
        return self._mgr.restore(step, args=ocp.args.StandardRestore())

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
