"""Preemption-tolerant supervised training — bounded restarts, manifest
chains, rewind-and-skip, and goodput-loss accounting.

Two of five on-chip bench rounds died ``backend_unreachable`` (BENCH_r03/
r05): at production scale preemption and chip loss are the steady state,
not the exception. The observability substrate already *names* every
failure — the backend probe exits 3, the hang watchdog exits 4, the run
manifest stamps ``nonfinite``/``oom``/``error`` on crash paths, the
flight recorder dumps the offending batches — but nothing *survived*
them: a killed run stayed dead until a human restarted it, and the lost
wall time vanished from every ledger.

:class:`Supervisor` closes that loop, PaLM-style (Chowdhery et al. 2022
rewound and skipped bad batches; MegaScale, Jiang et al. 2024, attributes
its goodput to exactly this automation):

- **Bounded restarts.** The child ``train.py`` is re-spawned on failure
  with exponential backoff, up to ``max_restarts``. Exit 0 ends the
  chain; exit 2 (usage error) is terminal — restarting a typo does not
  help. Everything else (probe exit 3, watchdog exit 4, crash, signal
  kill) restarts. Resume is the trainer's own step-exact restore: the
  supervisor only observes the checkpoint directory, it never touches
  jax (same philosophy as ``utils.backend_probe`` — the parent must
  stay alive precisely when backend init would hang).
- **Manifest chain.** Each attempt's ``manifest.json`` is preserved
  under ``<log_dir>/attempts/`` before the next attempt overwrites it,
  and one supervisor manifest (``supervisor.json`` — a regular
  :class:`~sav_tpu.obs.manifest.RunManifest`, so the sentinel and
  ``run_report`` read it natively) carries the chain: per-attempt
  outcome, restart reason, resumed-from step, wall/lost seconds.
- **Goodput accounting.** Lost wall time is booked as
  ``goodput/lost_s``: for a failed attempt, wall time minus the step
  time of the steps that *survived* into the next attempt's restore
  point (per-step time read from the attempt's own fleet heartbeats —
  flushed per line, so even a SIGKILL leaves them). ``goodput_frac`` =
  1 − (lost + backoff)/wall is a first-class, sentinel-gateable metric,
  and ``accounted_frac`` proves the chain explains where the wall time
  went.
- **Rewind-and-skip.** When an attempt dies ``nonfinite``, the flight
  recorder's incident bundle names the offending step; the next attempt
  gets ``--skip-steps <step>`` so the resumed data stream drops exactly
  that batch (the data-plane half, :func:`skip_step_batches`, is
  applied by ``train.py``). Each step is skipped at most once per chain
  — a NaN that survives its batch being skipped is a model/optimizer
  problem, and looping on it would silently eat the dataset.

Import contract: stdlib-only at module scope (no jax, no numpy). The
supervisor runs in the parent process of on-chip jobs, where importing
the backend is exactly what hangs; ``tools/run_report.py --chain`` reads
chains on laptops. The batch-fingerprint helpers import numpy lazily.

See docs/elasticity.md for the exit-code table and chain schema.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Iterator, Optional

from sav_tpu.obs.manifest import OUTCOMES, RunManifest

CHAIN_SCHEMA = 1

#: Exit codes with contract meaning (docs/elasticity.md):
#:   0 — done;  2 — usage error (terminal, restarting cannot help);
#:   3 — backend unreachable (utils.backend_probe);  4 — hang watchdog.
EXIT_OK = 0
EXIT_USAGE = 2
EXIT_BACKEND = 3
EXIT_HANG = 4

# Supervisor-only CLI flags stripped from the child's argv. Maps flag →
# whether it consumes a value argument.
SUPERVISOR_FLAGS = {
    "--supervise": False,
    "--max-restarts": True,
    "--restart-backoff": True,
}


def strip_supervisor_flags(argv: list, extra_value_flags: tuple = ()) -> list:
    """Child argv = the supervisor's argv minus the supervisor-only flags
    (both ``--flag value`` and ``--flag=value`` spellings).

    ``extra_value_flags``: additional value-taking flags to strip —
    ``train.py --supervise`` strips the user's ``--skip-steps`` and seeds
    the supervisor's cumulative skip ledger with it instead, so the
    supervisor-appended skip set (which includes the user's) is the only
    one the child sees (click's last-value-wins would otherwise drop
    whichever came first).
    """
    flags = dict(SUPERVISOR_FLAGS)
    for name in extra_value_flags:
        flags[name] = True
    out = []
    skip_next = False
    for arg in argv:
        if skip_next:
            skip_next = False
            continue
        name = arg.split("=", 1)[0]
        if name in flags:
            skip_next = flags[name] and "=" not in arg
            continue
        out.append(arg)
    return out


def parse_skip_steps(spec: Optional[str]) -> set:
    """``"120,121"`` → {120, 121} (1-indexed completed-step numbers)."""
    if not spec:
        return set()
    steps = set()
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            step = int(part)
        except ValueError:
            raise ValueError(
                f"--skip-steps entries must be integers, got {part!r}"
            ) from None
        if step < 1:
            raise ValueError(
                f"--skip-steps entries are 1-indexed step numbers, got {step}"
            )
        steps.add(step)
    return steps


def skip_step_batches(
    it: Iterator[dict],
    skip_steps: set,
    *,
    start_step: int = 0,
    on_skip: Optional[Callable[[int, dict], None]] = None,
) -> Iterator[dict]:
    """Drop the batches at the named *schedule positions* (PaLM-style
    rewind-and-skip, the data-plane half).

    Positions are 1-indexed steps of the uninterrupted schedule: position
    ``p`` is the batch the original run consumed at step ``p``. Dropping
    shifts every later batch one step earlier — the bad example is never
    trained on, the total step count is unchanged (exactly the published
    rewind-and-skip semantics). ``start_step`` anchors the counter for
    resumed streams (the iterator's first batch is position
    ``start_step + 1``). ``on_skip(position, batch)`` fires once per
    dropped batch — train.py wires it to a manifest note carrying the
    batch's blake2b fingerprint so the skip is auditable.
    """
    pending = set(skip_steps)
    it = iter(it)

    def gen():
        pos = start_step
        for batch in it:
            pos += 1
            while pos in pending:
                pending.discard(pos)
                if on_skip is not None:
                    on_skip(pos, batch)
                try:
                    batch = next(it)
                except StopIteration:
                    return
                pos += 1
            yield batch

    return gen()


def resume_schedule_position(step: int, skip_steps) -> int:
    """Original-schedule position of the batch consumed at ``step`` once
    ``skip_steps`` positions have been dropped.

    Rewind-and-skip shifts the stream: after dropping position ``p``,
    step ``s >= p`` consumes a LATER original batch. A restart that
    resumes after a skip must rebuild its (position-keyed) data stream
    from this shifted position — and keep the full chain-level skip set
    — or it would re-train an already-consumed batch and desync the
    effective schedule from the skip-applied reference. Both train.py
    (stream construction) and the chaos verifier (expected-hash
    recomputation) use this one function, so they cannot drift.
    """
    pos = step
    for p in sorted(set(skip_steps)):
        if p <= pos:
            pos += 1
    return pos


# --------------------------------------------------------- chaos injection


def chaos_wrap(
    it: Iterator[dict],
    *,
    start_step: int = 0,
    env: Optional[dict] = None,
) -> Iterator[dict]:
    """Fault-injection seam for the chaos harness (tools/chaos_soak.py).

    Env-gated and position-keyed so it is a no-op in production and
    deterministic under restarts (positions are uninterrupted-schedule
    steps, like :func:`skip_step_batches`):

      SAV_CHAOS_NAN_STEP=N   — replace the batch at position N's images
                               with NaN (float batches only): the step
                               goes nonfinite, debug_nans kills the run,
                               the recorder dumps the bundle — the
                               planted incident rewind-and-skip must cure.
      SAV_CHAOS_HANG_STEP=N  — sleep SAV_CHAOS_HANG_SECS (default 3600)
                               before yielding position N: no step
                               completes, the watchdog's exit-4 contract
                               fires.
      SAV_CHAOS_ONCE_DIR=D   — fire the hang at most once across the
                               whole restart chain (a marker file in D
                               records it). Without this a restarted run
                               replays position N and hangs again: a NaN
                               has a cure (skip the batch), a hang does
                               not — it models a transient infra fault.

    NaN re-injection after a restart is intended: the poisoned position
    is data, and the skip wrapper (applied *outside* this one) drops it.
    """
    env = env if env is not None else os.environ
    nan_at = env.get("SAV_CHAOS_NAN_STEP")
    hang_at = env.get("SAV_CHAOS_HANG_STEP")
    if not nan_at and not hang_at:
        return it
    nan_at = int(nan_at) if nan_at else None
    hang_at = int(hang_at) if hang_at else None
    hang_secs = float(env.get("SAV_CHAOS_HANG_SECS", 3600.0))
    once_dir = env.get("SAV_CHAOS_ONCE_DIR")

    def _hang_armed(pos: int) -> bool:
        if once_dir is None:
            return True
        marker = os.path.join(once_dir, f"chaos_hang_{pos}.fired")
        if os.path.exists(marker):
            return False
        try:
            os.makedirs(once_dir, exist_ok=True)
            with open(marker, "w") as f:
                f.write(str(time.time()))
        except OSError:
            pass  # marker failure must not turn the fault off
        return True

    def gen():
        import numpy as np  # lazy: module import stays stdlib-only

        pos = start_step
        for batch in it:
            pos += 1
            if hang_at is not None and pos == hang_at and _hang_armed(pos):
                time.sleep(hang_secs)
            if nan_at is not None and pos == nan_at:
                batch = dict(batch)
                images = np.array(batch["images"], copy=True)
                if images.dtype.kind != "f":
                    raise ValueError(
                        "SAV_CHAOS_NAN_STEP needs a float batch to poison, "
                        f"got {images.dtype} (run the chaos child without "
                        "--device-preprocess)"
                    )
                images[...] = np.nan
                batch["images"] = images
            yield batch

    return gen()


# ------------------------------------------------------------ chain reading


def latest_checkpoint_step(checkpoint_dir: Optional[str]) -> Optional[int]:
    """Newest *committed* checkpoint step, read without orbax/jax.

    Orbax commits a step by atomically renaming its temp directory to the
    bare step number, so integer-named directories are exactly the
    committed set (in-flight saves carry a ``.orbax-checkpoint-tmp``
    suffix and are skipped).
    """
    if not checkpoint_dir or not os.path.isdir(checkpoint_dir):
        return None
    steps = [
        int(name)
        for name in os.listdir(checkpoint_dir)
        if name.isdigit() and os.path.isdir(os.path.join(checkpoint_dir, name))
    ]
    return max(steps) if steps else None


def read_attempt_heartbeats(log_dir: str, pid: int) -> list:
    """This attempt's heartbeat records (``kind: hb``) from the shared
    ``fleet/proc_0.jsonl`` stream, filtered by the child's pid — attempts
    append to one file, the pid tells them apart. Torn tails skipped."""
    path = os.path.join(log_dir, "fleet", "proc_0.jsonl")
    records = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a killed writer
                if rec.get("kind") == "hb" and rec.get("pid") == pid:
                    records.append(rec)
    except OSError:
        pass
    return records


def newest_incident(log_dir: str) -> Optional[dict]:
    """Newest flight-recorder incident bundle's ``incident.json`` (with
    its path under ``"path"``), or None. Memdump bundles are skipped —
    they carry no step context to rewind to."""
    root = os.path.join(log_dir, "incidents")
    if not os.path.isdir(root):
        return None
    best = None
    for name in sorted(os.listdir(root)):
        if not name.startswith("step_"):
            continue
        path = os.path.join(root, name, "incident.json")
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        doc["path"] = os.path.dirname(path)
        if best is None or doc.get("created_unix", 0) >= best.get(
            "created_unix", 0
        ):
            best = doc
    return best


def load_chain(log_dir: str) -> Optional[dict]:
    """The supervisor manifest (``<log_dir>/supervisor.json``) as a dict,
    or None when the run was never supervised."""
    path = os.path.join(log_dir, "supervisor.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def verify_chain(
    doc: dict,
    *,
    min_accounted: float = 0.99,
    expect_attempts: Optional[int] = None,
) -> list:
    """Structural checks on a supervisor manifest; returns a list of
    problem strings (empty = verified). The chaos harness layers its
    data-level checks (batch-hash match, loss continuity, skip-once) on
    top of this."""
    problems = []
    if doc.get("outcome") != "ok":
        problems.append(f"chain outcome is {doc.get('outcome')!r}, not ok")
    chain = (doc.get("notes") or {}).get("chain") or {}
    attempts = chain.get("attempts") or []
    if not attempts:
        problems.append("chain has no attempts")
        return problems
    if expect_attempts is not None and len(attempts) != expect_attempts:
        problems.append(
            f"expected {expect_attempts} attempts, chain has {len(attempts)}"
        )
    metrics = doc.get("metrics") or {}
    accounted = metrics.get("accounted_frac")
    if not isinstance(accounted, (int, float)):
        problems.append("no accounted_frac metric")
    elif accounted < min_accounted:
        problems.append(
            f"goodput accounting covers only {accounted:.2%} of wall time "
            f"(< {min_accounted:.0%})"
        )
    if not isinstance(metrics.get("goodput_frac"), (int, float)):
        problems.append("no goodput_frac metric")
    for a in attempts[:-1]:
        if a.get("restart_reason") is None:
            problems.append(
                f"attempt {a.get('attempt')} restarted without a reason"
            )
    if attempts[-1].get("exit_code") != 0:
        problems.append(
            f"final attempt exit code {attempts[-1].get('exit_code')}"
        )
    return problems


# --------------------------------------------------------------- supervisor


def _signal_name(code: int) -> str:
    try:
        return signal.Signals(-code).name
    except (ValueError, ImportError):
        return f"SIG{-code}"


def classify_exit(
    exit_code: Optional[int], manifest_outcome: Optional[str]
) -> str:
    """Restart-reason label for one attempt: the child's own finalized
    manifest outcome when it got far enough to write one, else the exit
    code's contract meaning (a SIGKILL leaves the manifest at 'running',
    which means nothing — the signal is the fact)."""
    if manifest_outcome in OUTCOMES and manifest_outcome != "ok":
        return manifest_outcome
    if exit_code == EXIT_OK:
        return "ok"
    if exit_code is not None and exit_code < 0:
        return f"killed:{_signal_name(exit_code)}"
    if exit_code == EXIT_BACKEND:
        return "backend_unreachable"
    if exit_code == EXIT_HANG:
        return "hang"
    if exit_code == EXIT_USAGE:
        return "usage_error"
    return f"crash:rc={exit_code}"


class Supervisor:
    """Run a training command under bounded-restart supervision.

    Args:
      child_argv: full child command (``[sys.executable, "train.py", ...]``).
      log_dir: the run's telemetry sink (shared with the child): the
        supervisor manifest, preserved attempt manifests, and the
        heartbeat/incident artifacts it reads all live here.
      checkpoint_dir: the child's ``-c`` directory — observed (stdlib
        directory listing only, never orbax) for resumed-from steps.
      max_restarts: restart budget (attempts = restarts + 1).
      backoff_base_s / backoff_max_s: exponential restart backoff
        (base · 2^(restart−1), capped). Deterministic — no jitter — so
        soak chains replay.
      capture: redirect each attempt's stdout+stderr to
        ``attempts/attempt_<k>.out`` (the chaos harness's mode) instead
        of inheriting the supervisor's.
      skip_steps: initial rewind-and-skip ledger (the user's own
        ``--skip-steps``, stripped from the child argv by train.py); the
        cumulative set — initial + incident-decided — is passed to EVERY
        attempt so the schedule shift survives later restarts.
      on_spawn: callback ``(attempt, popen)`` — the chaos harness's kill
        hook.
      env: extra child environment (merged over ``os.environ``).
      serve: serve-mode chain (the PR-15 replica fleet,
        sav_tpu/serve/fleet.py): a serving child never exits 0 on its
        own — it serves until told to stop — so the chain's success
        path is :meth:`request_stop` (the pool calls it, then SIGTERMs
        the child): once a stop is requested, the NEXT child exit ends
        the chain with outcome ``ok`` regardless of the raw code (a
        SIGTERM-killed server is a completed serve, not a crash), and
        its wall time is never booked as lost. Rewind-and-skip is
        training-only and stays off this path (serving has no schedule
        to rewind).
      manifest_src: the child manifest the per-attempt preservation
        copies aside (default ``<log_dir>/manifest.json``; serve
        replicas write ``manifest-serve-r<rank>.json`` into the SHARED
        fleet log dir, which is not this supervisor's chain dir).
      sleep / clock: injectable for tests.

    The supervisor itself never imports jax (the parent of an on-chip
    job must not be hangable by the backend) and never exits the
    process: :meth:`run` *returns* the chain's exit code.
    """

    def __init__(
        self,
        child_argv: list,
        *,
        log_dir: str,
        checkpoint_dir: Optional[str],
        max_restarts: int = 16,
        backoff_base_s: float = 5.0,
        backoff_max_s: float = 300.0,
        capture: bool = False,
        on_spawn: Optional[Callable] = None,
        env: Optional[dict] = None,
        skip_steps=None,
        serve: bool = False,
        manifest_src: Optional[str] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.time,
    ):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.child_argv = list(child_argv)
        self.log_dir = log_dir
        self.checkpoint_dir = checkpoint_dir
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.capture = capture
        self.on_spawn = on_spawn
        self.env = dict(env) if env else {}
        self.serve = bool(serve)
        self.manifest_src = manifest_src
        self._stop_requested = threading.Event()
        self._sleep = sleep
        self._clock = clock
        self.child: Optional[subprocess.Popen] = None
        self.attempts: list = []
        self.skipped_steps: set = set(skip_steps or ())
        self._backoff_total = 0.0
        self.manifest = RunManifest(
            os.path.join(log_dir, "supervisor.json"),
            kind="supervisor",
            argv=list(child_argv),
        )

    def request_stop(self) -> None:
        """Mark the chain as deliberately stopping (serve mode's success
        path — the pool calls this BEFORE signalling the child so the
        resulting exit ends the chain instead of burning a restart).
        Callable from any thread; the caller still delivers the signal."""
        self._stop_requested.set()

    @property
    def stop_requested(self) -> bool:
        return self._stop_requested.is_set()

    # ------------------------------------------------------------- internals

    def _attempt_dir(self) -> str:
        path = os.path.join(self.log_dir, "attempts")
        os.makedirs(path, exist_ok=True)
        return path

    def _preserve_manifest(self, attempt: int) -> Optional[str]:
        """Copy the attempt's manifest aside before the next attempt
        overwrites it; returns the preserved path + parsed outcome."""
        src = self.manifest_src or os.path.join(self.log_dir, "manifest.json")
        if not os.path.exists(src):
            return None
        dst = os.path.join(
            self._attempt_dir(), f"attempt_{attempt:03d}.manifest.json"
        )
        try:
            with open(src) as f:
                payload = f.read()
            tmp = f"{dst}.tmp"
            with open(tmp, "w") as f:
                f.write(payload)
            os.replace(tmp, dst)
            return dst
        except OSError:
            return None

    def _manifest_outcome(self, preserved: Optional[str]) -> Optional[str]:
        if preserved is None:
            return None
        try:
            with open(preserved) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        outcome = doc.get("outcome")
        return outcome if outcome in OUTCOMES else None

    def _decide_skip(
        self, outcome: Optional[str], since_unix: float
    ) -> list:
        """Rewind-and-skip decision after a ``nonfinite`` death: skip the
        incident bundle's recorded step, once per chain.

        ``since_unix``: the dead attempt's start time — a bundle created
        before it is a LEFTOVER from an earlier run sharing the log dir
        (or an attempt that dumped nothing this time), and skipping its
        step would drop a good batch while the real bad one replays.
        """
        if outcome != "nonfinite":
            return []
        incident = newest_incident(self.log_dir)
        if incident is None:
            return []
        created = incident.get("created_unix")
        # 1s slack: the bundle's clock and ours are the same host's, but
        # the dump may have started microseconds around the spawn stamp.
        if isinstance(created, (int, float)) and created < since_unix - 1.0:
            print(
                "supervisor: newest incident bundle "
                f"({incident.get('path')}) predates this attempt — "
                "treating it as stale, no rewind-and-skip",
                file=sys.stderr,
            )
            return []
        steps = []
        step = incident.get("step")
        # A replay verdict (tools/replay_step.py) names the first bad
        # step more precisely than the detection step; prefer it.
        verdict_path = os.path.join(
            incident.get("path", ""), "replay_verdict.json"
        )
        try:
            with open(verdict_path) as f:
                first_bad = json.load(f).get("first_bad_step")
            if isinstance(first_bad, int):
                step = first_bad
        except (OSError, json.JSONDecodeError):
            pass
        if isinstance(step, int) and step >= 1:
            if step not in self.skipped_steps:
                self.skipped_steps.add(step)
                steps.append(step)
        return steps

    def _account(self) -> dict:
        """Chain-level goodput accounting over the attempts so far.

        Per failed attempt: salvaged = steps that survived into the next
        attempt's restore point; lost = wall − salvaged · per-step time
        (per-step from the attempt's own heartbeats, falling back to the
        chain median). A successful attempt loses nothing; restart
        *backoff* is booked separately. ``accounted_frac`` is the share
        of supervisor wall time the chain explains (attempt walls +
        backoff) — the ≥99% soak criterion.
        """
        per_steps = [
            a["per_step_s"] for a in self.attempts
            if a.get("per_step_s") is not None
        ]
        fallback = (
            sorted(per_steps)[len(per_steps) // 2] if per_steps else None
        )
        lost_total = 0.0
        for i, a in enumerate(self.attempts):
            if a.get("exit_code") == EXIT_OK or a.get("stopped"):
                # A requested stop (serve mode) is a completed serve,
                # not lost wall — the replica was serving until told
                # to exit.
                a["lost_s"] = 0.0
                continue
            nxt = (
                self.attempts[i + 1] if i + 1 < len(self.attempts) else None
            )
            resumed_next = (
                nxt.get("resumed_from_step") if nxt is not None
                else latest_checkpoint_step(self.checkpoint_dir)
            )
            salvaged = max(
                (resumed_next or 0) - (a.get("resumed_from_step") or 0), 0
            )
            a["salvaged_steps"] = salvaged
            per_step = a.get("per_step_s") or fallback
            if per_step is not None:
                lost = max(a["wall_s"] - salvaged * per_step, 0.0)
            else:
                # Died before the first heartbeat: nothing salvageable
                # was measured — the whole attempt is lost time.
                lost = a["wall_s"]
            a["lost_s"] = round(lost, 3)
            lost_total += lost
        wall = max(self._clock() - self._t0, 1e-9)
        attempts_wall = sum(a["wall_s"] for a in self.attempts)
        return {
            "wall_s": round(wall, 3),
            "attempts_wall_s": round(attempts_wall, 3),
            "lost_s": round(lost_total, 3),
            "backoff_s": round(self._backoff_total, 3),
            "goodput_frac": round(
                max(1.0 - (lost_total + self._backoff_total) / wall, 0.0), 6
            ),
            "accounted_frac": round(
                min((attempts_wall + self._backoff_total) / wall, 1.0), 6
            ),
        }

    def _publish(self, goodput: dict) -> None:
        self.manifest.note("chain", {
            "schema": CHAIN_SCHEMA,
            "attempts": self.attempts,
            "skipped_steps": sorted(self.skipped_steps),
            "goodput": goodput,
        })
        self.manifest.set_metrics({
            "attempts": float(len(self.attempts)),
            "goodput_frac": goodput["goodput_frac"],
            "accounted_frac": goodput["accounted_frac"],
            "goodput/lost_s": goodput["lost_s"],
            "goodput/backoff_s": goodput["backoff_s"],
        })

    # ------------------------------------------------------------------ run

    def run(self) -> int:
        """Supervise until success, a terminal failure, or budget
        exhaustion; returns the exit code for the caller to exit with."""
        self._t0 = self._clock()
        self.manifest.begin()
        attempt = 0
        while True:
            attempt += 1
            resumed_from = latest_checkpoint_step(self.checkpoint_dir) or 0
            argv = list(self.child_argv)
            if self.skipped_steps:
                # The CUMULATIVE skip set rides every attempt: a skip
                # shifts every later batch one step earlier, and a
                # restart resuming past the skipped position must
                # rebuild its stream from the shifted position
                # (resume_schedule_position in train.py) — dropping the
                # set after one attempt would re-train a consumed batch.
                argv += [
                    "--skip-steps",
                    ",".join(map(str, sorted(self.skipped_steps))),
                ]
            env = dict(os.environ)
            env.update(self.env)
            env["SAV_SUPERVISED_ATTEMPT"] = str(attempt)
            out = None
            if self.capture:
                out = open(
                    os.path.join(
                        self._attempt_dir(), f"attempt_{attempt:03d}.out"
                    ),
                    "w",
                )
            t_start = self._clock()
            try:
                self.child = subprocess.Popen(
                    argv, env=env,
                    stdout=out if out is not None else None,
                    stderr=subprocess.STDOUT if out is not None else None,
                )
            except OSError as e:
                if out is not None:
                    out.close()
                self.manifest.finalize(
                    "error", error=f"spawn failed: {e!r}", exit_code=1
                )
                return 1
            if self.on_spawn is not None:
                try:
                    self.on_spawn(attempt, self.child)
                except Exception:
                    pass  # a chaos-hook bug must not kill supervision
            try:
                # Supervising IS waiting: the CHILD's watchdog bounds the
                # child (exit-4); the supervisor has no deadline of its
                # own to enforce on top.
                rc = self.child.wait()  # savlint: disable=SAV123 -- child liveness is the child watchdog's contract; an outer timeout would re-implement it worse
            finally:
                if out is not None:
                    out.close()
            wall = self._clock() - t_start
            preserved = self._preserve_manifest(attempt)
            outcome = self._manifest_outcome(preserved)
            reason = classify_exit(rc, outcome)
            beats = read_attempt_heartbeats(self.log_dir, self.child.pid)
            last_hb = beats[-1] if beats else None
            per_step = None
            if last_hb and last_hb.get("steps"):
                step_s = (last_hb.get("b") or {}).get("step")
                if isinstance(step_s, (int, float)) and step_s > 0:
                    per_step = step_s / last_hb["steps"]
            record = {
                "attempt": attempt,
                "pid": self.child.pid,
                "start_unix": round(t_start, 3),
                "wall_s": round(wall, 3),
                "exit_code": rc,
                "outcome": outcome or ("ok" if rc == 0 else "running"),
                "restart_reason": None if rc == EXIT_OK else reason,
                "resumed_from_step": resumed_from,
                "last_step": (
                    last_hb.get("step") if last_hb else resumed_from
                ),
                "per_step_s": (
                    round(per_step, 6) if per_step is not None else None
                ),
                "skip_steps": sorted(self.skipped_steps),
                "manifest": (
                    os.path.relpath(preserved, self.log_dir)
                    if preserved else None
                ),
            }
            if self._stop_requested.is_set():
                # Serve-mode success path: the pool asked the chain to
                # stop, then signalled the child — whatever code the
                # dying server returned, this is a completed serve, not
                # a failure to restart from.
                record["stopped"] = True
                record["outcome"] = outcome or "ok"
                record["restart_reason"] = None
                self.attempts.append(record)
                goodput = self._account()
                self._publish(goodput)
                self.manifest.finalize(
                    "ok", exit_code=0, notes={"stop_requested": True}
                )
                return 0
            self.attempts.append(record)
            if rc == EXIT_OK:
                goodput = self._account()
                self._publish(goodput)
                self.manifest.finalize("ok", exit_code=0)
                return 0
            if rc == EXIT_USAGE:
                goodput = self._account()
                self._publish(goodput)
                self.manifest.finalize(
                    "error",
                    error="child usage error (exit 2): restarting cannot "
                    "help; fix the command line",
                    exit_code=EXIT_USAGE,
                )
                return EXIT_USAGE
            decided = (
                [] if self.serve else self._decide_skip(outcome, t_start)
            )
            if decided:
                self.attempts[-1]["skip_decided"] = list(decided)
            restarts_used = attempt - 1
            if restarts_used >= self.max_restarts:
                goodput = self._account()
                self._publish(goodput)
                final = outcome if outcome in OUTCOMES else "error"
                self.manifest.finalize(
                    final if final != "ok" else "error",
                    error=(
                        f"restart budget exhausted after {attempt} attempts "
                        f"(last: {reason})"
                    ),
                    exit_code=rc if isinstance(rc, int) and rc > 0 else 1,
                )
                return rc if isinstance(rc, int) and rc > 0 else 1
            backoff = min(
                self.backoff_base_s * (2 ** (attempt - 1)),
                self.backoff_max_s,
            )
            print(
                f"supervisor: attempt {attempt} ended ({reason}); "
                f"restarting in {backoff:.1f}s "
                f"(restart {attempt}/{self.max_restarts}"
                + (
                    f", rewind-and-skip step(s) {decided}"
                    if decided else ""
                )
                + ")",
                file=sys.stderr,
            )
            goodput = self._account()
            self._publish(goodput)
            t_sleep = self._clock()
            self._sleep(backoff)
            self._backoff_total += self._clock() - t_sleep
