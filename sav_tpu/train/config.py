"""Typed training configuration.

Replaces the reference's three disjoint config systems (click flags,
jaxline ml_collections dicts, and reflection-resolved optimizer names —
SURVEY.md §5 'Config / flag system') with one dataclass that serializes to
JSON next to the checkpoints. Defaults mirror the reference recipe
(/root/reference/train.py:130-220: 300 epochs, lr 5e-4 × bs/512, 5-epoch
warmup cosine, label smoothing 0.1, AdamW-style weight decay).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional


@dataclasses.dataclass
class TrainConfig:
    # Model
    model_name: str = "deit_s_patch16"
    num_classes: int = 1000
    image_size: int = 224
    compute_dtype: str = "bfloat16"
    # None=auto (three-way measured dispatch: fused-short / xla / flash by
    # shape band + the attn_tune cache — see sav_tpu/ops/attention.py) |
    # 'xla' | 'fused' | 'pallas'.
    attention_backend: Optional[str] = None
    # Path to a tools/attn_tune.py shape→config cache consulted by the
    # 'auto' dispatcher (block configs + measured backend winners per
    # attention shape). None = the SAV_ATTN_TUNE_CACHE env var, then the
    # checked-in default table (sav_tpu/ops/attn_tune_cache.json — the
    # PERF.md §5 measurements). Applied process-wide at Trainer
    # construction (trace-time state only; no jitted path reads it).
    attention_tune_cache: Optional[str] = None
    # Softmax dtype on the XLA attention path. None = inherit compute_dtype
    # (the reference's semantics: its logits einsum runs in the model
    # dtype). Under bf16 compute this halves the dominant [B,H,L,L] HBM
    # traffic (−15% step time on v5e, PERF.md §6) at ~2⁻⁸ relative logit
    # precision; accuracy-gated by tools/logits_dtype_gate.py (identical
    # final top-1 under f32 and bf16 compute — gated on the 48² digits
    # recipe only; re-gate on the first full-scale/197+-token run, where
    # bf16 softmax error compounds over more steps). Set 'float32' to
    # force f32 softmax under bf16 compute. Threaded as a model attribute
    # (create_model(..., logits_dtype=...)); ignored when Trainer is
    # handed an externally built model, which carries its own setting.
    attention_logits_dtype: Optional[str] = None
    # int8 quantized projection/FFN dots (sav_tpu/ops/quant.py, ISSUE 17):
    # "int8" = the AQT-style QAT training arm — per-channel symmetric
    # scales, int8×int8→int32 accumulation, STE forward, stochastic-
    # rounded int8 gradient dots (rng rides the trainer's fold_in ladder
    # as a "quant" stream). The param tree is byte-identical to the
    # float arm, so quant checkpoints convert to int8 serving trees via
    # sav_tpu.ops.quant.quantize_params (ServeConfig.quant_weights).
    # Attention QK/AV stays in compute_dtype (PERF §5: not matmul-
    # roofline-bound). None = the plain float path. Threaded as a model
    # attribute (create_model(..., quant=...)); an externally built
    # model carries its own setting.
    quant: Optional[str] = None
    # Extra kwargs for create_model (e.g. {'remat': True} to rematerialize
    # encoder blocks when activations are HBM-bound, or architecture
    # overrides like {'num_layers': 2} for smoke runs). Serialized with the
    # config; must be JSON-representable.
    model_overrides: Optional[dict] = None

    # Device-side batch finishing: the host pipeline ships post-augment
    # uint8 images (4x fewer host->device bytes than f32, 2x fewer than
    # late-bf16) and the jitted steps normalize + apply the augment
    # string's CutMix/MixUp on device with replayable jax.random draws
    # (sav_tpu/ops/preprocess.py). Pair with
    # load(device_preprocess=True) or savrec_train_iterator(normalize=False);
    # the savrec raw path ships NHWC only, so keep transpose_images=False
    # with it (the iterator rejects the combination).
    device_preprocess: bool = False

    # Async device feed (sav_tpu/data/feeder.py; docs/input_pipeline.md):
    # fit()/evaluate() pull batches through a background thread that
    # overlaps host fetch + sharded device_put with device compute
    # (double buffering). False restores the serial fetch→put→step loop
    # (the --no-async-feed escape hatch).
    async_feed: bool = True
    # Placed batches buffered beyond the one in flight (backpressure
    # bound). Placed-batch HBM exposure is feed_depth queued + 1 the
    # worker is placing + feed_depth + 1 dispatched-not-retired (fit and
    # evaluate both cap run-ahead at that); during an epoch-boundary
    # eval inside fit() the train feeder's queue stays full, so the two
    # bounds stack.
    feed_depth: int = 2
    # Persistent XLA compilation cache directory
    # (jax_compilation_cache_dir; sav_tpu/utils/compile_cache.py). Repeat
    # runs of the same program skip the multi-minute compile — the 493 s
    # TNT trace (PERF.md §12) becomes a disk read. None disables.
    compilation_cache_dir: Optional[str] = None

    # Data
    global_batch_size: int = 1024
    num_train_images: int = 1_281_167  # ImageNet-1k train
    augment: str = "cutmix_mixup_randaugment_405"
    transpose_images: bool = True  # HWCN double-transpose trick

    # Optimization
    num_epochs: int = 300
    base_lr: float = 5e-4  # scaled by global_batch/512 (train.py:214)
    lr_scaling_divisor: int = 512
    end_lr: float = 1e-5
    warmup_epochs: int = 5
    weight_decay: float = 0.05
    clip_grad_norm: Optional[float] = 1.0
    # Adam moment updates on one flat buffer (optax.flatten) — kills per-leaf
    # kernel-launch overhead. None = auto: on for pure data-parallel meshes,
    # off whenever a model/fsdp/expert axis exists (a flat moment vector
    # cannot shard like its parameters). False also keeps the per-leaf
    # opt-state layout of pre-round-3 checkpoints.
    fused_optimizer: Optional[bool] = None
    label_smoothing: float = 0.1
    # Parameter EMA (e.g. 0.9999): eval runs on the averaged weights (the
    # DeiT/CaiT-recipe standard). Lives in opt_state
    # (optimizer.track_params_ema), so it checkpoints/shards with the rest;
    # None keeps the opt-state layout of EMA-less checkpoints.
    ema_decay: Optional[float] = None
    aux_loss_weight: float = 0.01  # weight on sown 'losses' (MoE balance etc.)
    grad_accum_steps: int = 1  # micro-batches per optimizer update
    seed: int = 42

    # Mesh: axis name -> size (-1 absorbs remaining devices)
    mesh_axes: Optional[dict] = None
    # Declarative sharding layout (sav_tpu/parallel/layout.py;
    # docs/parallelism.md): a built-in name ('dp' | 'tpN' | 'fsdpN' |
    # '2dXxY') or the path of a preset JSON emitted by
    # tools/mesh_tune.py. States the mesh AND every param/activation
    # spec in one object; mutually exclusive with mesh_axes (two
    # sources of layout truth), stamped into the run manifest as
    # notes.layout. None keeps the mesh_axes-implied layout.
    layout_preset: Optional[str] = None
    # Sequence parallelism: 'ring' | 'ulysses' routes every self-attention
    # core through sav_tpu.parallel.seq_parallel over the mesh's 'seq'
    # axis (mesh_axes must include it; train.py --sp N builds both).
    # Exact numerics incl. CLS-odd lengths (pad-and-mask); self-attention
    # models only, deterministic attention only. Under SP the softmax
    # statistics are always f32 (an online-softmax requirement), so
    # attention_logits_dtype='bfloat16' does not apply, and the per-shard
    # core is dense XLA (attention_backend='pallas' is rejected; the bare
    # parallel.ring_attention op exposes flash mode for divisible lengths).
    sequence_parallel: Optional[str] = None
    # Pipeline parallelism: S > 1 pipelines the encoder stack of a
    # ViT-family model over the mesh's 'pipe' axis (GPipe microbatch
    # schedule, sav_tpu/models/pipelined.py; train.py --pp S builds the
    # mesh). The per-data-shard batch (global_batch_size / grad_accum_steps
    # / data-axis-size) must be divisible by pipeline_microbatches; bubble
    # fraction is (S-1)/(M+S-1). ViT family only; MoE and stage dropout
    # are rejected at construction.
    pipeline_parallel: Optional[int] = None
    pipeline_microbatches: int = 8

    # Logging / checkpointing
    eval_every_epochs: int = 5
    checkpoint_every_epochs: int = 10
    # Step-granular checkpoint cadences (docs/elasticity.md): save every
    # N completed steps and/or every T seconds, in ADDITION to the epoch
    # cadence. Saves fire at the trainer's log boundary — the step's
    # metrics sync already drained the pipeline there, and Orbax's async
    # checkpointing writes on the side — so a cadence adds no step-time
    # pause beyond the host-memory copy; both cadences count from the
    # LAST save, quantized up to the next log boundary (a misaligned
    # log_every_steps coarsens a save by at most one log window, never
    # to the lcm). This is what makes resume
    # step-exact mid-epoch (the resumable data stream replays from the
    # restored step; rng is a pure function of (seed, step)): without a
    # step cadence a preemption loses up to checkpoint_every_epochs of
    # work. None disables either cadence.
    checkpoint_every_steps: Optional[int] = None
    checkpoint_every_secs: Optional[float] = None
    checkpoint_dir: Optional[str] = None
    checkpoint_keep: int = 3
    log_every_steps: int = 100

    # Observability / debugging (SURVEY.md §5 — none of this existed in the
    # reference): optional jax.profiler trace window and NaN guards.
    profile_dir: Optional[str] = None
    profile_start_step: int = 10
    profile_num_steps: int = 5
    debug_nans: bool = False

    # Run telemetry (sav_tpu.obs; docs/observability.md).
    # Sink directory for spans.trace.json / goodput.json (None falls back
    # to checkpoint_dir, then cwd).
    log_dir: Optional[str] = None
    # In-jit optimization diagnostics folded into the step metrics
    # (param/update norms, update-to-param ratio, per-layer-group grad
    # norms, nonfinite counts) plus HBM + retrace telemetry at log time.
    # Rides the existing per-log device_get — zero extra transfers.
    diagnostics: bool = False
    # Host-side span tracer around fit()'s phases; writes a
    # Chrome-trace-event JSON (Perfetto-loadable) to <log_dir>.
    trace_spans: bool = False
    # Steady-state hang watchdog: abort with exit 4 + full stack dump when
    # no step completes within this many seconds (None disables). Armed
    # after the first step so compile time cannot false-fire it.
    watchdog_secs: Optional[float] = None
    # Watchdog soft (warning) stage (docs/fleet.md): when no step
    # completes within this many seconds — must be < watchdog_secs — the
    # watchdog dumps all thread stacks + a fleet-heartbeat event and arms
    # the anomaly profiler, but the run CONTINUES; only the hard
    # watchdog_secs deadline keeps the exit-4 contract. None disables
    # the soft stage.
    watchdog_soft_secs: Optional[float] = None
    # Fleet telemetry (sav_tpu.obs.fleet; docs/fleet.md): every process
    # appends a heartbeat record (step, goodput buckets, HBM/retrace
    # telemetry, last incident pointer) to <log_dir>/fleet/proc_<i>.jsonl
    # at the existing log boundary — zero extra device syncs (savlint
    # SAV112) — and process 0 writes the merged fleet manifest
    # (fleet/fleet.json: step skew, straggler ranking, dead-host
    # suspicion) at the end of fit. Requires a log_dir/checkpoint_dir
    # sink; render with tools/fleet_status.py or run_report.py --fleet.
    fleet: bool = True
    # Anomaly-triggered profiling (sav_tpu.obs.autoprof; docs/fleet.md):
    # when the goodput ledger flags a stall anomaly, a log window's
    # per-step time spikes past a robust median+MAD gate, or the
    # watchdog crosses its soft stage, arm jax.profiler for a bounded
    # autoprof_steps-step trace under <log_dir>/autoprof/, stamped into
    # the run manifest (notes.autoprof). Budgeted like the flight
    # recorder's incidents: at most autoprof_max captures per run.
    autoprof: bool = False
    autoprof_steps: int = 4
    autoprof_max: int = 2
    # Per-chip peak FLOP/s override for MFU/roofline accounting
    # (sav_tpu/obs/costs.py; train.py --peak-flops). None = resolve from
    # the device-kind table; unknown accelerators then report no MFU, and
    # CPU resolves to a deterministic fake peak (labeled 'cpu-fake') so
    # the attribution/MFU plumbing stays assertable in tier-1.
    peak_flops: Optional[float] = None
    # Flight recorder (sav_tpu.obs.recorder; docs/incident_replay.md):
    # keep a bounded ring of the last record_depth steps' host-side
    # context (batch content hash + shapes/dtypes, rng recipe, logged
    # metrics) plus the raw host batches of the newest record_batches
    # steps and a periodic pre-step TrainState snapshot every
    # record_snapshot_every steps (None = record_batches). On an incident
    # — nonfinite logged metrics, a loss spike beyond spike_sigma scaled
    # MADs, a watchdog hang, or an uncaught exception — fit() dumps a
    # replayable bundle under <log_dir>/incidents/step_<N>/ for
    # tools/replay_step.py. Steady-state cost is host-only bookkeeping
    # (no extra device syncs; savlint SAV111 enforces); the periodic
    # snapshot is the one pipeline drain recording adds.
    record: bool = False
    record_depth: int = 16
    record_batches: int = 4
    record_snapshot_every: Optional[int] = None
    # Loss-spike incident gate: flag a logged loss more than spike_sigma
    # scaled MADs above the rolling median of healthy windows (upward
    # only; 0 disables). Armed after 8 healthy windows so early-training
    # noise cannot false-fire.
    spike_sigma: float = 6.0
    # Memory forensics (sav_tpu.obs.memdump; docs/profiling.md): on an
    # oom-classified exception, dump an incident bundle under
    # <log_dir>/incidents/memdump_<step>/ — live-buffer ranking
    # classified against the training state, HBM snapshot + watermark,
    # per-group parameter-byte estimates, and a device-memory pprof
    # where the backend supports one. Steady-state cost is a host-side
    # memory_stats() counter read per log boundary (the HBM watermark,
    # stamped into the manifest on every exit path regardless of this
    # knob). On by default: forensics only run when the run is already
    # dead.
    memdump: bool = True
    # Runtime sanitizers (sav_tpu.analysis.sanitize;
    # docs/static_analysis.md): after the first completed step, arm
    # jax.transfer_guard_host_to_device("disallow") on the training
    # thread (an implicit host->device transfer in the hot loop raises —
    # the feeder's explicit device_puts on its own thread are exempt)
    # and hard-fail the run the moment the jitted step re-traces
    # (RetraceSanitizerError names the step; diagnostics' retrace
    # metric only reports at the next log window).
    sanitize: bool = False

    @property
    def steps_per_epoch(self) -> int:
        return self.num_train_images // self.global_batch_size

    @property
    def total_steps(self) -> int:
        return self.steps_per_epoch * self.num_epochs

    @property
    def learning_rate(self) -> float:
        return self.base_lr * self.global_batch_size / self.lr_scaling_divisor

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "TrainConfig":
        return cls(**json.loads(text))
