"""Named experiment presets.

Capability parity with the reference's jaxline experiment configs
(/root/reference/experiments/BoTNet/botnet_t3_imagenet.py:31-60: bs 2048,
300 epochs, cosine peak 1e-3, AdamW wd 0.05 on weights / plain Adam on
biases, bf16, ``cutmix_mixup_randaugment_405``) plus the model papers'
recipes that the zoo encodes (SURVEY.md §6) — expressed as
:class:`~sav_tpu.train.config.TrainConfig` constructors instead of
reflection-resolved ``ml_collections`` dicts.

The weight/bias optimizer split is the masked-AdamW in
:mod:`sav_tpu.train.optimizer` (AdamW with zero decay on a parameter IS
Adam, so one masked transform reproduces jaxline's two-group chain).

Usage::

    config = get_preset("botnet_t3_imagenet", checkpoint_dir="/ckpt")
    Trainer(config).fit(...)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from sav_tpu.train.config import TrainConfig

_PRESETS: dict[str, dict[str, Any]] = {}


def register_preset(name: str, **kwargs: Any) -> None:
    _PRESETS[name] = kwargs


def preset_names() -> list[str]:
    return sorted(_PRESETS)


def get_preset(name: str, **overrides: Any) -> TrainConfig:
    """Build the named TrainConfig, with field overrides applied on top."""
    if name not in _PRESETS:
        raise ValueError(
            f"unknown preset {name!r}; available: {', '.join(preset_names())}"
        )
    kwargs = dict(_PRESETS[name])
    kwargs.update(overrides)
    valid = {f.name for f in dataclasses.fields(TrainConfig)}
    unknown = set(kwargs) - valid
    if unknown:
        raise TypeError(f"invalid TrainConfig fields for preset {name}: {unknown}")
    return TrainConfig(**kwargs)


# --------------------------------------------------------------- ImageNet-1k

# The reference's one concrete experiment config (botnet_t3_imagenet.py):
# absolute peak LR 1e-3 at bs 2048 → expressed via divisor = batch size.
register_preset(
    "botnet_t3_imagenet",
    model_name="botnet_t3",
    global_batch_size=2048,
    num_epochs=300,
    base_lr=1e-3,
    lr_scaling_divisor=2048,
    warmup_epochs=5,
    weight_decay=0.05,
    label_smoothing=0.1,
    augment="cutmix_mixup_randaugment_405",
    compute_dtype="bfloat16",
)

# DeiT-S/16 (the north-star benchmark model): DeiT recipe — bs 1024,
# lr 5e-4 × bs/512, 300 epochs, wd 0.05, RA + cutmix/mixup.
register_preset(
    "deit_s_imagenet",
    model_name="deit_s_patch16",
    global_batch_size=1024,
    num_epochs=300,
    base_lr=5e-4,
    lr_scaling_divisor=512,
    warmup_epochs=5,
    weight_decay=0.05,
    label_smoothing=0.1,
    augment="cutmix_mixup_randaugment_405",
    compute_dtype="bfloat16",
)

register_preset(
    "vit_b_imagenet",
    model_name="vit_b_patch16",
    global_batch_size=1024,
    num_epochs=300,
    base_lr=5e-4,
    lr_scaling_divisor=512,
    weight_decay=0.05,
    augment="cutmix_mixup_randaugment_405",
)

# CaiT-S24: DeiT recipe + the per-size stochastic depth already baked into
# the registry config (create_model.py:79-168 parity).
register_preset(
    "cait_s24_imagenet",
    model_name="cait_s_24",
    global_batch_size=1024,
    num_epochs=300,
    base_lr=5e-4,
    lr_scaling_divisor=512,
    weight_decay=0.05,
    augment="cutmix_mixup_randaugment_405",
)

register_preset(
    "cvt_13_imagenet",
    model_name="cvt-13",
    global_batch_size=2048,
    num_epochs=300,
    base_lr=1e-3,
    lr_scaling_divisor=2048,
    weight_decay=0.05,
    augment="cutmix_mixup_randaugment_405",
)

register_preset(
    "tnt_s_imagenet",
    model_name="tnt_s_patch16",
    global_batch_size=1024,
    num_epochs=300,
    base_lr=5e-4,
    lr_scaling_divisor=512,
    weight_decay=0.05,
    augment="cutmix_mixup_randaugment_405",
)

register_preset(
    "ceit_s_imagenet",
    model_name="ceit_s",
    global_batch_size=1024,
    num_epochs=300,
    base_lr=5e-4,
    lr_scaling_divisor=512,
    weight_decay=0.05,
    augment="cutmix_mixup_randaugment_405",
)

register_preset(
    "mixer_b_imagenet",
    model_name="mixer_b_patch16",
    global_batch_size=4096,
    num_epochs=300,
    base_lr=1e-3,
    lr_scaling_divisor=4096,
    weight_decay=0.1,
    augment="cutmix_mixup_randaugment_405",
)

# ------------------------------------------------------------ smoke configs

# CPU-runnable end-to-end slice (BASELINE.json configs[0] shape).
register_preset(
    "vit_ti_cifar_smoke",
    model_name="vit_ti_patch16",
    num_classes=10,
    image_size=32,
    compute_dtype="float32",
    global_batch_size=64,
    num_train_images=50_000,
    num_epochs=2,
    warmup_epochs=1,
    transpose_images=False,
    augment="",
)

# Elasticity smoke (docs/elasticity.md): the chaos-soak / kill-resume
# child — a 2-layer ViT small enough that a CPU attempt restarts in
# seconds, float32 so resumed loss curves are bit-comparable against an
# uninterrupted reference, a long epoch (1000 steps) so every soak kill
# lands mid-epoch, and a tight log cadence so heartbeats (the supervisor's
# progress/goodput source) land every 2 steps. Pair with
# ``--synth-data --checkpoint-every-steps N`` on the CLI.
register_preset(
    "elastic_smoke",
    model_name="vit_ti_patch16",
    model_overrides=dict(num_layers=2, embed_dim=64, num_heads=4),
    num_classes=10,
    image_size=32,
    compute_dtype="float32",
    global_batch_size=8,
    num_train_images=8 * 1000,
    num_epochs=1,
    warmup_epochs=0,
    base_lr=1e-3,
    lr_scaling_divisor=8,
    transpose_images=False,
    augment="",
    log_every_steps=2,
    seed=0,
)

# The RESULTS.md record run: scikit-learn digits as ImageNet-layout
# TFRecords (tools/make_digits_tfrecords.py), trained through the full real
# path to 85%+ top-1 from scratch (reproduced twice). Two knobs live on the
# CLI, not TrainConfig: pass ``--crop-min-area 0.5 --no-train-flip``
# (dataset-scale calibration; digits have chirality).
register_preset(
    "vit_ti_digits",
    model_name="vit_ti_patch16",
    num_classes=10,
    image_size=48,
    global_batch_size=128,
    num_train_images=1438,
    num_epochs=150,
    warmup_epochs=10,
    base_lr=2e-3,
    augment="cutmix_mixup",
    transpose_images=False,
    seed=42,
)

# Per-family digits recipes (VERDICT r3 item 4): every model family trained
# through the identical real path as vit_ti_digits — TFRecord JPEG bytes →
# Inception crop (pass ``--crop-min-area 0.5 --no-train-flip`` on the CLI)
# → per-example CutMix/MixUp → masked AdamW → warmup-cosine — with
# architecture scaled via model_overrides to the 1.4k-example 48² dataset
# (depth cut; widths/mechanisms kept so each family's distinguishing
# machinery actually runs: CaiT's talking-heads trunk + class attention +
# LayerScale + stoch depth, CvT/BoTNet's BatchNorm batch_stats path, TNT's
# two-stream blocks, CeiT's LeFF + LCA head, Mixer's token/channel MLPs).
_DIGITS_RECIPE = dict(
    num_classes=10,
    image_size=48,
    global_batch_size=128,
    num_train_images=1438,
    num_epochs=150,
    warmup_epochs=10,
    base_lr=2e-3,
    augment="cutmix_mixup",
    transpose_images=False,
    seed=42,
)

register_preset(
    "cait_digits",
    model_name="cait_xxs_24",
    model_overrides=dict(
        num_layers=6,
        num_layers_token_only=2,
        patch_shape=(8, 8),
        stoch_depth_rate=0.05,
    ),
    **_DIGITS_RECIPE,
)
register_preset(
    "cvt_digits",
    model_name="cvt-13",
    model_overrides=dict(num_layers=(1, 1, 2)),
    **_DIGITS_RECIPE,
)
register_preset(
    "botnet_digits",
    model_name="botnet_t3",
    model_overrides=dict(stage_sizes=(1, 1, 2, 1)),
    **_DIGITS_RECIPE,
)
register_preset(
    "tnt_digits",
    model_name="tnt_s_patch16",
    model_overrides=dict(num_layers=4, patch_shape=(8, 8)),
    **_DIGITS_RECIPE,
)
register_preset(
    "ceit_digits",
    model_name="ceit_t",
    model_overrides=dict(num_layers=4),
    **_DIGITS_RECIPE,
)
register_preset(
    "mixer_digits",
    model_name="mixer_s_patch32",
    model_overrides=dict(num_layers=6, patch_shape=(8, 8)),
    **_DIGITS_RECIPE,
)

# RandAugment-inclusive digits recipe (VERDICT r4 item 5): the flagship
# augment path — mixes AND RandAugment together, the combination the
# reference's default `cutmix_mixup_randaugment_405` runs — with magnitude
# calibrated for 48² digits: 2 layers, magnitude 1 (`randaugment_201`
# semantics; the 405 geometric ops at ImageNet translate/cutout scale
# destroy a 48² glyph, which is why the record runs dropped RA). Pass the
# usual ``--crop-min-area 0.5 --no-train-flip`` on the CLI.
register_preset(
    "vit_ti_digits_ra",
    model_name="vit_ti_patch16",
    **{**_DIGITS_RECIPE, "augment": "cutmix_mixup_randaugment_201"},
)

# ------------------------------------------------- full-scale dress rehearsal

# ImageNet-shaped end-to-end rehearsal (VERDICT r4 item 3): the exact
# production configuration — deit_s trunk, 1000-class head, 224² (197
# tokens), bf16, the COMPLETE default augment DSL (RandAugment 4 layers
# mag 5 + CutMix + MixUp) — on the synthetic label-derived dataset
# (tools/make_synth_imagenet.py), ~560 steps at bs 256:
#
#   python tools/make_synth_imagenet.py --out .data/synth_imagenet
#   python train.py --preset deit_s_rehearsal --data-dir .data/synth_imagenet \
#       --num-train-images 2048 --num-eval-images 256 -c .ckpt/rehearsal
#
# Proves the full-scale config path (RA included) executes end to end,
# loss decreases, and checkpoints restore — scale anchor
# /root/reference/train.py:159 + input_pipeline.py:38-62. On the 1-core
# CPU host override --batch-size 64 --num-epochs 4 (~2 min/step at 224²).
register_preset(
    "deit_s_rehearsal",
    model_name="deit_s_patch16",
    num_classes=1000,
    image_size=224,
    compute_dtype="bfloat16",
    global_batch_size=256,
    num_train_images=2048,
    num_epochs=70,
    warmup_epochs=5,
    base_lr=5e-4,
    weight_decay=0.05,
    augment="cutmix_mixup_randaugment_405",
    transpose_images=False,
    eval_every_epochs=10,
    checkpoint_every_epochs=10,
    log_every_steps=8,
    seed=0,
)
