"""Schedules and the masked AdamW optimizer.

Reference behavior rebuilt: warmup-cosine schedule (train.py:215-220) and the
jaxline per-group optimizer that applied weight decay to weights but not
biases (experiments/base.py:84-104) — expressed here as a single
``optax.adamw`` with a mask over parameter paths instead of two reflected
optimizers, plus global-norm clipping (train.py:25).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class EmaState(NamedTuple):
    """Exponential moving average of the *parameters* (not gradients)."""

    ema: Any


def track_params_ema(decay: float) -> optax.GradientTransformation:
    """Maintain ``ema = decay·ema + (1-decay)·params`` as optimizer state.

    Must sit LAST in the optax chain: it applies the (final) updates to the
    incoming params to see the post-step values, and passes the updates
    through unchanged. Living inside ``opt_state`` means the EMA rides
    checkpoints, sharding rules (path-suffix matching places the mirror
    tree like its parameters), and donation for free — no TrainState
    change, so checkpoints from EMA-less configs keep restoring.
    """
    if not 0.0 <= decay <= 1.0:
        raise ValueError(f"ema decay must be in [0, 1], got {decay}")

    def init_fn(params):
        return EmaState(ema=jax.tree.map(lambda p: p.astype(jnp.float32), params))

    def update_fn(updates, state, params=None):
        if params is None:
            raise ValueError("track_params_ema requires params")
        new_params = optax.apply_updates(params, updates)
        ema = jax.tree.map(
            lambda e, p: decay * e + (1.0 - decay) * p.astype(e.dtype),
            state.ema,
            new_params,
        )
        return updates, EmaState(ema=ema)

    return optax.GradientTransformation(init_fn, update_fn)


def ema_params(opt_state) -> Optional[Any]:
    """Extract the EMA parameter tree from an optimizer state, or None."""
    found = [
        s.ema
        for s in jax.tree_util.tree_leaves(
            opt_state, is_leaf=lambda x: isinstance(x, EmaState)
        )
        if isinstance(s, EmaState)
    ]
    return found[0] if found else None


def warmup_cosine_schedule(
    learning_rate: float,
    *,
    steps_per_epoch: int,
    warmup_epochs: int,
    num_epochs: int,
    end_lr: float = 1e-5,
) -> optax.Schedule:
    warmup_steps = max(1, warmup_epochs * steps_per_epoch)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=learning_rate,
        warmup_steps=warmup_steps,
        # optax requires decay_steps > warmup_steps; short runs (warmup
        # longer than the whole schedule) degenerate to warmup-only.
        decay_steps=max(warmup_steps + 1, num_epochs * steps_per_epoch),
        end_value=end_lr,
    )


def weight_decay_mask(params: Any) -> Any:
    """True (decay) for rank≥2 kernels; False for biases, norm scales,
    position tables, CLS tokens, LayerScale — the reference's weight/bias
    split (base.py:95-103) generalized by rank + name."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def decays(path, leaf):
        path_str = "/".join(k.key if hasattr(k, "key") else str(k) for k in path)
        if leaf.ndim < 2:
            return False
        no_decay_names = ("pos_embed", "cls", "rel_emb_h", "rel_emb_w")
        return not any(n in path_str for n in no_decay_names)

    leaves = [decays(p, l) for p, l in flat]
    return jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(params), leaves)


def make_optimizer(
    schedule: optax.Schedule,
    *,
    weight_decay: float = 0.05,
    clip_grad_norm: Optional[float] = 1.0,
    fused: bool = True,
    ema_decay: Optional[float] = None,
) -> optax.GradientTransformation:
    """Masked AdamW, by default with the Adam moment math on one flat vector.

    ``fused=True`` wraps ``scale_by_adam`` in ``optax.flatten`` so the
    m/v/bias-correction updates run as a handful of fused kernels over one
    contiguous buffer instead of ~10 small kernels per parameter leaf —
    measured 9.3 ms/step of mostly launch overhead on the DeiT-S profile
    (PERF.md §1/§5). Numerically identical (flatten is a reshape); the decay
    mask and global-norm clip stay tree-wise (the mask needs parameter
    paths). Changes the optimizer-state checkpoint layout — set
    ``fused=False`` to restore pre-round-3 checkpoints.
    """
    chain = []
    if clip_grad_norm is not None:
        chain.append(optax.clip_by_global_norm(clip_grad_norm))
    adam = optax.scale_by_adam()
    if fused:
        adam = optax.flatten(adam)
    chain += [
        adam,
        optax.add_decayed_weights(weight_decay, mask=weight_decay_mask),
        optax.scale_by_learning_rate(schedule),
    ]
    if ema_decay is not None:
        # Last: sees the final updates, so the EMA tracks post-step params.
        chain.append(track_params_ema(ema_decay))
    return optax.chain(*chain)
