"""Train state pytree.

One state for both stateless and BatchNorm models — collapsing the
reference's duplicated ``experiments/base.py`` / ``base_with_state.py``
trainers (SURVEY.md §2.6): ``batch_stats`` is just an (possibly empty)
collection threaded through the step.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp


class TrainState(flax.struct.PyTreeNode):
    step: Any
    params: Any
    opt_state: Any
    batch_stats: Any  # {} for models without BatchNorm

    @classmethod
    def create(cls, params, opt_state, batch_stats=None):
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=opt_state,
            batch_stats=batch_stats if batch_stats is not None else {},
        )
