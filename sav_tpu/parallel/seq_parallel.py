"""Model-facing sequence parallelism: pad-and-mask routing into ring/Ulysses.

:mod:`sav_tpu.parallel.ring_attention` and :mod:`sav_tpu.parallel.ulysses`
are exact SP attention *ops* over already-divisible sequence lengths. Vision
transformers produce awkward lengths (a CLS token makes ViT's 224²/16² grid
197 tokens), so the model seam lives here: pad the sequence to a multiple of
the ``seq`` mesh axis, mask the padded keys out of every softmax (via the
shard bodies' ``valid_len`` parameter — one implementation of the ring /
all-to-all numerics, shared with the bare ops), run the sequence-parallel
op, slice the padding back off. This is what
``AttentionBlock(seq_parallel=...)`` calls — the TrainConfig-reachable path
(``train.py --sp N``), closing SURVEY.md §5's long-context gap at the
*framework* level rather than as a bare library op.

Masking is key-side only: padded *query* rows compute garbage that the final
slice discards, while padded *key* columns must not receive probability
mass. Softmax statistics run in f32 (an online-softmax requirement for
ring's running max/denominator); ``attention_logits_dtype='bfloat16'`` does
not apply under SP — see ``TrainConfig.sequence_parallel``.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from sav_tpu.parallel._compat import shard_map
from sav_tpu.parallel.mesh import SEQ_AXIS, batch_axes
from sav_tpu.parallel.ring_attention import (
    _ring_shard_fn,
    _ring_talking_heads_shard_fn,
)
from sav_tpu.parallel.ulysses import _ulysses_shard_fn

METHODS = ("ring", "ulysses")

# ---------------------------------------------------------------------------
# Batch-replication fallback observability. Replicating the batch across
# the sequence group is *correct* but multiplies per-device attention
# memory/compute by the data-axis product — a silent footgun at training
# scale, so degraded-parallelism runs must be machine-visible. Listeners
# (Trainer.fit registers one per fit: once-per-fit warning +
# SpanTracer.instant + manifest note) take precedence; without any, the
# module warns once per (batch, group) shape per process instead of
# per trace.

_replication_listeners: list = []
_replication_warned: set = set()


def on_batch_replication(callback):
    """Register ``callback(info_dict)`` for replication-fallback events;
    returns a zero-arg unsubscribe. Listener exceptions are swallowed —
    observability must never fail a trace."""
    _replication_listeners.append(callback)

    def unsubscribe():
        try:
            _replication_listeners.remove(callback)
        except ValueError:
            pass

    return unsubscribe


def _replication_fallback(b: int, group: int) -> None:
    info = {"batch": int(b), "data_axis_product": int(group)}
    handled = False
    for callback in list(_replication_listeners):
        try:
            callback(dict(info))
            handled = True
        except Exception:
            pass
    key = (int(b), int(group))
    if not handled and key not in _replication_warned:
        _replication_warned.add(key)
        warnings.warn(
            f"sequence_parallel_attention: batch {b} does not divide the "
            f"mesh's data-axis product {group}; replicating the batch "
            "across all sequence-group members. Size the global batch as "
            "a multiple of the data axes for training-scale calls.",
            stacklevel=3,
        )


def sequence_parallel_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    *,
    mesh: Mesh,
    method: str = "ring",
    seq_axis: str = SEQ_AXIS,
    batch_axis=None,
    scale: Optional[float] = None,
    talking_heads: Optional[tuple] = None,
) -> jax.Array:
    """Exact SP attention for arbitrary (CLS-token-odd) sequence lengths.

    Args:
      query/key/value: global ``[B, L, H, D]`` self-attention projections
        (equal lengths — this is the model seam, not a cross-attention op).
      mesh: mesh containing ``seq_axis``.
      method: ``'ring'`` (ppermute K/V streaming — any head count, the
        long-context default) or ``'ulysses'`` (two all-to-alls — requires
        ``H % mesh[seq_axis] == 0``).
      batch_axis: mesh axes the batch dim shards over; default: the mesh's
        batch axes when the batch divides them, else replicated.
      scale: logits scale, default ``D ** -0.5``.
      talking_heads: optional ``(w_pre, w_post)`` pair of ``[H, H]`` head-
        mixing matrices (CaiT trunk). Ring only: the mixing couples heads
        across the softmax, handled exactly by head-pair accumulators
        (:func:`sav_tpu.parallel.ring_attention._ring_talking_heads_shard_fn`);
        Ulysses scatters heads across devices, which the mix would have to
        cross — rejected.

    Returns:
      ``[B, L, H, D]`` like the inputs.
    """
    if method not in METHODS:
        raise ValueError(
            f"unknown sequence-parallel method {method!r}; choose from {METHODS}"
        )
    if talking_heads is not None and method != "ring":
        raise ValueError(
            "talking-heads sequence parallelism is ring-only (Ulysses "
            "shards heads across devices; the head mix would cross them)"
        )
    if query.shape != key.shape or key.shape != value.shape:
        raise ValueError(
            "sequence_parallel_attention is a self-attention seam: q/k/v "
            f"shapes must match, got {query.shape}/{key.shape}/{value.shape}"
        )
    if scale is None:
        scale = query.shape[-1] ** -0.5
    n = mesh.shape[seq_axis]
    b, length, heads, dim = query.shape
    if batch_axis is None:
        axes = batch_axes(mesh)
        group = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        # Shard the batch over the data axes when it divides; replicate it
        # otherwise (correct for any batch — each seq-group member then
        # holds the full batch, which is what small interactive calls and
        # single-example debugging want).
        batch_axis = axes if axes and b % group == 0 else None
        if batch_axis is None and axes and group > 1:
            # Fine for debugging, a footgun at training scale: route the
            # event through the observability hook above (listeners or a
            # once-per-shape process warning). Fires at trace time only.
            _replication_fallback(b, group)
    if method == "ulysses" and heads % n:
        raise ValueError(
            f"ulysses needs head count ({heads}) divisible by the "
            f"'{seq_axis}' axis ({n}); use method='ring'"
        )

    pad = (-length) % n
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        query = jnp.pad(query, widths)
        key = jnp.pad(key, widths)
        value = jnp.pad(value, widths)
    # valid_len=None compiles the unmasked shard bodies (no extra ops).
    valid_len = length if pad else None

    spec = P(batch_axis, seq_axis, None, None)
    if talking_heads is not None:
        w_pre, w_post = talking_heads
        rep = P()  # [H, H] mixing matrices replicate across the mesh
        shard_fn = functools.partial(
            _ring_talking_heads_shard_fn,
            axis_name=seq_axis,
            axis_size=n,
            scale=float(scale),
            valid_len=valid_len,
        )
        out = shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, rep, rep),
            out_specs=spec,
            check_rep=False,
        )(query, key, value, w_pre, w_post)
        if pad:
            out = out[:, :length]
        return out
    if method == "ring":
        shard_fn = functools.partial(
            _ring_shard_fn,
            axis_name=seq_axis,
            axis_size=n,
            scale=float(scale),
            valid_len=valid_len,
        )
    else:
        shard_fn = functools.partial(
            _ulysses_shard_fn,
            axis_name=seq_axis,
            scale=float(scale),
            valid_len=valid_len,
        )
    out = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )(query, key, value)
    if pad:
        out = out[:, :length]
    return out
