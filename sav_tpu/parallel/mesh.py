"""Device mesh construction and distributed bring-up.

The TPU-native replacement for the reference's pmap data parallelism
(/root/reference/train.py:228-231, experiments/base.py:64-68): one
``jax.sharding.Mesh`` over all devices; pjit/NamedSharding make XLA's
partitioner emit the gradient AllReduce over ICI/DCN (the reference wrote
``lax.pmean`` by hand — train.py:96). Multi-host bring-up goes through
``jax.distributed.initialize`` once per process.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names. data = batch (DP), fsdp = batch + parameter sharding
# (ZeRO-3 style), model = tensor parallel (TP), seq = sequence/context
# parallel (ring attention), pipe = pipeline stages, expert = MoE expert
# parallelism.
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

_distributed_initialized = False


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX exactly once (no-op on single process).

    Replaces the reference's implicit jaxline/TPU-VM host coordination
    (SURVEY.md §2.7). MUST be the first JAX call in the process: any
    backend-touching API (``jax.devices``, ``jax.process_count``, ...)
    before this makes ``jax.distributed.initialize`` raise. With no
    arguments, initialization is attempted only when the environment
    advertises a coordinator (TPU pod / SLURM); plain single-process runs
    fall through untouched.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return
    import os

    env_hints = (
        "COORDINATOR_ADDRESS",
        "JAX_COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS",
        "SLURM_JOB_ID",
    )
    explicit = coordinator_address is not None
    if explicit or any(os.environ.get(k) for k in env_hints):
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
    _distributed_initialized = True


def create_mesh(
    axis_sizes: Optional[dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over ``devices`` (default: all).

    ``axis_sizes`` maps axis name → size; a single ``-1`` entry absorbs the
    remaining devices. Default: everything on the ``data`` axis.

    Examples::

      create_mesh()                              # 1-D DP mesh
      create_mesh({"data": -1, "model": 2})      # DP × TP
      create_mesh({"data": 1, "seq": 8})         # sequence-parallel ring
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: n}
    names = tuple(axis_sizes)
    sizes = list(axis_sizes.values())
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one axis may be -1")
    if wild:
        known = int(np.prod([s for s in sizes if s != -1])) if len(sizes) > 1 else 1
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[wild[0]] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} != {n} devices")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def batch_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes the batch dimension shards over: ``data`` and (when present)
    ``fsdp`` — FSDP is batch-parallel for activations, parameter-sharded for
    weights."""
    return tuple(a for a in (DATA_AXIS, FSDP_AXIS) if a in mesh.axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the batch axes, replicate the rest."""
    return NamedSharding(mesh, P(batch_axes(mesh)))


def batch_sharding_at(mesh: Mesh, dim: int) -> NamedSharding:
    """Batch axes on dimension ``dim`` instead of the leading one — the
    trainer's transposed-images (HWCN: batch last) and fused-multi-step
    (leading ``[K, ...]`` steps axis: batch second) placements. Specs are
    prefixes, so the result applies to any leaf with ndim > ``dim``."""
    if dim < 0:
        raise ValueError(f"dim must be non-negative, got {dim}")
    return NamedSharding(mesh, P(*([None] * dim), batch_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
