"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context capability the reference lacked entirely (SURVEY.md §5
'Long-context / sequence parallelism: none'). Sequences are sharded over the
``seq`` mesh axis; each device holds a Q shard and streams K/V shards around
the ring with ``jax.lax.ppermute`` (XLA collective permute → ICI
neighbor-to-neighbor traffic), accumulating exact softmax attention with the
same online (m, l, acc) statistics the flash kernel uses. Communication
overlaps compute: the K/V rotation for step i+1 is issued while block i is
being contracted, and XLA pipelines the ppermute over ICI.

Memory per device: O(L_local · L_local) logits per block instead of O(L²) —
sequence length scales linearly with the ring size.

Differentiable (ppermute has a transpose rule); numerics cross-checked
against the dense XLA core in ``tests/test_ring_attention.py``.
"""

from __future__ import annotations

import functools
import importlib
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sav_tpu.parallel._compat import shard_map

from sav_tpu.parallel.mesh import SEQ_AXIS

# importlib: `import ... as` and `from ... import` both resolve the
# attribute `flash_attention`, which ops/__init__ rebinds to the same-named
# function; sys.modules holds the real submodule.
_fa = importlib.import_module("sav_tpu.ops.flash_attention")

_NEG_INF = float("-inf")


def _mask_key_block(s, origin, blk_len: int, valid_len: int):
    """Force logits at global key positions ``>= valid_len`` to −inf.

    Each K/V block travels with its origin shard index (rotated along with
    the block) so global positions stay recoverable after any number of
    ppermutes."""
    key_pos = origin * blk_len + jax.lax.iota(jnp.int32, blk_len)
    return jnp.where(key_pos[None, None, None, :] < valid_len, s, _NEG_INF)


def _online_softmax_update(m, l, s, masked: bool):
    """One block's contribution to the running (max, denominator).

    Returns ``(m_new, l_new, alpha, p)``: the updated statistics, the
    rescale factor for existing accumulators, and the block's unnormalized
    probabilities — the same (m, l, acc) algebra the flash kernel uses."""
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    if masked:
        # A fully-masked block leaves m at -inf; exp(-inf - -inf) = nan,
        # so guard the shift (the block contributes exactly zero mass).
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), _NEG_INF, m - m_safe))
        p = jnp.exp(s - m_safe)
    else:
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
    return m_new, alpha * l + jnp.sum(p, axis=-1, keepdims=True), alpha, p


def _ring_loop(k, v, origin, state, block_fn, *, axis_name: str,
               axis_size: int):
    """Rotate K/V (and the origin index, when masking) around the ring,
    folding each block into ``state`` via ``block_fn(state, k, v, origin)``."""
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for step in range(axis_size):
        state = block_fn(state, k, v, origin)
        if step + 1 < axis_size:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            if origin is not None:
                origin = jax.lax.ppermute(origin, axis_name, perm)
    return state


def _guard_zero_denominator(l):
    # Defensive NaN guard. Masking is key-side only, so every query row
    # (padded or not) always attends to >= 1 valid key and l > 0 holds —
    # this should be unreachable. Kept so that a future mask variant that
    # can zero a full row degrades to zeros, not 0/0 NaNs that would
    # poison reductions run over the raw output.
    return jnp.where(l == 0.0, 1.0, l)


def _ring_shard_fn(q, k, v, *, axis_name: str, axis_size: int, scale: float,
                   valid_len: Optional[int] = None):
    """Per-shard body. q/k/v: ``[B, L_loc, H, D]`` (local shards).

    ``valid_len`` (static) masks global key positions ``>= valid_len`` out
    of every softmax — the pad-and-mask path :mod:`sav_tpu.parallel.seq_parallel`
    uses for CLS-odd model sequence lengths. ``None`` compiles to the
    unmasked loop (no extra ops).
    """
    batch, q_len, heads, dim = q.shape
    m = jnp.full((batch, heads, q_len, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, q_len, 1), jnp.float32)
    acc = jnp.zeros((batch, q_len, heads, dim), jnp.float32)
    masked = valid_len is not None
    origin = jax.lax.axis_index(axis_name) if masked else None

    def one_block(state, k_blk, v_blk, origin):
        m, l, acc = state
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        if masked:
            s = _mask_key_block(s, origin, k_blk.shape[1], valid_len)
        m_new, l_new, alpha, p = _online_softmax_update(m, l, s, masked)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        # alpha: [B,H,Lq,1] → broadcast over the [B,Lq,H,D] accumulator.
        alpha_q = jnp.transpose(alpha, (0, 2, 1, 3))
        return m_new, l_new, acc * alpha_q + pv

    m, l, acc = _ring_loop(
        k, v, origin, (m, l, acc), one_block,
        axis_name=axis_name, axis_size=axis_size,
    )
    if masked:
        l = _guard_zero_denominator(l)
    out = acc / jnp.transpose(l, (0, 2, 1, 3))
    return out.astype(q.dtype)


def _ring_talking_heads_shard_fn(
    q, k, v, w_pre, w_post, *, axis_name: str, axis_size: int, scale: float,
    valid_len: Optional[int] = None,
):
    """Ring attention with CaiT's pre/post-softmax head mixing — exact, one
    rotation (the seam that unlocks SP for talking-heads trunks).

    Head mixing couples heads across the softmax, which breaks the per-head
    online accumulator of :func:`_ring_shard_fn`: the post-mix probability
    ``pm_j = Σ_i Wpost[i,j] p_i`` pairs source-head-``i`` probabilities with
    head-``j`` *values*, so the output does not decompose into per-head
    attention outputs. It does decompose into head-*pair* accumulators::

        out[q,j] = Σ_i Wpost[i,j] · (Σ_k p_i,qk · v_k,j) / l_i,q
                 = Σ_i Wpost[i,j] · A[i,j,q] / l_i,q

    where ``A[i,j] = Σ_k exp(s̃_i,qk − m_i,q) v_k,j`` accumulates online
    with source-head-``i`` statistics (running max ``m_i``, denominator
    ``l_i``) exactly like flash — per-device memory is O(H²·L_loc·D), still
    no L² term, at H× the PV FLOPs (H is 4-16 for the model zoo). The
    pre-softmax mix ``s̃ = Wpreᵀ s`` is block-local and rides unchanged;
    key-side masking applies after it (padded columns forced to −inf, so
    they carry zero mass regardless of what the mix wrote there).
    """
    batch, q_len, heads, dim = q.shape
    m = jnp.full((batch, heads, q_len, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, q_len, 1), jnp.float32)
    # Head-pair accumulator: [B, src_head i, val_head j, Lq, D].
    acc = jnp.zeros((batch, heads, heads, q_len, dim), jnp.float32)
    masked = valid_len is not None
    origin = jax.lax.axis_index(axis_name) if masked else None
    w_pre32 = w_pre.astype(jnp.float32)

    def one_block(state, k_blk, v_blk, origin):
        m, l, acc = state
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        # Pre-softmax mix (TalkingHeadsBlock convention: out_i = Σ_h W[h,i] x_h).
        s = jnp.einsum("hi,bhqk->biqk", w_pre32, s)
        if masked:
            s = _mask_key_block(s, origin, k_blk.shape[1], valid_len)
        m_new, l_new, alpha, p = _online_softmax_update(m, l, s, masked)
        # [B,i,Lq,K] × [B,K,j,D] → [B,i,j,Lq,D]
        pv = jnp.einsum(
            "biqk,bkjd->bijqd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        # alpha: [B,i,Lq,1] → broadcast over (j, D) in [B,i,j,Lq,D].
        return m_new, l_new, acc * alpha[:, :, None, :, :] + pv

    m, l, acc = _ring_loop(
        k, v, origin, (m, l, acc), one_block,
        axis_name=axis_name, axis_size=axis_size,
    )
    if masked:
        l = _guard_zero_denominator(l)
    # out[b,q,j,d] = Σ_i Wpost[i,j] · acc[b,i,j,q,d] / l[b,i,q]
    normed = acc / l[:, :, None, :, :]
    out = jnp.einsum("ij,bijqd->bqjd", w_post.astype(jnp.float32), normed)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Flash-mode ring: each ring step runs the fused Pallas kernel on the local
# (Q, K_blk, V_blk) pair and the normalized partials are merged online with
# their logsumexps — per-device memory stays O(L_loc·D + H·L_loc), never
# O(L_loc²), in BOTH directions:
#
#   forward   o = Σ_i softmax-partial_i merged by lse_i (exact)
#   backward  re-stream the ring with the GLOBAL lse: p_blk = exp(s − lse)
#             is the globally-normalized probability block, so the blocked
#             backward kernels yield dq partials (summed locally) and
#             dk/dv partials that ride the ring home in carried f32
#             accumulators (one full rotation returns them to their owner).
#
# Autodiff of the dense ring loop would instead save every per-step
# [B,H,L_loc,L_loc] probability block — O(L_loc·L) per device. The
# custom_vjp contains the ppermutes, so it composes with shard_map.
# ---------------------------------------------------------------------------


def _flash_ring_forward_steps(q, k, v, *, axis_name, axis_size, scale,
                              block_q, block_kv, interpret):

    batch, q_len, heads, dim = q.shape
    acc = jnp.zeros((batch, q_len, heads, dim), jnp.float32)
    m = jnp.full((batch, heads, q_len), _NEG_INF, jnp.float32)
    denom = jnp.zeros((batch, heads, q_len), jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for step in range(axis_size):
        o_blk, lse_pad = _fa._flash_forward(
            q, k, v, None, scale, block_q, block_kv, interpret, with_lse=True
        )
        lse_blk = lse_pad[:, :q_len, 0].reshape(batch, heads, q_len)
        m_new = jnp.maximum(m, lse_blk)
        w_old = jnp.exp(m - m_new)
        w_blk = jnp.exp(lse_blk - m_new)
        # weights are [B,H,Lq] → broadcast over the [B,Lq,H,D] partials.
        to_q = lambda x: jnp.transpose(x, (0, 2, 1))[..., None]
        acc = acc * to_q(w_old) + o_blk.astype(jnp.float32) * to_q(w_blk)
        denom = denom * w_old + w_blk
        m = m_new
        if step + 1 < axis_size:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)

    out = (acc / jnp.transpose(denom, (0, 2, 1))[..., None]).astype(q.dtype)
    lse_global = m + jnp.log(denom)  # [B, H, Lq] f32
    return out, lse_global


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, axis_size, scale, block_q, block_kv,
                interpret):
    out, _ = _flash_ring_forward_steps(
        q, k, v, axis_name=axis_name, axis_size=axis_size, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
    )
    return out


def _ring_flash_fwd(q, k, v, axis_name, axis_size, scale, block_q, block_kv,
                    interpret):
    out, lse = _flash_ring_forward_steps(
        q, k, v, axis_name=axis_name, axis_size=axis_size, scale=scale,
        block_q=block_q, block_kv=block_kv, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, axis_size, scale, block_q, block_kv,
                    interpret, residuals, g):

    q, k, v, out, lse = residuals
    lse_pad = _fa.lse_padded_layout(lse, q.shape[1], block_q)

    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for _ in range(axis_size):
        dq_p, dk_b, dv_b = _fa._flash_backward_pallas(
            q, k, v, out, lse_pad, g, scale, block_q, block_kv, interpret
        )
        dq = dq + dq_p.astype(jnp.float32)
        dk = dk + dk_b.astype(jnp.float32)
        dv = dv + dv_b.astype(jnp.float32)
        # Rotate K/V together with their gradient accumulators: after the
        # full loop (axis_size rotations) each lands back on its owner.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        dk = jax.lax.ppermute(dk, axis_name, perm)
        dv = jax.lax.ppermute(dv, axis_name, perm)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    *,
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    batch_axis: Optional[str] = None,
    scale: Optional[float] = None,
    backend: str = "xla",
    block_q: int = 512,
    block_kv: int = 512,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Exact attention over sequence-sharded inputs.

    Args:
      query/key/value: global ``[B, L, H, D]`` arrays; ``L`` must divide by
        the ``seq_axis`` mesh size. Under jit the arrays should already be
        sharded ``P(batch_axis, seq_axis, None, None)``; calling it on
        unsharded host arrays also works (shard_map partitions them).
      mesh: mesh containing ``seq_axis`` (and optionally ``batch_axis``).
      scale: logits scale, default ``D ** -0.5``.
      backend: ``'xla'`` — dense per-block logits (numerics reference);
        ``'pallas'`` — each ring step runs the fused flash kernel and the
        blocked backward re-streams the ring, so nothing O(L_loc²) exists
        on any device in either direction (the configuration for truly
        long contexts; see module comment).

    Returns:
      ``[B, L, H, D]``, sharded like the query.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    if backend not in ("xla", "pallas"):
        raise ValueError(f"unknown ring attention backend: {backend!r}")
    axis_size = mesh.shape[seq_axis]
    if query.shape[1] % axis_size:
        raise ValueError(
            f"sequence length {query.shape[1]} not divisible by "
            f"{seq_axis}={axis_size}"
        )
    spec = P(batch_axis, seq_axis, None, None)
    if backend == "pallas":
        # positional args only: custom_vjp's nondiff_argnums handling
        # rejects keywords.
        fscale = float(scale)

        def shard_fn(q, k, v):
            return _ring_flash(
                q, k, v, seq_axis, axis_size, fscale, block_q, block_kv,
                interpret,
            )
    else:
        shard_fn = functools.partial(
            _ring_shard_fn,
            axis_name=seq_axis,
            axis_size=axis_size,
            scale=float(scale),
        )
    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(query, key, value)
