"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context capability the reference lacked entirely (SURVEY.md §5
'Long-context / sequence parallelism: none'). Sequences are sharded over the
``seq`` mesh axis; each device holds a Q shard and streams K/V shards around
the ring with ``jax.lax.ppermute`` (XLA collective permute → ICI
neighbor-to-neighbor traffic), accumulating exact softmax attention with the
same online (m, l, acc) statistics the flash kernel uses. Communication
overlaps compute: the K/V rotation for step i+1 is issued while block i is
being contracted, and XLA pipelines the ppermute over ICI.

Memory per device: O(L_local · L_local) logits per block instead of O(L²) —
sequence length scales linearly with the ring size.

Differentiable (ppermute has a transpose rule); numerics cross-checked
against the dense XLA core in ``tests/test_ring_attention.py``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sav_tpu.parallel._compat import shard_map

from sav_tpu.parallel.mesh import SEQ_AXIS

_NEG_INF = float("-inf")


def _ring_shard_fn(q, k, v, *, axis_name: str, axis_size: int, scale: float):
    """Per-shard body. q/k/v: ``[B, L_loc, H, D]`` (local shards)."""
    batch, q_len, heads, dim = q.shape
    m = jnp.full((batch, heads, q_len, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, q_len, 1), jnp.float32)
    acc = jnp.zeros((batch, q_len, heads, dim), jnp.float32)

    def one_block(m, l, acc, k_blk, v_blk):
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        # alpha: [B,H,Lq,1] → broadcast over the [B,Lq,H,D] accumulator.
        alpha_q = jnp.transpose(alpha, (0, 2, 1, 3))
        return m_new, l_new, acc * alpha_q + pv

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    for step in range(axis_size):
        m, l, acc = one_block(m, l, acc, k, v)
        if step + 1 < axis_size:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
    out = acc / jnp.transpose(l, (0, 2, 1, 3))
    return out.astype(q.dtype)


def ring_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    *,
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    batch_axis: Optional[str] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over sequence-sharded inputs.

    Args:
      query/key/value: global ``[B, L, H, D]`` arrays; ``L`` must divide by
        the ``seq_axis`` mesh size. Under jit the arrays should already be
        sharded ``P(batch_axis, seq_axis, None, None)``; calling it on
        unsharded host arrays also works (shard_map partitions them).
      mesh: mesh containing ``seq_axis`` (and optionally ``batch_axis``).
      scale: logits scale, default ``D ** -0.5``.

    Returns:
      ``[B, L, H, D]``, sharded like the query.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    axis_size = mesh.shape[seq_axis]
    if query.shape[1] % axis_size:
        raise ValueError(
            f"sequence length {query.shape[1]} not divisible by "
            f"{seq_axis}={axis_size}"
        )
    spec = P(batch_axis, seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            _ring_shard_fn,
            axis_name=seq_axis,
            axis_size=axis_size,
            scale=float(scale),
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(query, key, value)
