from sav_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    batch_axes,
    batch_sharding,
    create_mesh,
    distributed_init,
    replicated,
)
from sav_tpu.parallel.pipelining import (
    pipeline,
    stack_stage_params,
    stage_param_shardings,
)
from sav_tpu.parallel.ring_attention import ring_attention
from sav_tpu.parallel.seq_parallel import sequence_parallel_attention
from sav_tpu.parallel.ulysses import ulysses_attention
from sav_tpu.parallel.sharding import (
    DEFAULT_EP_RULES,
    DEFAULT_TP_RULES,
    add_fsdp_axis,
    param_path_specs,
    param_shardings,
    shard_params,
)

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "SEQ_AXIS",
    "pipeline",
    "stack_stage_params",
    "stage_param_shardings",
    "batch_axes",
    "batch_sharding",
    "create_mesh",
    "distributed_init",
    "replicated",
    "DEFAULT_EP_RULES",
    "DEFAULT_TP_RULES",
    "add_fsdp_axis",
    "param_path_specs",
    "param_shardings",
    "shard_params",
    "ring_attention",
    "sequence_parallel_attention",
    "ulysses_attention",
]
