"""shard_map compatibility across jax versions.

jax >= 0.8 promotes ``shard_map`` to ``jax.shard_map`` and renames
``check_rep`` → ``check_vma``; the experimental import still works but warns.
All framework call sites import :func:`shard_map` from here.
"""

from __future__ import annotations

import functools
import inspect

import jax

if hasattr(jax, "shard_map"):
    _impl = jax.shard_map
    _params = inspect.signature(_impl).parameters
    if "check_rep" in _params:
        shard_map = _impl
    else:

        @functools.wraps(_impl)
        def shard_map(f=None, /, *, check_rep=None, **kwargs):
            if check_rep is not None:
                kwargs.setdefault("check_vma", check_rep)
            return _impl(f, **kwargs)

else:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # noqa: F401
