"""GPipe-style microbatch pipeline parallelism over a mesh axis.

Capability headroom beyond the reference, which had data parallelism only
(SURVEY.md §2.7 — TP/PP/SP/EP all absent). Stages are laid out over the
``pipe`` mesh axis; parameters for stage *i* live only on that device slice,
and activations circulate stage-to-stage with ``jax.lax.ppermute`` — XLA
collective-permute, i.e. neighbor-to-neighbor ICI traffic, the same physics
as the ring-attention rotation (:mod:`sav_tpu.parallel.ring_attention`).

Design (the scaling-book collective-pipelining recipe, TPU-idiomatic):

- The batch is split into ``M`` microbatches. A single ``lax.scan`` runs
  ``M + S - 1`` ticks; on each tick every stage applies its block to its
  current activation and the results rotate one hop around the ring. Stage 0
  feeds fresh microbatches, stage ``S-1`` produces outputs — the classic
  GPipe schedule with bubble fraction ``(S-1)/(M+S-1)``, expressed as one
  compiled program (no per-stage Python dispatch, no dynamic shapes).
- Per-stage parameters are *stacked* along a leading stage axis and sharded
  ``P('pipe')`` so each device holds exactly its own stage's weights; inside
  ``shard_map`` the leading axis has local size 1 and is squeezed away.
- Everything is differentiable: ``ppermute`` has a transpose rule (the
  backward pass rotates gradients the opposite direction), so pipeline-
  parallel training falls out of ``jax.grad`` with no hand-written backward
  schedule.

Composes with data parallelism by passing ``batch_axis``: activations are
then sharded ``P('data')`` on the batch dim while circulating over
``pipe``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sav_tpu.parallel._compat import shard_map

from sav_tpu.parallel.mesh import PIPE_AXIS

StageFn = Callable[[Any, jax.Array], jax.Array]


def module_stage_fn(module, **apply_kwargs) -> StageFn:
    """Adapt a Flax module into a pipeline stage function.

    ``module`` is any shape-preserving block (the model-zoo case: a ViT
    ``EncoderBlock`` — every stage then runs one or more transformer layers
    on its ``[mb, L, C]`` activation slice). ``apply_kwargs`` are forwarded
    to ``module.apply`` (e.g. ``is_training=False``; pipeline training with
    dropout would need per-stage RNG plumbing — sow a need before wiring).

    The per-stage parameter trees come from initializing ``module`` once
    per stage (identical structure, different values), then
    :func:`stack_stage_params`.
    """

    def stage_fn(params, x):
        return module.apply({"params": params}, x, **apply_kwargs)

    return stage_fn


def stack_stage_params(param_trees: Sequence[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading stage axis.

    Each leaf of the result has shape ``[S, ...]``; shard it ``P('pipe')``
    (see :func:`stage_param_shardings`) so stage *i*'s weights live on pipe
    slice *i* only.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def stage_param_shardings(stacked_params: Any, mesh: Mesh, pipe_axis: str = PIPE_AXIS) -> Any:
    """``NamedSharding`` tree placing the leading stage axis over ``pipe``."""
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P(pipe_axis)), stacked_params
    )


def _per_device(
    params: Any,
    x: jax.Array,
    *,
    stage_fn: StageFn,
    axis: str,
    num_stages: int,
    num_microbatches: int,
):
    """Per-shard pipeline body. ``x``: ``[B_loc, ...]`` local batch."""
    i = jax.lax.axis_index(axis)
    params = jax.tree.map(lambda p: p[0], params)  # [1, ...] shard → this stage
    mb = x.shape[0] // num_microbatches
    x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
    num_ticks = num_microbatches + num_stages - 1
    perm = [(j, (j + 1) % num_stages) for j in range(num_stages)]

    def tick(state, t):
        # Stage 0 reads fresh microbatches (clamped index during drain);
        # later stages read what rotated in from the previous stage.
        feed = x_mb[jnp.minimum(t, num_microbatches - 1)]
        inp = jnp.where(i == 0, feed, state)
        out = stage_fn(params, inp)
        nxt = jax.lax.ppermute(out, axis, perm)
        return nxt, out

    _, outs = jax.lax.scan(tick, jnp.zeros_like(x_mb[0]), jnp.arange(num_ticks))
    # Stage S-1 produced valid microbatch outputs on ticks S-1 .. T-1.
    outs = outs[num_stages - 1 :]
    # Replicate the result across the pipe axis (mask + psum: only the last
    # stage contributes).
    mask = (i == num_stages - 1).astype(outs.dtype)
    outs = jax.lax.psum(outs * mask, axis)
    return outs.reshape(x.shape[0], *outs.shape[2:])


def pipeline(
    stage_fn: StageFn,
    stacked_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    pipe_axis: str = PIPE_AXIS,
    batch_axis: Optional[str] = None,
) -> jax.Array:
    """Run ``x`` through ``S`` pipelined stages of ``stage_fn``.

    Args:
      stage_fn: ``(stage_params, activation [mb, ...]) -> activation`` — one
        pipeline stage (e.g. a group of transformer blocks). Activation
        shapes must match across stages.
      stacked_params: per-stage params stacked ``[S, ...]`` on every leaf
        (:func:`stack_stage_params`), sharded over ``pipe_axis``.
      x: batch ``[B, ...]``; ``B`` (the per-``batch_axis``-shard size) must
        divide by ``num_microbatches``.
      mesh: mesh containing ``pipe_axis`` (and optionally ``batch_axis``).
      num_microbatches: GPipe microbatch count ``M``; bubble fraction is
        ``(S-1)/(M+S-1)`` — use ``M >= 4·S`` for <20% bubble.
      batch_axis: optional mesh axis sharding the batch dim (DP × PP).

    Returns:
      ``[B, ...]`` outputs, replicated over ``pipe_axis``.
    """
    num_stages = mesh.shape[pipe_axis]
    batch_shards = mesh.shape[batch_axis] if batch_axis else 1
    local_b = x.shape[0] // batch_shards
    if local_b % num_microbatches:
        raise ValueError(
            f"per-shard batch {local_b} (global {x.shape[0]} over "
            f"{batch_shards} '{batch_axis}' shards) must be divisible by "
            f"num_microbatches={num_microbatches}"
        )
    for path, leaf in jax.tree_util.tree_flatten_with_path(stacked_params)[0]:
        if leaf.shape[0] != num_stages:
            name = "/".join(str(k) for k in path)
            raise ValueError(
                f"stacked param {name!r} has {leaf.shape[0]} stages on its "
                f"leading axis but mesh axis {pipe_axis!r} has {num_stages} "
                "devices — a mismatch would silently drop stages"
            )
    spec = P(batch_axis)
    fn = shard_map(
        functools.partial(
            _per_device,
            stage_fn=stage_fn,
            axis=pipe_axis,
            num_stages=num_stages,
            num_microbatches=num_microbatches,
        ),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(pipe_axis), stacked_params), spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(stacked_params, x)
