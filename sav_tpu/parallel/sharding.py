"""Parameter sharding rules (tensor parallelism without touching modules).

The reference had DP only (SURVEY.md §2.7). Here TP is a first-class option:
instead of annotating every module with ``with_partitioning``, we
pattern-match flattened parameter paths against regex rules and build
``NamedSharding`` trees. Under ``jax.jit`` the partitioner propagates the
resulting layouts through the computation and inserts the right collectives
over ICI.

Default transformer TP layout (Megatron-style, over ``model`` axis):
  - Q/K/V projections ``(in, heads, head_ch)`` → heads sharded,
  - output merge ``(heads, head_ch, out)``     → heads sharded (row-parallel),
  - MLP fc1 ``(in, hidden)``  → hidden sharded (column-parallel),
  - MLP fc2 ``(hidden, out)`` → hidden sharded (row-parallel),
  - everything else replicated.
The pairing means each attention/MLP block needs exactly one AllReduce on its
output — the layout the scaling-book recipe prescribes.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sav_tpu.parallel.mesh import MODEL_AXIS

# (path regex, partition spec builder taking the param ndim)
DEFAULT_TP_RULES: list[tuple[str, Any]] = [
    (r"to_q/kernel$", P(None, MODEL_AXIS, None)),
    (r"to_k/kernel$", P(None, MODEL_AXIS, None)),
    (r"to_v/kernel$", P(None, MODEL_AXIS, None)),
    (r"to_(q|k|v)/bias$", P(MODEL_AXIS, None)),
    (r"to_out/kernel$", P(MODEL_AXIS, None, None)),
    (r"(fc1|expand)/kernel$", P(None, MODEL_AXIS)),
    (r"(fc1|expand)/bias$", P(MODEL_AXIS)),
    (r"(fc2|project)/kernel$", P(MODEL_AXIS, None)),
]


def param_path_specs(
    params: Any, rules: list[tuple[str, Any]] | None = None
) -> Any:
    """Tree of ``PartitionSpec`` matching ``params``, from path-regex rules."""
    rules = DEFAULT_TP_RULES if rules is None else rules
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def spec_for(path, leaf):
        path_str = "/".join(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        for pattern, spec in rules:
            if re.search(pattern, path_str) and len(spec) <= leaf.ndim:
                return spec
        return P()

    specs = [spec_for(path, leaf) for path, leaf in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def param_shardings(
    params: Any, mesh: Mesh, rules: list[tuple[str, Any]] | None = None
) -> Any:
    """Tree of ``NamedSharding`` for ``params``.

    With no ``model`` axis in the mesh (pure DP) the *default* rules are
    skipped (everything replicates). Caller-supplied rules are always
    honored — they may target other mesh axes (e.g. ``seq``).
    """
    if rules is None:
        rules = DEFAULT_TP_RULES if MODEL_AXIS in mesh.axis_names else []
    specs = param_path_specs(params, rules)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)


def shard_params(params: Any, mesh: Mesh, rules=None) -> Any:
    """Place a parameter tree onto the mesh according to the rules."""
    shardings = param_shardings(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)
