"""Parameter sharding rules (tensor parallelism without touching modules).

The reference had DP only (SURVEY.md §2.7). Here TP is a first-class option:
instead of annotating every module with ``with_partitioning``, we
pattern-match flattened parameter paths against regex rules and build
``NamedSharding`` trees. Under ``jax.jit`` the partitioner propagates the
resulting layouts through the computation and inserts the right collectives
over ICI.

Default transformer TP layout (Megatron-style, over ``model`` axis):
  - Q/K/V projections ``(in, heads, head_ch)`` → heads sharded,
  - output merge ``(heads, head_ch, out)``     → heads sharded (row-parallel),
  - MLP fc1 ``(in, hidden)``  → hidden sharded (column-parallel),
  - MLP fc2 ``(hidden, out)`` → hidden sharded (row-parallel),
  - everything else replicated.
The pairing means each attention/MLP block needs exactly one AllReduce on its
output — the layout the scaling-book recipe prescribes.
"""

from __future__ import annotations

import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sav_tpu.parallel.mesh import (
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
)

# (path regex, partition spec builder taking the param ndim)
DEFAULT_TP_RULES: list[tuple[str, Any]] = [
    (r"to_qkv/kernel$", P(None, None, MODEL_AXIS, None)),
    (r"to_qkv/bias$", P(None, MODEL_AXIS, None)),
    (r"to_q/kernel$", P(None, MODEL_AXIS, None)),
    (r"to_k/kernel$", P(None, MODEL_AXIS, None)),
    (r"to_v/kernel$", P(None, MODEL_AXIS, None)),
    (r"to_(q|k|v)/bias$", P(MODEL_AXIS, None)),
    (r"to_out/kernel$", P(MODEL_AXIS, None, None)),
    (r"(fc1|expand)/kernel$", P(None, MODEL_AXIS)),
    (r"(fc1|expand)/bias$", P(MODEL_AXIS)),
    (r"(fc2|project)/kernel$", P(MODEL_AXIS, None)),
]

# Expert parallelism: MoE expert weights carry a leading expert dimension
# sharded over the 'expert' mesh axis (router stays replicated). Applied
# automatically when the mesh has that axis.
DEFAULT_EP_RULES: list[tuple[str, Any]] = [
    (r"experts_(w1|w2)$", P(EXPERT_AXIS, None, None)),
    (r"experts_(b1|b2)$", P(EXPERT_AXIS, None)),
]

# Pipeline parallelism: every leaf of a PipelinedViT's 'pipe_stages' subtree
# carries a leading [S, ...] stage axis — shard it over 'pipe' so stage i's
# weights live only on pipe slice i (sav_tpu/models/pipelined.py). Matched
# FIRST so the stage-axis placement wins over any suffix rule that would
# otherwise hit the same leaf.
DEFAULT_PP_RULES: list[tuple[str, Any]] = [
    (r"pipe_stages/", P(PIPE_AXIS)),
]


def param_path_specs(
    params: Any, rules: list[tuple[str, Any]] | None = None
) -> Any:
    """Tree of ``PartitionSpec`` matching ``params``, from path-regex rules."""
    rules = DEFAULT_TP_RULES if rules is None else rules
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def spec_for(path, leaf):
        path_str = "/".join(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        for pattern, spec in rules:
            if re.search(pattern, path_str) and len(spec) <= leaf.ndim:
                return spec
        return P()

    specs = [spec_for(path, leaf) for path, leaf in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def add_fsdp_axis(
    spec: Any, shape: tuple[int, ...], fsdp_size: int, *, min_elements: int
) -> Any:
    """Augment a PartitionSpec with FSDP sharding (ZeRO-3 style).

    Shards the largest not-already-sharded dimension divisible by
    ``fsdp_size`` over the ``fsdp`` axis. Small tensors (< ``min_elements``)
    stay replicated — sharding tiny norm scales/biases costs more in
    collective latency than it saves in HBM.
    """
    import numpy as np

    if int(np.prod(shape)) < min_elements:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    candidates = [
        (shape[i], i)
        for i, e in enumerate(entries)
        if e is None and shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size
    ]
    if not candidates:
        return spec
    _, dim = max(candidates)
    entries[dim] = FSDP_AXIS
    return P(*entries)


def param_shardings(
    params: Any,
    mesh: Mesh,
    rules: list[tuple[str, Any]] | None = None,
    *,
    fsdp_min_elements: int = 2**16,
) -> Any:
    """Tree of ``NamedSharding`` for ``params``.

    Default rules are chosen from the mesh: TP rules when a ``model`` axis
    is present, EP rules when an ``expert`` axis is present, otherwise
    everything replicates (pure DP). Caller-supplied rules are always
    honored — they may target other mesh axes (e.g. ``seq``). When the mesh
    has an ``fsdp`` axis, every large parameter is additionally sharded over
    it (largest free dimension) — under jit the partitioner inserts the
    per-layer all-gathers and reduce-scatters this implies.
    """
    if rules is None:
        rules = []
        if PIPE_AXIS in mesh.axis_names:
            rules = rules + DEFAULT_PP_RULES
        if EXPERT_AXIS in mesh.axis_names:
            rules = rules + DEFAULT_EP_RULES
        if MODEL_AXIS in mesh.axis_names:
            rules = rules + DEFAULT_TP_RULES
    specs = param_path_specs(params, rules)
    if FSDP_AXIS in mesh.axis_names:
        fsdp_size = mesh.shape[FSDP_AXIS]
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        spec_leaves = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        new_leaves = [
            add_fsdp_axis(s, leaf.shape, fsdp_size, min_elements=fsdp_min_elements)
            for s, (_, leaf) in zip(spec_leaves, flat)
        ]
        treedef = jax.tree_util.tree_structure(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        specs = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, mesh: Mesh, rules=None) -> Any:
    """Place a parameter tree onto the mesh according to the rules."""
    shardings = param_shardings(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)
