"""Ulysses (all-to-all) sequence parallelism: head-scatter exact attention.

The second sequence-parallel strategy next to :mod:`ring_attention`
(long-context capability the reference lacked — SURVEY.md §5). Where ring
attention streams K/V shards around a ``ppermute`` ring, Ulysses re-shards
once: inputs arrive sequence-sharded ``[B, L/n, H, D]``, an all-to-all over
the ``seq`` axis swaps the sharded dimension from sequence to heads
(``[B, L, H/n, D]``), every device then runs ordinary *full-sequence*
attention on its head group, and a reverse all-to-all restores sequence
sharding. Two collectives total per attention call (vs. n-1 ppermute steps
for ring), so Ulysses wins when ``heads % n == 0`` and the sequence fits in
HBM once re-gathered per head group; ring wins for extreme lengths where
even one head's full [L, L] tile is too large.

Both collectives are ``jax.lax.all_to_all`` → XLA AllToAll riding ICI.
Differentiable (all_to_all is its own transpose up to axis swap); numerics
cross-checked against the dense XLA core in ``tests/test_ulysses.py``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from sav_tpu.parallel._compat import shard_map
from sav_tpu.parallel.mesh import SEQ_AXIS


def _ulysses_shard_fn(q, k, v, *, axis_name: str, scale: float,
                      backend: str = "xla",
                      valid_len: Optional[int] = None):
    """Per-shard body. q/k/v: ``[B, L_loc, H, D]`` (sequence shards).

    ``valid_len`` (static, XLA backend only) masks key positions
    ``>= valid_len`` — the pad-and-mask path
    :mod:`sav_tpu.parallel.seq_parallel` uses for CLS-odd lengths; after
    the all-to-all the whole (padded) sequence is local, so a plain iota
    mask suffices.
    """

    def seq_to_heads(x):
        # [B, L/n, H, D] → [B, L, H/n, D]: split heads across the axis
        # group, gather the full sequence.
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if backend == "pallas":
        # Fused kernel (blocked fwd AND bwd) on the full-sequence head
        # group: local memory stays O(L·D) — the long-context setting.
        from sav_tpu.ops import flash_attention

        out = flash_attention(q, k, v, scale=scale)
    else:
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * scale
        if valid_len is not None:
            key_pos = jax.lax.iota(jnp.int32, k.shape[1])
            s = jnp.where(
                key_pos[None, None, None, :] < valid_len, s, float("-inf")
            )
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
        ).astype(q.dtype)
    return heads_to_seq(out)


def ulysses_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    *,
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    batch_axis: Optional[str] = None,
    scale: Optional[float] = None,
    backend: str = "xla",
) -> jax.Array:
    """Exact attention over sequence-sharded inputs via head all-to-all.

    Args:
      query/key/value: global ``[B, L, H, D]`` arrays; ``L`` and ``H`` must
        both divide by the ``seq_axis`` mesh size. Under jit the arrays
        should already be sharded ``P(batch_axis, seq_axis, None, None)``.
      mesh: mesh containing ``seq_axis`` (and optionally ``batch_axis``).
      scale: logits scale, default ``D ** -0.5``.
      backend: ``'xla'`` (dense local core, numerics reference) or
        ``'pallas'`` (fused flash kernel with blocked backward on the local
        head group — O(L·D) local memory for long contexts).

    Returns:
      ``[B, L, H, D]``, sharded like the query.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    if backend not in ("xla", "pallas"):
        raise ValueError(f"unknown ulysses attention backend: {backend!r}")
    axis_size = mesh.shape[seq_axis]
    if query.shape[1] % axis_size:
        raise ValueError(
            f"sequence length {query.shape[1]} not divisible by "
            f"{seq_axis}={axis_size}"
        )
    if query.shape[2] % axis_size:
        raise ValueError(
            f"head count {query.shape[2]} not divisible by "
            f"{seq_axis}={axis_size}; use ring_attention for H < mesh size"
        )
    spec = P(batch_axis, seq_axis, None, None)
    fn = shard_map(
        functools.partial(
            _ulysses_shard_fn, axis_name=seq_axis, scale=float(scale),
            backend=backend,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(query, key, value)
