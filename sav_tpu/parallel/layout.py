"""Declarative sharding layouts — one object that *states* how a run is
partitioned.

Before this module, every parallelism arm declared its sharding in a
different place: TP was a regex-rule list in :mod:`sav_tpu.parallel.
sharding`, FSDP a shard-biggest-dim heuristic bolted on after the rules,
pipeline/MoE their own rule lists, and the batch/activation specs were
inline ``PartitionSpec`` constructions scattered through the trainer and
the serve engine. :class:`SpecLayout` is the canonical, serializable
statement of a layout — named specs per layer role (qkv / out-proj /
fc1 / fc2 / embed / norm / head, plus the expert and pipe-stage trees and
the activation/batch specs) — from which every param and activation spec
in the repo is derived. The legacy rule lists in ``sharding.py`` are thin
consumers of the default layouts, and savlint SAV117 keeps ad-hoc
``PartitionSpec`` construction out of the rest of the tree.

Tensor parallelism comes in two shapes:

- **1D** (``tp_heads_axis='model'``): Megatron-style — attention heads and
  the MLP hidden dim column-split, output projections row-split; each
  block needs exactly one AllReduce on its output.
- **2D** (``tp_heads_axis='x'``, ``tp_feature_axis='y'``): the SUMMA-style
  grid the 2D-TP literature prescribes — heads/hidden over ``x`` AND the
  model feature dim over ``y``, so no single axis has to swallow the
  whole TP degree. The collective pairing per block: the ``x``-split
  contractions reduce over ``x`` (AllReduce), the ``y``-split feature dim
  all-gathers/reduce-scatters over ``y`` as activations enter/leave each
  projection — all partitioner-inserted from these specs. Activations
  carry ``P(batch, None, 'y')`` between blocks
  (:meth:`SpecLayout.activation_spec`; the model applies it through
  :meth:`BoundLayout.constrain_tokens` when a layout is threaded into
  ``create_model``).

Layouts serialize to JSON (:meth:`SpecLayout.to_dict` /
:meth:`SpecLayout.from_dict`) and round-trip through the preset files
``tools/mesh_tune.py`` emits (:func:`save_layout_preset` /
:func:`load_layout_preset`); ``train.py --layout-preset`` and
``ServeConfig.layout_preset`` accept either a preset path or a built-in
name (:func:`resolve_layout`). The chosen layout is stamped into the run
manifest as ``notes.layout`` by the trainer and the serve engine.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import warnings
from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sav_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
    create_mesh,
)

# 2D tensor-parallel axis names (SNIPPETS.md [2]: named 2D-TP specs over
# x,y). 'x' is the major axis (heads / MLP hidden — the 1D 'model' role);
# 'y' is the minor axis (the model feature dim).
TP_X_AXIS = "x"
TP_Y_AXIS = "y"

_BUILTIN_NAME = re.compile(
    r"^(dp|tp(?P<tp>\d+)|fsdp(?P<fsdp>\d+)|2d(?P<x>\d+)x(?P<y>\d+))$"
)


def _spec_to_jsonable(spec: P) -> list:
    """PartitionSpec -> JSON shape: None | str | [str, ...] per entry."""
    out = []
    for entry in spec:
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def _spec_from_jsonable(entries: list) -> P:
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


def specs_from_rules(params: Any, rules: list[tuple[str, Any]]) -> Any:
    """Tree of ``PartitionSpec`` matching ``params`` from (regex, spec)
    rules — the one rule matcher every consumer (layout-derived and
    custom) goes through. First matching rule whose spec fits the leaf's
    rank wins; no match replicates."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def spec_for(path, leaf):
        path_str = "/".join(
            k.key if hasattr(k, "key") else str(k) for k in path
        )
        for pattern, spec in rules:
            if re.search(pattern, path_str) and len(spec) <= leaf.ndim:
                return spec
        return P()

    specs = [spec_for(path, leaf) for path, leaf in flat]
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


# ------------------------------------------------------------- FSDP augment

# Warn-once registry for the FSDP replication fallback: keyed by
# (path-or-shape, axis size) so distinct offenders each get one warning
# and repeated sweeps over the same tree stay quiet.
_fsdp_fallback_warned: set = set()


def reset_fsdp_fallback_warnings() -> None:
    """Test seam: forget which FSDP fallbacks have already warned."""
    _fsdp_fallback_warned.clear()


def add_fsdp_axis(
    spec: Any,
    shape: tuple[int, ...],
    fsdp_size: int,
    *,
    min_elements: int,
    axis: str = FSDP_AXIS,
    path: str = "",
) -> Any:
    """Augment a PartitionSpec with FSDP sharding (ZeRO-3 style).

    Divisibility-aware by rule: among the dims the layout left free
    (entry ``None``), the largest one divisible by ``fsdp_size`` is
    sharded; an indivisible biggest dim falls back to the next divisible
    one rather than forcing an uneven shard. When NO free dim divides,
    the parameter stays replicated — and that fallback WARNS (once per
    offender): a silently-replicated large parameter defeats the memory
    win FSDP was turned on for. Small tensors (< ``min_elements``) stay
    replicated silently — sharding tiny norm scales/biases costs more in
    collective latency than it saves in HBM.
    """
    import numpy as np

    if int(np.prod(shape)) < min_elements:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    candidates = [
        (shape[i], i)
        for i, e in enumerate(entries)
        if e is None and shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size
    ]
    if not candidates:
        key = (path or str(shape), fsdp_size)
        if key not in _fsdp_fallback_warned:
            _fsdp_fallback_warned.add(key)
            warnings.warn(
                f"FSDP fallback: no free dim of {path or 'parameter'} "
                f"{tuple(shape)} divides the '{axis}' axis size "
                f"{fsdp_size}; the parameter stays REPLICATED (its HBM is "
                "paid on every shard). Pick an fsdp size that divides the "
                "model's dims, or accept the replication (reported once "
                "per offender).",
                stacklevel=2,
            )
        return spec
    _, dim = max(candidates)
    entries[dim] = axis
    return P(*entries)


# ---------------------------------------------------------------- SpecLayout


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """Canonical, serializable sharding layout (the SNIPPETS.md [3] shape).

    ``mesh_axes`` is the ordered axis→size table the mesh is built from
    (one ``-1`` absorbs the remaining devices); the ``*_axis`` fields name
    which of those axes carries each parallelism arm. Everything else —
    per-role param specs, the regex rule list, batch/activation specs —
    is *derived*, so the dataclass stays the single declarative source.
    """

    name: str = "dp"
    mesh_axes: tuple = ((DATA_AXIS, -1),)
    tp_heads_axis: Optional[str] = None  # 'model' (1D) | 'x' (2D major)
    tp_feature_axis: Optional[str] = None  # 'y' (2D minor)
    data_axis: str = DATA_AXIS
    fsdp_axis: Optional[str] = None
    expert_axis: Optional[str] = None
    pipe_axis: Optional[str] = None
    seq_axis: Optional[str] = None
    # Shard the classifier head over the TP axes (vocab-parallel style).
    # Off in every built-in preset: the head is a sliver of the FLOPs and
    # replicated logits keep the loss/eval path collective-free.
    shard_head: bool = False
    fsdp_min_elements: int = 2**16
    # Provenance: 'builtin:<name>' | 'preset:<path>' | 'mesh-axes' | None.
    source: Optional[str] = None

    def __post_init__(self):
        axes = self.mesh_axes
        if isinstance(axes, dict):
            axes = tuple(axes.items())
        else:
            axes = tuple((str(a), int(s)) for a, s in axes)
        object.__setattr__(self, "mesh_axes", axes)
        names = [a for a, _ in axes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate mesh axes in {names}")
        if sum(1 for _, s in axes if s == -1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if self.tp_feature_axis and not self.tp_heads_axis:
            raise ValueError(
                "tp_feature_axis (2D minor) requires tp_heads_axis (major)"
            )
        for field in (
            "tp_heads_axis", "tp_feature_axis", "fsdp_axis",
            "expert_axis", "pipe_axis", "seq_axis",
        ):
            axis = getattr(self, field)
            if axis is not None and axis not in names:
                raise ValueError(
                    f"{field}={axis!r} is not a mesh axis (have {names})"
                )

    # ------------------------------------------------------------- axes

    def axis_dict(self) -> dict[str, int]:
        return dict(self.mesh_axes)

    def tp_degree(self) -> int:
        """Product of the (declared, non-wildcard) TP axis sizes."""
        sizes = self.axis_dict()
        degree = 1
        for axis in (self.tp_heads_axis, self.tp_feature_axis):
            if axis is not None and sizes.get(axis, -1) != -1:
                degree *= sizes[axis]
        return degree

    def create_mesh(self, devices=None) -> Mesh:
        """Build the layout's mesh. A ``-1`` axis absorbs the remaining
        devices (all of them when ``devices`` is None); a fully explicit
        layout takes exactly the devices it sizes — a ``{"data": 1,
        "x": 2, "y": 2}`` serving preset claims 4 chips of however many
        the host has, instead of failing the product check."""
        sizes = self.axis_dict()
        if devices is None and sizes and all(s != -1 for s in sizes.values()):
            import numpy as np

            need = int(np.prod(list(sizes.values())))
            have = jax.devices()
            if need < len(have):
                devices = have[:need]
        return create_mesh(sizes, devices=devices)

    def validate_against_mesh(self, mesh: Mesh) -> None:
        """The layout's declared axes must exist on ``mesh`` with the
        declared sizes (``-1`` matches anything). A mismatch means two
        sources of layout truth — fail loudly."""
        for field in (
            "tp_heads_axis", "tp_feature_axis", "fsdp_axis",
            "expert_axis", "pipe_axis", "seq_axis",
        ):
            axis = getattr(self, field)
            if axis is not None and axis not in mesh.axis_names:
                raise ValueError(
                    f"layout {self.name!r} declares {field}={axis!r} but "
                    f"the mesh has axes {mesh.axis_names}"
                )
        for axis, size in self.mesh_axes:
            if axis not in mesh.axis_names:
                raise ValueError(
                    f"layout {self.name!r} declares mesh axis {axis!r} but "
                    f"the mesh has {mesh.axis_names}"
                )
            if size != -1 and mesh.shape[axis] != size:
                raise ValueError(
                    f"layout {self.name!r} sizes axis {axis!r}={size} but "
                    f"the mesh has {axis!r}={mesh.shape[axis]}"
                )

    def batch_axes(self) -> tuple[str, ...]:
        """Mesh axes the batch dim shards over (data + fsdp when present —
        FSDP is batch-parallel for activations)."""
        names = [a for a, _ in self.mesh_axes]
        return tuple(
            a for a in (self.data_axis, self.fsdp_axis)
            if a is not None and a in names
        )

    # ------------------------------------------------------------- specs

    def batch_spec(self, dim: int = 0) -> P:
        """Spec placing the batch axes on dimension ``dim`` (``dim=0`` is
        the plain per-leaf batch spec; the trainer's transposed-images and
        leading-steps placements use other dims)."""
        return P(*([None] * dim), self.batch_axes())

    def activation_spec(self) -> P:
        """Token activations ``[B, L, D]``: batch axes on B, the 2D-TP
        feature axis (when present) on D."""
        return P(self.batch_axes(), None, self.tp_feature_axis)

    def role_specs(self) -> dict[str, P]:
        """The layer-role table: role -> canonical PartitionSpec.

        Kernel conventions (flax): ``qkv`` is the fused 4-D
        ``(in, 3, heads, head_ch)`` projection (the separate 3-D
        ``to_q/k/v`` kernels drop the packing dim), ``out_proj`` is
        ``(heads, head_ch, out)``, ``fc1``/``fc2`` are
        ``(in, hidden)``/``(hidden, out)``, ``expert`` carries a leading
        expert dim, ``pipe_stages`` a leading stage dim.
        """
        h, f = self.tp_heads_axis, self.tp_feature_axis
        specs = {
            "qkv": P(f, None, h, None),
            "qkv_bias": P(None, h, None),
            "out_proj": P(h, None, f),
            "fc1": P(f, h),
            "fc1_bias": P(h),
            "fc2": P(h, f),
            "embed": P(),
            "norm": P(),
            "head": P(f, h) if (self.shard_head and h) else P(),
            "expert": (
                P(self.expert_axis, None, None) if self.expert_axis else P()
            ),
            "pipe_stages": P(self.pipe_axis) if self.pipe_axis else P(),
            "activation": self.activation_spec(),
            "batch": self.batch_spec(),
        }
        if h is None:
            for role in ("qkv", "qkv_bias", "out_proj", "fc1", "fc1_bias",
                         "fc2"):
                specs[role] = P()
        return specs

    def param_rules(self) -> list[tuple[str, P]]:
        """The (path-regex, spec) rule list this layout implies — the one
        ``sharding.DEFAULT_*_RULES`` are now derived from. Every spec is
        read out of :meth:`role_specs` (ONE table; the separate
        ``to_q/k/v`` kernels and the biases are positional projections of
        the fused-qkv role, not hand-written duplicates). Pipe first (the
        stage-axis placement must win over suffix rules), then expert,
        then TP."""
        roles = self.role_specs()
        rules: list[tuple[str, P]] = []
        if self.pipe_axis:
            rules.append((r"pipe_stages/", roles["pipe_stages"]))
        if self.expert_axis:
            expert = roles["expert"]
            rules += [
                (r"experts_(w1|w2)$", expert),
                (r"experts_(b1|b2)$", P(*list(expert)[:2])),
            ]
        if self.tp_heads_axis:
            qkv = roles["qkv"]              # (in, 3, heads, head_ch)
            qkv_sep = P(qkv[0], qkv[2], qkv[3])  # drop the packing dim
            qkv_bias = roles["qkv_bias"]    # (3, heads, head_ch)
            sep_bias = P(qkv_bias[1], qkv_bias[2])
            rules += [
                (r"to_qkv/kernel$", qkv),
                (r"to_qkv/bias$", qkv_bias),
                (r"to_q/kernel$", qkv_sep),
                (r"to_k/kernel$", qkv_sep),
                (r"to_v/kernel$", qkv_sep),
                (r"to_(q|k|v)/bias$", sep_bias),
                (r"to_out/kernel$", roles["out_proj"]),
                (r"(fc1|expand)/kernel$", roles["fc1"]),
                (r"(fc1|expand)/bias$", roles["fc1_bias"]),
                (r"(fc2|project)/kernel$", roles["fc2"]),
            ]
            if self.shard_head:
                head = roles["head"]
                rules += [
                    (r"head/kernel$", head),
                    (r"head/bias$", P(head[1])),
                ]
        return rules

    def param_specs(self, params: Any, *, mesh: Optional[Mesh] = None) -> Any:
        """Tree of ``PartitionSpec`` for ``params`` (rules + FSDP
        augmentation; no mesh required when the layout sizes its axes
        explicitly — a wildcard ``-1`` fsdp axis resolves against
        ``mesh`` when given, and falls through un-augmented otherwise)."""
        specs = specs_from_rules(params, self.param_rules())
        if self.fsdp_axis is None:
            return specs
        sizes = self.axis_dict()
        fsdp_size = sizes.get(self.fsdp_axis, -1)
        if fsdp_size == -1 and mesh is not None and (
            self.fsdp_axis in mesh.axis_names
        ):
            # A -1 fsdp axis means "the remaining devices" — the mesh
            # knows how many that is. Skipping augmentation here would
            # silently replicate every parameter, the exact failure the
            # warn-once fallback exists to surface.
            fsdp_size = int(mesh.shape[self.fsdp_axis])
        if fsdp_size in (-1, 0, 1):
            return specs
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        spec_leaves = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
        new_leaves = [
            add_fsdp_axis(
                s,
                leaf.shape,
                fsdp_size,
                min_elements=self.fsdp_min_elements,
                axis=self.fsdp_axis,
                path="/".join(
                    str(getattr(k, "key", k)) for k in path
                ),
            )
            for s, (path, leaf) in zip(spec_leaves, flat)
        ]
        treedef = jax.tree_util.tree_structure(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def param_spec_table(self, params: Any) -> dict[str, P]:
        """Flattened ``path -> spec`` view of :meth:`param_specs` — the
        golden-snapshot surface (a layout regression reads as a one-line
        diff of this table)."""
        specs = self.param_specs(params)
        flat = jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
        return {
            "/".join(str(getattr(k, "key", k)) for k in path): spec
            for path, spec in flat
        }

    def param_shardings(self, params: Any, mesh: Mesh) -> Any:
        """Tree of ``NamedSharding`` for ``params`` on ``mesh``."""
        self.validate_against_mesh(mesh)
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.param_specs(params, mesh=mesh),
            is_leaf=lambda x: isinstance(x, P),
        )

    # ----------------------------------------------------- serialization

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "mesh_axes": dict(self.mesh_axes),
            "tp_heads_axis": self.tp_heads_axis,
            "tp_feature_axis": self.tp_feature_axis,
            "data_axis": self.data_axis,
            "fsdp_axis": self.fsdp_axis,
            "expert_axis": self.expert_axis,
            "pipe_axis": self.pipe_axis,
            "seq_axis": self.seq_axis,
            "shard_head": self.shard_head,
            "fsdp_min_elements": self.fsdp_min_elements,
        }

    @classmethod
    def from_dict(cls, doc: dict, *, source: Optional[str] = None
                  ) -> "SpecLayout":
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in doc.items() if k in known}
        if source is not None:
            kwargs["source"] = source
        return cls(**kwargs)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "SpecLayout":
        return cls.from_dict(json.loads(text))

    def describe(self, mesh: Optional[Mesh] = None) -> dict:
        """The ``notes.layout`` manifest stamp: name, axis sizes (resolved
        against the mesh when given), the TP shape, and which arms are
        on — "which layout was this run" reads from this one note."""
        sizes = (
            {a: int(mesh.shape[a]) for a in mesh.axis_names}
            if mesh is not None
            else self.axis_dict()
        )
        tp = None
        if self.tp_feature_axis:
            tp = "2d"
        elif self.tp_heads_axis:
            tp = "1d"
        return {
            "name": self.name,
            "mesh_axes": sizes,
            "tp": tp,
            "tp_axes": [
                a for a in (self.tp_heads_axis, self.tp_feature_axis)
                if a is not None
            ],
            "fsdp_axis": self.fsdp_axis,
            "expert_axis": self.expert_axis,
            "pipe_axis": self.pipe_axis,
            "seq_axis": self.seq_axis,
            "shard_head": self.shard_head,
            "source": self.source,
        }


# ---------------------------------------------------------------- binding


class BoundLayout:
    """A :class:`SpecLayout` bound to a concrete mesh: the object the
    trainer/engine hand around, turning declarative specs into
    ``NamedSharding`` placements and activation constraints."""

    def __init__(self, layout: SpecLayout, mesh: Mesh):
        layout.validate_against_mesh(mesh)
        self.layout = layout
        self.mesh = mesh

    def batch_sharding(self, dim: int = 0) -> NamedSharding:
        return NamedSharding(self.mesh, self.layout.batch_spec(dim))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def activation_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, self.layout.activation_spec())

    def param_shardings(self, tree: Any) -> Any:
        return self.layout.param_shardings(tree, self.mesh)

    def constrain_tokens(self, x):
        """Pin token activations ``[B, L, D]`` to the layout's activation
        spec (a ``with_sharding_constraint``). A no-op unless the layout
        declares a 2D-TP feature axis — 1D TP propagates fine from the
        param specs alone — or the input is not a token tensor."""
        if self.layout.tp_feature_axis is None or x.ndim != 3:
            return x
        return jax.lax.with_sharding_constraint(
            x, self.activation_sharding()
        )


def constrain_tokens(x, layout: Optional[BoundLayout]):
    """Module-side seam: apply ``layout.constrain_tokens`` when a bound
    layout was threaded in (``create_model(..., layout=...)``), identity
    otherwise."""
    if layout is None:
        return x
    return layout.constrain_tokens(x)


# ----------------------------------------------------------- construction


def layout_from_mesh_axes(
    axes: Optional[dict], *, name: Optional[str] = None
) -> SpecLayout:
    """Infer the layout a mesh-axes dict implies — the back-compat bridge
    for configs that state ``mesh_axes`` instead of a layout: ``model`` →
    1D TP, ``x``/``y`` → 2D TP, ``fsdp``/``expert``/``pipe``/``seq`` by
    presence. This is exactly the rule-selection logic
    ``sharding.param_shardings`` applied before layouts existed."""
    axes = dict(axes) if axes else {DATA_AXIS: -1}
    if TP_X_AXIS in axes:
        heads, feature = TP_X_AXIS, (TP_Y_AXIS if TP_Y_AXIS in axes else None)
    elif MODEL_AXIS in axes:
        heads, feature = MODEL_AXIS, None
    else:
        heads, feature = None, None
    if name is None:
        arms = [
            a for a in (
                "2d" if feature else ("tp" if heads else None),
                "fsdp" if FSDP_AXIS in axes else None,
                "expert" if EXPERT_AXIS in axes else None,
                "pipe" if PIPE_AXIS in axes else None,
                "seq" if SEQ_AXIS in axes else None,
            ) if a
        ]
        name = "+".join(arms) if arms else "dp"
    return SpecLayout(
        name=name,
        mesh_axes=tuple(axes.items()),
        tp_heads_axis=heads,
        tp_feature_axis=feature,
        fsdp_axis=FSDP_AXIS if FSDP_AXIS in axes else None,
        expert_axis=EXPERT_AXIS if EXPERT_AXIS in axes else None,
        pipe_axis=PIPE_AXIS if PIPE_AXIS in axes else None,
        seq_axis=SEQ_AXIS if SEQ_AXIS in axes else None,
        source="mesh-axes",
    )


def layout_from_mesh(mesh: Mesh, *, name: Optional[str] = None) -> SpecLayout:
    return layout_from_mesh_axes(
        {a: int(mesh.shape[a]) for a in mesh.axis_names}, name=name
    )


def builtin_layout(name: str) -> SpecLayout:
    """Named built-ins: ``dp`` | ``tp<N>`` | ``fsdp<N>`` | ``2d<X>x<Y>``
    (the remaining devices always land on the data axis)."""
    m = _BUILTIN_NAME.match(name)
    if not m:
        raise ValueError(
            f"unknown layout {name!r}; built-ins are 'dp', 'tpN', 'fsdpN', "
            "'2dXxY' (e.g. tp2, fsdp4, 2d2x2) or a preset JSON path"
        )
    axes: dict[str, int] = {DATA_AXIS: -1}
    if m.group("tp"):
        axes[MODEL_AXIS] = int(m.group("tp"))
    elif m.group("fsdp"):
        axes[FSDP_AXIS] = int(m.group("fsdp"))
    elif m.group("x"):
        axes[TP_X_AXIS] = int(m.group("x"))
        axes[TP_Y_AXIS] = int(m.group("y"))
    layout = layout_from_mesh_axes(axes, name=name)
    return dataclasses.replace(layout, source=f"builtin:{name}")


# ------------------------------------------------------------ preset files

PRESET_SCHEMA = 1


def save_layout_preset(
    path: str,
    layout: SpecLayout,
    *,
    grad_accum_steps: Optional[int] = None,
    provenance: Optional[dict] = None,
) -> dict:
    """Write a layout preset (the ``tools/mesh_tune.py`` output format;
    ``train.py --layout-preset`` / ``ServeConfig.layout_preset`` consume
    it). Atomic tmp+replace like every other artifact writer."""
    doc = {
        "schema": PRESET_SCHEMA,
        "kind": "layout-preset",
        "layout": layout.to_dict(),
    }
    if grad_accum_steps is not None:
        doc["grad_accum_steps"] = int(grad_accum_steps)
    if provenance:
        doc["provenance"] = provenance
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, default=str)
    os.replace(tmp, path)
    return doc


def load_layout_preset(path: str) -> tuple[SpecLayout, dict]:
    """Read a preset file -> (layout, full doc). Accepts both the preset
    wrapper and a bare layout dict (hand-written presets)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: layout preset must be a JSON object")
    body = doc.get("layout", doc)
    layout = SpecLayout.from_dict(body, source=f"preset:{path}")
    return layout, (doc if "layout" in doc else {"layout": body})


def resolve_layout(spec) -> Optional[SpecLayout]:
    """One resolver for every layout-accepting surface.

    ``None`` → None (caller falls back to mesh-axes inference);
    :class:`SpecLayout` → itself; dict → :meth:`SpecLayout.from_dict`;
    str → a preset path when it looks like one (contains a separator,
    ends in ``.json``, or exists on disk), else a built-in name.
    """
    if spec is None:
        return None
    if isinstance(spec, SpecLayout):
        return spec
    if isinstance(spec, dict):
        return SpecLayout.from_dict(spec)
    if isinstance(spec, str):
        if os.sep in spec or spec.endswith(".json") or os.path.exists(spec):
            return load_layout_preset(spec)[0]
        return builtin_layout(spec)
    raise TypeError(f"cannot resolve a layout from {type(spec).__name__}")
