"""ViT — Vision Transformer.

Capability parity with /root/reference/models/vit.py:9-99 (pre-LN encoder,
learned absolute position embeddings, zero-init CLS token and head), with the
attention core running on the backend-dispatched Pallas/XLA seam.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import (
    AddAbsPosEmbed,
    FFBlock,
    FixedPositionalEmbedding,
    PatchEmbedBlock,
    SelfAttentionBlock,
)
from sav_tpu.models.layers.moe import MoEFFBlock
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class EncoderBlock(nn.Module):
    """Pre-LN transformer block: LN→MHSA→res, LN→FF→res (vit.py:9-32)."""

    num_heads: int
    expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    moe_num_experts: Optional[int] = None  # MoE FF instead of dense FF
    moe_top_k: int = 2
    moe_router_z_loss_weight: float = 0.1  # see MoEFFBlock; 0 disables
    use_rotary: bool = False
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    seq_parallel: Optional[str] = None  # 'ring'|'ulysses' over seq_mesh
    seq_mesh: Optional[Any] = None
    # BoundLayout (sav_tpu/parallel/layout.py): pins the block's output
    # tokens to the layout's activation spec — the 2D-TP between-block
    # constraint. None (the default and every 1D/DP run) is a no-op.
    layout: Optional[Any] = None
    # int8 quantized projection/FFN dots ("int8" QAT / "int8_serve" —
    # sav_tpu/ops/quant.py); the attention core stays in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = nn.LayerNorm(dtype=self.dtype)(inputs)
        x = SelfAttentionBlock(
            num_heads=self.num_heads,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            use_rotary=self.use_rotary,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            seq_parallel=self.seq_parallel,
            seq_mesh=self.seq_mesh,
            quant=self.quant,
            dtype=self.dtype,
        )(x, is_training)
        x = x + inputs
        y = nn.LayerNorm(dtype=self.dtype)(x)
        if self.moe_num_experts:
            y = MoEFFBlock(
                num_experts=self.moe_num_experts,
                top_k=self.moe_top_k,
                router_z_loss_weight=self.moe_router_z_loss_weight,
                expand_ratio=self.expand_ratio,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
            )(y, is_training)
        else:
            y = FFBlock(
                expand_ratio=self.expand_ratio,
                dropout_rate=self.dropout_rate,
                quant=self.quant,
                dtype=self.dtype,
            )(y, is_training)
        from sav_tpu.parallel.layout import constrain_tokens

        return constrain_tokens(x + y, self.layout)


class Encoder(nn.Module):
    """Abs pos-emb + dropout, N pre-LN blocks, final LN (vit.py:35-58)."""

    num_layers: int
    num_heads: int
    expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    moe_num_experts: Optional[int] = None
    moe_top_k: int = 2
    moe_router_z_loss_weight: float = 0.1  # see MoEFFBlock; 0 disables
    moe_every: int = 2  # MoE FF on every moe_every-th block (GShard-style)
    # 'learned' (reference vit.py:46), 'sincos', 'rotary' (RoPE on Q/K in
    # every block), or 'none'.
    pos_embed: str = "learned"
    # Rematerialize each encoder block in the backward pass
    # (jax.checkpoint via nn.remat): activation HBM drops from O(layers)
    # block internals to O(layers) block *boundaries*, for ~1/3 more
    # forward FLOPs — the standard TPU trade when batch or sequence
    # length is HBM-bound.
    remat: bool = False
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    seq_parallel: Optional[str] = None  # 'ring'|'ulysses' over seq_mesh
    seq_mesh: Optional[Any] = None
    layout: Optional[Any] = None  # see EncoderBlock.layout
    quant: Optional[str] = None  # see EncoderBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        if self.pos_embed == "learned":
            x = AddAbsPosEmbed(dtype=self.dtype)(inputs)
        elif self.pos_embed == "sincos":
            x = FixedPositionalEmbedding(dtype=self.dtype)(inputs)
        elif self.pos_embed in ("rotary", "none"):
            x = inputs
        else:
            raise ValueError(f"unknown pos_embed mode: {self.pos_embed!r}")
        x = nn.Dropout(rate=self.dropout_rate)(x, deterministic=not is_training)
        # nn.remat's static_argnums counts the bound module as argument 0,
        # so is_training (python-bool control flow inside the block) is 2.
        block_cls = (
            nn.remat(EncoderBlock, static_argnums=(2,)) if self.remat
            else EncoderBlock
        )
        for i in range(self.num_layers):
            is_moe = bool(self.moe_num_experts) and i % self.moe_every == (
                self.moe_every - 1
            )
            x = block_cls(
                num_heads=self.num_heads,
                expand_ratio=self.expand_ratio,
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                moe_num_experts=self.moe_num_experts if is_moe else None,
                moe_top_k=self.moe_top_k,
                moe_router_z_loss_weight=self.moe_router_z_loss_weight,
                use_rotary=self.pos_embed == "rotary",
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                seq_parallel=self.seq_parallel,
                seq_mesh=self.seq_mesh,
                layout=self.layout,
                quant=self.quant,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x, is_training)
        return nn.LayerNorm(dtype=self.dtype)(x)


class ViT(nn.Module):
    """inputs ``[B, H, W, C]`` NHWC → logits ``[B, num_classes]`` (vit.py:61-99)."""

    num_classes: int
    embed_dim: int
    num_layers: int
    num_heads: int
    patch_shape: tuple[int, int]
    expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    moe_num_experts: Optional[int] = None
    moe_top_k: int = 2
    moe_router_z_loss_weight: float = 0.1  # see MoEFFBlock; 0 disables
    moe_every: int = 2
    pos_embed: str = "learned"
    remat: bool = False  # see Encoder.remat
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    seq_parallel: Optional[str] = None  # 'ring'|'ulysses' over seq_mesh
    seq_mesh: Optional[Any] = None
    layout: Optional[Any] = None  # see EncoderBlock.layout
    # int8 quant arm: encoder projections/FFNs + the classifier head;
    # the patch embed conv and pos embeds stay in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = PatchEmbedBlock(
            patch_shape=self.patch_shape, embed_dim=self.embed_dim, dtype=self.dtype
        )(inputs)
        b = x.shape[0]
        cls_tok = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
        cls_tok = jnp.broadcast_to(cls_tok.astype(x.dtype), (b, 1, self.embed_dim))
        x = jnp.concatenate([cls_tok, x], axis=1)
        x = Encoder(
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            expand_ratio=self.expand_ratio,
            attn_dropout_rate=self.attn_dropout_rate,
            dropout_rate=self.dropout_rate,
            moe_num_experts=self.moe_num_experts,
            moe_top_k=self.moe_top_k,
            moe_router_z_loss_weight=self.moe_router_z_loss_weight,
            moe_every=self.moe_every,
            pos_embed=self.pos_embed,
            remat=self.remat,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            seq_parallel=self.seq_parallel,
            seq_mesh=self.seq_mesh,
            layout=self.layout,
            quant=self.quant,
            dtype=self.dtype,
        )(x, is_training)
        cls_out = x[:, 0]
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(cls_out)
