"""Config-reachable pipeline parallelism for the ViT family.

:mod:`sav_tpu.parallel.pipelining` provides the GPipe schedule as a bare
library op (stage_fn + stacked params). This module packages it as a normal
Flax model so the *same* Trainer/``fit()``/checkpoint/CLI path that runs
every other zoo model runs a pipelined one — ``train.py --pp S`` builds it
(VERDICT r4 item 6; capability headroom over the reference, which had data
parallelism only, SURVEY.md §2.7).

Design:

- The encoder's ``num_layers`` blocks are grouped into ``S = mesh['pipe']``
  stages of ``num_layers/S`` blocks each. Per-stage parameters live in ONE
  flax param subtree ``pipe_stages`` whose every leaf carries a leading
  ``[S, ...]`` stage axis — :func:`sav_tpu.parallel.sharding.param_shardings`
  shards that axis over ``pipe`` (``DEFAULT_PP_RULES``), so stage *i*'s
  weights exist only on pipe slice *i*, and the optimizer-state mirrors
  (Adam mu/nu, EMA) inherit the same placement by path suffix.
- Stem (patch embed + CLS + position embedding), final LayerNorm, and head
  stay outside the pipeline and replicate over ``pipe`` — they are a few
  percent of FLOPs/params; pipelining them would buy nothing and cost two
  extra ring hops.
- ``sequential=True`` (or initialization, or no mesh) runs the stages as a
  plain Python loop — the numerics reference the CPU-mesh test compares
  against, and what ``model.init`` uses (the schedule is execution-only;
  parameters are identical either way).

Scope (enforced, not silent): stage blocks run deterministically — dropout /
stochastic-depth inside pipelined stages would need per-tick RNG plumbing
through the ``lax.scan`` schedule (fold rng over (stage, tick)) which no
recipe currently needs; MoE's sown balance losses cannot cross the
``shard_map`` boundary. Both compositions raise at construction.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import (
    AddAbsPosEmbed,
    FixedPositionalEmbedding,
    PatchEmbedBlock,
)
from sav_tpu.models.vit import EncoderBlock
from sav_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS
from sav_tpu.parallel.pipelining import pipeline, stack_stage_params

Dtype = Any


class ViTStage(nn.Module):
    """One pipeline stage: ``depth`` deterministic pre-LN encoder blocks."""

    depth: int
    num_heads: int
    expand_ratio: float = 4.0
    use_rotary: bool = False
    remat: bool = False  # rematerialize each block (see vit.Encoder.remat)
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, is_training: bool) -> jax.Array:
        # nn.remat's static_argnums counts the bound module as argument 0,
        # so is_training (python-bool control flow in the block) is 2.
        block_cls = (
            nn.remat(EncoderBlock, static_argnums=(2,)) if self.remat
            else EncoderBlock
        )
        for i in range(self.depth):
            x = block_cls(
                num_heads=self.num_heads,
                expand_ratio=self.expand_ratio,
                use_rotary=self.use_rotary,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                dtype=self.dtype,
                name=f"layer_{i}",
            )(x, is_training)
        return x


class PipelinedViT(nn.Module):
    """ViT with its encoder stack pipelined over the ``pipe`` mesh axis.

    Same math as :class:`sav_tpu.models.vit.ViT` (stem → pre-LN encoder →
    final LN → zero-init head; /root/reference/models/vit.py:61-99 is the
    capability anchor), different *execution*: the encoder runs the GPipe
    microbatch schedule of :func:`sav_tpu.parallel.pipelining.pipeline`.
    """

    num_classes: int
    embed_dim: int
    num_layers: int
    num_heads: int
    patch_shape: tuple[int, int]
    num_stages: int
    num_microbatches: int = 8
    expand_ratio: float = 4.0
    remat: bool = False  # rematerialize stage blocks in the backward pass
    pos_embed: str = "learned"  # 'learned' | 'sincos' | 'rotary' | 'none'
    # The mesh carrying the 'pipe' axis (and usually 'data'). None → the
    # sequential path (single-process debugging / numerics reference).
    pipe_mesh: Optional[Any] = None
    batch_axis: Optional[str] = DATA_AXIS
    sequential: bool = False
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        if self.num_layers % self.num_stages:
            raise ValueError(
                f"num_layers={self.num_layers} must divide into "
                f"num_stages={self.num_stages} equal pipeline stages"
            )
        x = PatchEmbedBlock(
            patch_shape=self.patch_shape, embed_dim=self.embed_dim,
            dtype=self.dtype,
        )(inputs)
        b = x.shape[0]
        cls_tok = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
        cls_tok = jnp.broadcast_to(cls_tok.astype(x.dtype), (b, 1, self.embed_dim))
        x = jnp.concatenate([cls_tok, x], axis=1)
        if self.pos_embed == "learned":
            x = AddAbsPosEmbed(dtype=self.dtype)(x)
        elif self.pos_embed == "sincos":
            x = FixedPositionalEmbedding(dtype=self.dtype)(x)
        elif self.pos_embed not in ("rotary", "none"):
            raise ValueError(f"unknown pos_embed mode: {self.pos_embed!r}")

        stage = ViTStage(
            depth=self.num_layers // self.num_stages,
            num_heads=self.num_heads,
            expand_ratio=self.expand_ratio,
            use_rotary=self.pos_embed == "rotary",
            remat=self.remat,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            dtype=self.dtype,
        )

        def init_stages(rng):
            return stack_stage_params([
                stage.init(
                    {"params": jax.random.fold_in(rng, i)}, x[:1], False
                )["params"]
                for i in range(self.num_stages)
            ])

        stages = self.param("pipe_stages", init_stages)

        def stage_fn(stage_params, h):
            return stage.apply({"params": stage_params}, h, is_training)

        if self.sequential or self.pipe_mesh is None or self.is_initializing():
            # Numerics-reference path; also used at init (the GPipe schedule
            # is execution-only — parameters are identical either way).
            h = x
            for i in range(self.num_stages):
                h = stage_fn(jax.tree.map(lambda p: p[i], stages), h)
            x = h
        else:
            x = pipeline(
                stage_fn,
                stages,
                x,
                mesh=self.pipe_mesh,
                num_microbatches=self.num_microbatches,
                pipe_axis=PIPE_AXIS,
                batch_axis=(
                    self.batch_axis
                    if self.batch_axis in self.pipe_mesh.axis_names
                    else None
                ),
            )

        x = nn.LayerNorm(dtype=self.dtype)(x)
        cls_out = x[:, 0]
        return nn.Dense(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(cls_out)


def create_pipelined_model(
    model_name: str,
    *,
    num_stages: int,
    mesh,
    num_microbatches: int = 8,
    num_classes: int = 1000,
    dtype=jnp.float32,
    backend: Optional[str] = None,
    logits_dtype=None,
    **overrides,
) -> PipelinedViT:
    """Build the pipelined counterpart of a registered ViT-family config.

    Reuses the registry hyperparameters (embed_dim/num_layers/num_heads/
    patch_shape/pos_embed) of ``model_name``; non-ViT families and
    unsupported compositions (MoE, dropout inside stages) raise.
    """
    from sav_tpu.models.registry import _REGISTRY, model_names
    from sav_tpu.models.vit import ViT

    if model_name not in _REGISTRY:
        raise ValueError(
            f"unknown model {model_name!r}; available: {', '.join(model_names())}"
        )
    cls, kwargs = _REGISTRY[model_name]
    if cls is not ViT:
        raise ValueError(
            f"pipeline parallelism is ViT-family only (uniform shape-"
            f"preserving encoder stack); {model_name!r} is {cls.__name__}. "
            "CvT/BoTNet change resolution between stages, TNT carries a "
            "two-stream state, CaiT switches attention type mid-trunk — "
            "see docs/parallelism.md"
        )
    merged = dict(kwargs, **overrides)
    if merged.get("moe_num_experts"):
        raise ValueError(
            "MoE + pipeline parallelism is unsupported: sown balance losses "
            "cannot cross the pipeline's shard_map boundary"
        )
    for field in ("attn_dropout_rate", "dropout_rate"):
        if merged.pop(field, 0.0):
            raise ValueError(
                f"{field} > 0 inside pipelined stages is unsupported "
                "(per-tick RNG plumbing through the GPipe scan is not "
                "wired); train without stage dropout or without --pp"
            )
    if PIPE_AXIS not in mesh.axis_names or mesh.shape[PIPE_AXIS] != num_stages:
        raise ValueError(
            f"mesh must carry a '{PIPE_AXIS}' axis of size {num_stages}; "
            f"got axes {dict(mesh.shape)}"
        )
    return PipelinedViT(
        num_classes=num_classes,
        num_stages=num_stages,
        num_microbatches=num_microbatches,
        pipe_mesh=mesh,
        backend=backend,
        logits_dtype=logits_dtype,
        dtype=dtype,
        **merged,
    )
