"""Parameter surgery: resolution transfer for pretrained checkpoints.

Capability beyond the reference (which had no finetuning path at all): the
standard ViT recipe of bicubic-resampling the learned absolute position
table when changing input resolution (DeiT/CaiT finetune at 384 from a 224
pretrain this way). Works on any param tree containing ``AddAbsPosEmbed``
tables (ViT, CaiT, TNT outer stream, MLP-Mixer has none).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

POS_EMBED_KEY = "pos_embed"


def _has_cls(length: int) -> bool:
    """Infer a leading CLS slot from the token count: k² → pure grid,
    1 + k² → CLS + grid (the two are never ambiguous for k ≥ 1)."""
    if math.isqrt(length) ** 2 == length:
        return False
    if math.isqrt(length - 1) ** 2 == length - 1:
        return True
    raise ValueError(f"token count {length} is neither k² nor 1+k²")


def resize_pos_embed_table(
    table: jax.Array,
    new_len: int,
    *,
    has_cls: bool | None = None,
    method: str = "bicubic",
) -> jax.Array:
    """Resample a ``[1, L, D]`` position table to ``[1, new_len, D]``.

    The (square) patch grid is resized with ``jax.image.resize``; a leading
    CLS position (auto-detected from the token count unless ``has_cls`` is
    given) is carried over unchanged.
    """
    if table.ndim != 3 or table.shape[0] != 1:
        raise ValueError(f"expected [1, L, D] table, got {table.shape}")
    if table.shape[1] == new_len:
        return table
    if has_cls is None:
        has_cls = _has_cls(table.shape[1])
    cls_part = table[:, :1] if has_cls else table[:, :0]
    grid_part = table[:, 1:] if has_cls else table
    grid_new = new_len - cls_part.shape[1]
    g_old = math.isqrt(grid_part.shape[1])
    g_new = math.isqrt(grid_new)
    if g_old * g_old != grid_part.shape[1] or g_new * g_new != grid_new:
        raise ValueError(
            f"non-square grids: {grid_part.shape[1]} -> {grid_new} tokens"
        )
    dim = table.shape[-1]
    grid = grid_part.reshape(1, g_old, g_old, dim).astype(jnp.float32)
    resized = jax.image.resize(grid, (1, g_new, g_new, dim), method=method)
    resized = resized.reshape(1, grid_new, dim).astype(table.dtype)
    return jnp.concatenate([cls_part, resized], axis=1)


def adapt_pos_embeds(params: Any, target_params: Any, *,
                     has_cls: bool | None = None,
                     method: str = "bicubic") -> Any:
    """Return ``params`` with every ``pos_embed`` table resized to match the
    corresponding table in ``target_params`` (e.g. from ``model.init`` at
    the new resolution). All other leaves pass through unchanged; shapes
    that already match are untouched.
    """
    flat_tgt = {
        tuple(p): l
        for p, l in jax.tree_util.tree_flatten_with_path(target_params)[0]
    }

    def fix(path, leaf):
        key = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        tgt = flat_tgt.get(tuple(path))
        if key == POS_EMBED_KEY and tgt is not None and tgt.shape != leaf.shape:
            return resize_pos_embed_table(
                leaf, tgt.shape[1], has_cls=has_cls, method=method
            )
        return leaf

    return jax.tree_util.tree_map_with_path(fix, params)
