"""Named model registry.

Replaces the reference's if/elif factory (/root/reference/models/create_model.py:6-215)
with a declarative dict. All 31 reference config names resolve here, with the
reference's config bugs fixed against the papers (SURVEY.md §2.9):
  - #13 TNT-S/TNT-B hyperparameters un-swapped,
  - #14 CvT embed dim 384 (not 368),
  - #15 duplicate ``mixer_s_patch32`` key → ``mixer_b_patch16``; Mixer-L has
    24 layers.
Extra names beyond reference parity: ``vit_s_patch16`` / ``deit_s_patch16``
(the BASELINE.json north-star benchmark model) and ``vit_ti_patch16``
(the CPU-runnable smoke config).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp

from sav_tpu.models.botnet import BoTNet
from sav_tpu.models.cait import CaiT
from sav_tpu.models.ceit import CeiT
from sav_tpu.models.cvt import CvT
from sav_tpu.models.mlp_mixer import MLPMixer
from sav_tpu.models.tnt import TNT
from sav_tpu.models.vit import ViT

_REGISTRY: dict[str, tuple[type, dict[str, Any]]] = {}


def register(name: str, cls: type, **kwargs):
    _REGISTRY[name] = (cls, kwargs)


def _vit(embed_dim, num_layers, num_heads, patch):
    return dict(
        embed_dim=embed_dim,
        num_layers=num_layers,
        num_heads=num_heads,
        patch_shape=(patch, patch),
    )


# --- ViT family (create_model.py:10-37 + north-star extras) -----------------
register("vit_ti_patch16", ViT, **_vit(192, 12, 3, 16))
register("vit_s_patch32", ViT, **_vit(384, 12, 6, 32))
register("vit_s_patch16", ViT, **_vit(384, 12, 6, 16))
register("deit_s_patch16", ViT, **_vit(384, 12, 6, 16))
register("vit_b_patch32", ViT, **_vit(768, 12, 12, 32))
register("vit_b_patch16", ViT, **_vit(768, 12, 12, 16))
register("vit_l_patch32", ViT, **_vit(1024, 24, 16, 32))
register("vit_l_patch16", ViT, **_vit(1024, 24, 16, 16))
# RoPE variant: the reference declared rotary in its to-do (README.md:5) but
# never wired it (SURVEY.md §2.9 #12); here it is a working first-class config.
register("vit_s_patch16_rope", ViT, **_vit(384, 12, 6, 16), pos_embed="rotary")
# MoE variant (beyond reference parity): DeiT-S trunk with a top-2-routed
# 8-expert FF on every other block; experts shard over the 'expert' mesh axis.
register(
    "vit_moe_s_patch16_e8",
    ViT,
    **_vit(384, 12, 6, 16),
    moe_num_experts=8,
    moe_top_k=2,
)

# --- BoTNet (create_model.py:38-49) ----------------------------------------
register("botnet_t3", BoTNet, stage_sizes=(3, 4, 6, 6))
register("botnet_t4", BoTNet, stage_sizes=(3, 4, 23, 6))
register("botnet_t5", BoTNet, stage_sizes=(3, 4, 23, 12))

# --- TNT (create_model.py:50-63; S/B fixed per paper & tnt_test.py:14-15) ---
register(
    "tnt_s_patch16",
    TNT,
    embed_dim=384, inner_ch=24, num_layers=12, num_heads=6, inner_num_heads=4,
    patch_shape=(16, 16),
)
register(
    "tnt_b_patch16",
    TNT,
    embed_dim=640, inner_ch=40, num_layers=12, num_heads=10, inner_num_heads=4,
    patch_shape=(16, 16),
)

# --- CeiT (create_model.py:64-78) ------------------------------------------
register("ceit_t", CeiT, embed_dim=192, num_layers=12, num_heads=3, patch_shape=(4, 4))
register("ceit_s", CeiT, embed_dim=384, num_layers=12, num_heads=6, patch_shape=(4, 4))
register("ceit_b", CeiT, embed_dim=768, num_layers=12, num_heads=12, patch_shape=(4, 4))


# --- CaiT (create_model.py:79-168) -----------------------------------------
def _cait(embed_dim, num_layers, num_heads, stoch_depth_rate, layerscale_eps):
    return dict(
        embed_dim=embed_dim,
        num_layers=num_layers,
        num_layers_token_only=2,
        num_heads=num_heads,
        patch_shape=(16, 16),
        stoch_depth_rate=stoch_depth_rate,
        layerscale_eps=layerscale_eps,
    )


register("cait_xxs_24", CaiT, **_cait(192, 24, 4, 0.05, 1e-5))
register("cait_xxs_36", CaiT, **_cait(192, 36, 4, 0.1, 1e-6))
register("cait_xs_24", CaiT, **_cait(288, 24, 6, 0.05, 1e-5))
register("cait_xs_36", CaiT, **_cait(288, 36, 6, 0.1, 1e-6))
register("cait_s_24", CaiT, **_cait(384, 24, 8, 0.1, 1e-5))
register("cait_s_36", CaiT, **_cait(384, 36, 8, 0.2, 1e-6))
register("cait_s_48", CaiT, **_cait(384, 48, 8, 0.3, 1e-6))
register("cait_m_24", CaiT, **_cait(768, 24, 16, 0.2, 1e-5))
register("cait_m_36", CaiT, **_cait(768, 36, 16, 0.3, 1e-6))
register("cait_m_48", CaiT, **_cait(768, 48, 16, 0.4, 1e-6))

# --- CvT (create_model.py:169-183; 384 per paper & cvt_test.py:14-15) -------
register(
    "cvt-13", CvT,
    embed_dims=(64, 192, 384), num_layers=(1, 2, 10), num_heads=(1, 3, 6),
)
register(
    "cvt-21", CvT,
    embed_dims=(64, 192, 384), num_layers=(1, 4, 16), num_heads=(1, 3, 6),
)
register(
    "cvt-w24", CvT,
    embed_dims=(192, 768, 1024), num_layers=(2, 2, 20), num_heads=(3, 12, 16),
)


# --- MLP-Mixer (create_model.py:184-213; keys/layers fixed per paper) -------
def _mixer(embed_dim, num_layers, tokens_ch, channels_ch, patch):
    return dict(
        embed_dim=embed_dim,
        num_layers=num_layers,
        tokens_hidden_ch=tokens_ch,
        channels_hidden_ch=channels_ch,
        patch_shape=(patch, patch),
    )


register("mixer_s_patch32", MLPMixer, **_mixer(512, 8, 256, 2048, 32))
register("mixer_s_patch16", MLPMixer, **_mixer(512, 8, 256, 2048, 16))
register("mixer_b_patch32", MLPMixer, **_mixer(768, 12, 384, 3072, 32))
register("mixer_b_patch16", MLPMixer, **_mixer(768, 12, 384, 3072, 16))
register("mixer_l_patch32", MLPMixer, **_mixer(1024, 24, 512, 4096, 32))
register("mixer_l_patch16", MLPMixer, **_mixer(1024, 24, 512, 4096, 16))


def model_names() -> list[str]:
    return sorted(_REGISTRY)


def create_model(
    model_name: str,
    *,
    num_classes: int = 1000,
    dtype=jnp.float32,
    backend: Optional[str] = None,
    logits_dtype=None,
    seq_parallel: Optional[str] = None,
    seq_mesh=None,
    layout=None,
    quant: Optional[str] = None,
    **overrides,
):
    """Instantiate a named model config.

    Args:
      model_name: a key from :func:`model_names`.
      num_classes: classifier width.
      dtype: compute dtype (params stay fp32).
      backend: attention backend ('xla' | 'fused' | 'pallas' | None=auto —
        the measured three-way dispatch) threaded to every attention block.
      logits_dtype: softmax dtype for the XLA attention path, threaded to
        every attention block (None = inherit ``dtype``, the reference's
        semantics; 'float32' forces f32 softmax under bf16 compute).
      seq_parallel: 'ring' | 'ulysses' — route self-attention through
        sequence parallelism over ``seq_mesh``'s 'seq' axis
        (sav_tpu.parallel.seq_parallel; ViT/DeiT every block, TNT outer
        stream, CeiT trunk — others raise).
      seq_mesh: the jax.sharding.Mesh carrying the 'seq' axis; required
        with ``seq_parallel``.
      quant: int8 quantized projection/FFN dots (sav_tpu/ops/quant.py):
        "int8" (AQT-style QAT training arm) or "int8_serve" (int8
        weights + per-channel scales, the quantized serving tree) —
        threaded to every projection/FFN/head dot in every family; the
        attention QK/AV core stays in ``dtype`` (PERF §5). None = the
        plain float path, byte-identical param tree to before.
      layout: a :class:`~sav_tpu.parallel.layout.BoundLayout` threaded to
        models with a layout seam (ViT family): encoder blocks pin token
        activations to the layout's activation spec — the 2D-TP
        between-block constraint (docs/parallelism.md). Models without
        the seam ignore it (their specs still come from the layout's
        param rules at placement time).
      **overrides: per-call hyperparameter overrides.
    """
    if model_name not in _REGISTRY:
        raise ValueError(
            f"unknown model {model_name!r}; available: {', '.join(model_names())}"
        )
    cls, kwargs = _REGISTRY[model_name]
    merged = dict(kwargs, num_classes=num_classes, dtype=dtype, **overrides)
    # Attention-free models (MLP-Mixer) have no backend seam — skip injection.
    if backend is not None and "backend" in cls.__dataclass_fields__:
        merged["backend"] = backend
    if logits_dtype is not None and "logits_dtype" in cls.__dataclass_fields__:
        merged["logits_dtype"] = logits_dtype
    if layout is not None and "layout" in cls.__dataclass_fields__:
        merged["layout"] = layout
    if quant is not None:
        if "quant" not in cls.__dataclass_fields__:
            raise ValueError(
                f"{model_name!r} does not support the int8 quant arm "
                "(every registered family does — a custom class must "
                "declare a 'quant' field to opt in)"
            )
        merged["quant"] = quant
    if seq_parallel is not None:
        if "seq_parallel" not in cls.__dataclass_fields__:
            raise ValueError(
                f"{model_name!r} does not support sequence parallelism "
                "(SP-capable: ViT/DeiT, TNT outer stream, CeiT trunk, "
                "CaiT trunk (ring-only, talking-heads); CvT's strided conv "
                "projections and BoTNet's 2-D relative-position bias keep "
                "the dense path — see docs/parallelism.md)"
            )
        merged["seq_parallel"] = seq_parallel
        merged["seq_mesh"] = seq_mesh
    return cls(**merged)


def model_supports(model_name: str, field: str) -> bool:
    """Whether the named model's class has ``field`` as a constructor
    option (e.g. 'remat' — ViT-family only; 'backend' — attention models)."""
    if model_name not in _REGISTRY:
        raise ValueError(
            f"unknown model {model_name!r}; available: {', '.join(model_names())}"
        )
    cls, _ = _REGISTRY[model_name]
    return field in cls.__dataclass_fields__
