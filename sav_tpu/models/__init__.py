"""Model zoo re-exports (parity with /root/reference/models/__init__.py:1-8)."""

from sav_tpu.models.botnet import BoTNet
from sav_tpu.models.cait import CaiT
from sav_tpu.models.ceit import CeiT
from sav_tpu.models.cvt import CvT
from sav_tpu.models.mlp_mixer import MLPMixer
from sav_tpu.models.registry import (
    create_model,
    model_names,
    model_supports,
    register,
)
from sav_tpu.models.surgery import adapt_pos_embeds, resize_pos_embed_table
from sav_tpu.models.tnt import TNT
from sav_tpu.models.vit import ViT

__all__ = [
    "adapt_pos_embeds",
    "resize_pos_embed_table",
    "ViT",
    "BoTNet",
    "CeiT",
    "CaiT",
    "CvT",
    "TNT",
    "MLPMixer",
    "create_model",
    "model_names",
    "model_supports",
    "register",
]
