"""CvT — Convolutional vision Transformer.

Reference: /root/reference/models/cvt.py:10-171. Three stages of strided conv
token embedding + conv-projection attention blocks; CLS token only in the
last stage; no position embeddings anywhere (the convs provide locality).
Reference bugs fixed: blocks are pre-LN as in the paper, and the CLS token is
carried alongside the grid instead of being zero-padded into it (cvt.py:10-16,
51-61, 152-164; SURVEY.md §2.9 #19).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import CvTSelfAttentionBlock, FFBlock
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class ConvTokenEmbedBlock(nn.Module):
    """Strided conv + flatten + LN (cvt.py:19-35)."""

    embed_dim: int
    kernel_size: tuple[int, int]
    stride: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array):
        x = nn.Conv(
            features=self.embed_dim,
            kernel_size=self.kernel_size,
            strides=(self.stride, self.stride),
            padding="SAME",
            dtype=self.dtype,
            name="proj",
        )(inputs)
        b, h, w, c = x.shape
        tokens = nn.LayerNorm(dtype=self.dtype)(x.reshape(b, h * w, c))
        return tokens, (h, w)


class StageBlock(nn.Module):
    """Pre-LN: LN→CvT conv-projection SA→res, LN→FF→res."""

    num_heads: int
    expand_ratio: float = 4.0
    with_cls: bool = False
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # int8 quantized pointwise projection/FFN dots; the conv token
    # embeds and depthwise convs stay in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, tokens: jax.Array, grid_shape: tuple[int, int], is_training: bool
    ) -> jax.Array:
        x = nn.LayerNorm(dtype=self.dtype)(tokens)
        x = CvTSelfAttentionBlock(
            num_heads=self.num_heads,
            with_cls=self.with_cls,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            quant=self.quant,
            dtype=self.dtype,
        )(x, grid_shape, is_training)
        tokens = tokens + x
        y = nn.LayerNorm(dtype=self.dtype)(tokens)
        y = FFBlock(
            expand_ratio=self.expand_ratio,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
        )(y, is_training)
        return tokens + y


class Stage(nn.Module):
    """Token embed (+ optional CLS) then N stage blocks (cvt.py:71-113)."""

    embed_dim: int
    num_layers: int
    num_heads: int
    kernel_size: tuple[int, int]
    stride: int
    expand_ratio: float = 4.0
    insert_cls: bool = False
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    quant: Optional[str] = None  # see StageBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool):
        tokens, grid_shape = ConvTokenEmbedBlock(
            embed_dim=self.embed_dim,
            kernel_size=self.kernel_size,
            stride=self.stride,
            dtype=self.dtype,
        )(inputs)
        if self.insert_cls:
            cls_tok = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
            cls_tok = jnp.broadcast_to(
                cls_tok.astype(tokens.dtype), (tokens.shape[0], 1, self.embed_dim)
            )
            tokens = jnp.concatenate([cls_tok, tokens], axis=1)
        for i in range(self.num_layers):
            tokens = StageBlock(
                num_heads=self.num_heads,
                expand_ratio=self.expand_ratio,
                with_cls=self.insert_cls,
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                quant=self.quant,
                dtype=self.dtype,
                name=f"block_{i}",
            )(tokens, grid_shape, is_training)
        return tokens, grid_shape


class CvT(nn.Module):
    num_classes: int
    embed_dims: tuple[int, int, int] = (64, 192, 384)
    num_layers: tuple[int, int, int] = (1, 2, 10)
    num_heads: tuple[int, int, int] = (1, 3, 6)
    strides: tuple[int, int, int] = (4, 2, 2)
    kernel_sizes: tuple = ((7, 7), (3, 3), (3, 3))
    expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    quant: Optional[str] = None  # see StageBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = inputs
        tokens = None
        for s in range(3):
            last = s == 2
            tokens, grid_shape = Stage(
                embed_dim=self.embed_dims[s],
                num_layers=self.num_layers[s],
                num_heads=self.num_heads[s],
                kernel_size=self.kernel_sizes[s],
                stride=self.strides[s],
                expand_ratio=self.expand_ratio,
                insert_cls=last,
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                quant=self.quant,
                dtype=self.dtype,
                name=f"stage_{s}",
            )(x, is_training)
            if not last:
                # Re-grid tokens for the next stage's conv embed (cvt.py:148-150).
                b = tokens.shape[0]
                h, w = grid_shape
                x = tokens.reshape(b, h, w, self.embed_dims[s])

        out = nn.LayerNorm(dtype=self.dtype)(tokens[:, 0])
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(out)
