"""BoTNet — Bottleneck Transformers.

Reference: /root/reference/models/botnet.py:17-331 — a ResNet-50-style
backbone whose final stage replaces the 3×3 conv with 2-D relative-position
MHSA. The reference version never ran (AttributeErrors + a wrong output
einsum, SURVEY.md §2.9 #1-3); this is the working TPU rebuild: the relative
logits come from :mod:`sav_tpu.ops.relative` and attention runs on the shared
Pallas/XLA seam (the bias rides the fused softmax).

Uses BatchNorm → the trainer threads ``batch_stats`` (the reference needed a
separate ``base_with_state.py`` trainer; here one trainer handles both).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import BoTMHSA, SqueezeExciteBlock
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class BottleneckResNetBlock(nn.Module):
    """1×1 → 3×3(stride) → 1×1 convs + BN + swish, optional SE, zero-init
    final BN scale (botnet.py:17-67)."""

    filters: int
    strides: int = 1
    se_ratio: Optional[float] = 0.25
    activation_fn: Callable = nn.swish
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        norm = lambda name, **kw: nn.BatchNorm(
            use_running_average=not is_training,
            momentum=0.9,
            dtype=self.dtype,
            name=name,
            **kw,
        )
        conv = lambda feats, k, s, name: nn.Conv(
            feats, (k, k), strides=(s, s), padding="SAME", use_bias=False,
            dtype=self.dtype, name=name,
        )
        residual = inputs
        x = conv(self.filters, 1, 1, "conv1")(inputs)
        x = self.activation_fn(norm("bn1")(x))
        x = conv(self.filters, 3, self.strides, "conv2")(x)
        x = self.activation_fn(norm("bn2")(x))
        if self.se_ratio is not None:
            x = SqueezeExciteBlock(se_ratio=self.se_ratio, dtype=self.dtype)(x)
        x = conv(self.filters * 4, 1, 1, "conv3")(x)
        x = norm("bn3", scale_init=nn.initializers.zeros)(x)
        if residual.shape != x.shape:
            residual = conv(self.filters * 4, 1, self.strides, "proj_conv")(residual)
            residual = norm("proj_bn")(residual)
        return self.activation_fn(x + residual)


class BoTBlock(nn.Module):
    """Bottleneck block with the 3×3 conv replaced by BoTMHSA; stride is a
    2×2 average pool after attention (botnet.py:202-252)."""

    filters: int
    num_heads: int = 4
    strides: int = 1
    activation_fn: Callable = nn.swish
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # int8 quantized MHSA Q/K/V projections; the 1×1 convs + BNs stay
    # in ``dtype`` (conv-dominated — see docs/quantization.md on why
    # BoTNet's HBM win is head+projection-sized only).
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        norm = lambda name, **kw: nn.BatchNorm(
            use_running_average=not is_training,
            momentum=0.9,
            dtype=self.dtype,
            name=name,
            **kw,
        )
        conv = lambda feats, k, s, name: nn.Conv(
            feats, (k, k), strides=(s, s), padding="SAME", use_bias=False,
            dtype=self.dtype, name=name,
        )
        residual = inputs
        x = conv(self.filters, 1, 1, "conv1")(inputs)
        x = self.activation_fn(norm("bn1")(x))
        x = BoTMHSA(
            num_heads=self.num_heads,
            head_ch=self.filters // self.num_heads,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            quant=self.quant,
            dtype=self.dtype,
            name="mhsa",
        )(x)
        if self.strides == 2:
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = self.activation_fn(norm("bn2")(x))
        x = conv(self.filters * 4, 1, 1, "conv3")(x)
        x = norm("bn3", scale_init=nn.initializers.zeros)(x)
        if residual.shape != x.shape:
            residual = conv(self.filters * 4, 1, self.strides, "proj_conv")(residual)
            residual = norm("proj_bn")(residual)
        return self.activation_fn(x + residual)


class BoTNet(nn.Module):
    num_classes: int
    stage_sizes: tuple[int, int, int, int] = (3, 4, 6, 6)
    num_heads: int = 4
    se_ratio: Optional[float] = 0.25
    activation_fn: Callable = nn.swish
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    quant: Optional[str] = None  # see BoTBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding="SAME", use_bias=False,
            dtype=self.dtype, name="stem_conv",
        )(inputs)
        x = nn.BatchNorm(
            use_running_average=not is_training, momentum=0.9, dtype=self.dtype,
            name="stem_bn",
        )(x)
        x = self.activation_fn(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        filters = (64, 128, 256)
        for stage in range(3):
            for block in range(self.stage_sizes[stage]):
                x = BottleneckResNetBlock(
                    filters=filters[stage],
                    strides=2 if stage > 0 and block == 0 else 1,
                    se_ratio=self.se_ratio,
                    activation_fn=self.activation_fn,
                    dtype=self.dtype,
                    name=f"stage{stage + 1}_block{block}",
                )(x, is_training)
        for block in range(self.stage_sizes[3]):
            x = BoTBlock(
                filters=512,
                num_heads=self.num_heads,
                strides=2 if block == 0 else 1,
                activation_fn=self.activation_fn,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                quant=self.quant,
                dtype=self.dtype,
                name=f"stage4_block{block}",
            )(x, is_training)

        x = jnp.mean(x, axis=(1, 2))
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(x)
