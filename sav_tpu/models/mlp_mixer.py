"""MLP-Mixer. Reference: /root/reference/models/mlp_mixer.py:10-60."""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import FFBlock, PatchEmbedBlock
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class MixerBlock(nn.Module):
    """Token-mixing MLP (on transposed tokens) + channel-mixing MLP."""

    tokens_hidden_ch: int
    channels_hidden_ch: int
    dropout_rate: float = 0.0
    # int8 quantized mixing MLPs (both token- and channel-mixing dots
    # route through sav_tpu/ops/quant.py).
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = nn.LayerNorm(dtype=self.dtype)(inputs)
        x = jnp.swapaxes(x, -1, -2)  # [B, D, L]
        x = FFBlock(
            hidden_ch=self.tokens_hidden_ch,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
            name="token_mixing",
        )(x, is_training)
        x = jnp.swapaxes(x, -1, -2)
        x = x + inputs
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = FFBlock(
            hidden_ch=self.channels_hidden_ch,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
            name="channel_mixing",
        )(y, is_training)
        return x + y


class MLPMixer(nn.Module):
    num_classes: int
    embed_dim: int
    num_layers: int
    tokens_hidden_ch: int
    channels_hidden_ch: int
    patch_shape: tuple[int, int]
    dropout_rate: float = 0.0
    quant: Optional[str] = None  # see MixerBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = PatchEmbedBlock(
            patch_shape=self.patch_shape, embed_dim=self.embed_dim, dtype=self.dtype
        )(inputs)
        for i in range(self.num_layers):
            x = MixerBlock(
                tokens_hidden_ch=self.tokens_hidden_ch,
                channels_hidden_ch=self.channels_hidden_ch,
                dropout_rate=self.dropout_rate,
                quant=self.quant,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x, is_training)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(x)
