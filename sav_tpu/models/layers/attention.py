"""Canonical multi-head attention blocks.

Capability parity with the reference's attention family
(/root/reference/models/layers/attentions/attention.py:10-74,
talking_heads.py:5-14), redesigned around the backend-dispatched functional
cores in :mod:`sav_tpu.ops.attention` so every block can run on the
single-pass fused short-sequence kernel (``backend='fused'``), the
blockwise flash kernel (``backend='pallas'``) or the XLA reference path
(``backend='xla'``) — ``'auto'`` resolves per shape from the measured
attn_tune cache. Talking-heads mixing couples heads, so it gets its own
fused kernel that keeps all heads of a batch element in one grid cell
(:mod:`sav_tpu.ops.talking_heads` — CaiT's self-attention trunk); the XLA
path remains the numerics reference and the long-sequence/dropout fallback.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.ops.attention import dot_product_attention
from sav_tpu.ops.quant import (
    QuantDenseGeneral,
    int8_serve_dot,
    int8_ste_dot,
    quant_rng_data,
)
from sav_tpu.ops.rotary import apply_rotary_pos_emb, fixed_positional_embedding

Dtype = Any


class TalkingHeadsBlock(nn.Module):
    """Learned head-mixing transform (orthogonal init), applied to attention
    logits or probabilities. Reference: talking_heads.py:5-14.

    Calling with ``None`` returns the raw ``[H, H]`` kernel instead of
    applying it — the fused talking-heads kernel consumes the matrix
    directly while keeping the identical ``{pre,post}_softmax/kernel``
    checkpoint layout."""

    num_heads: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: Optional[jax.Array]) -> jax.Array:
        kernel = self.param(
            "kernel", nn.initializers.orthogonal(), (self.num_heads, self.num_heads)
        )
        if x is None:
            return kernel
        return jnp.einsum("hi,...hqk->...iqk", kernel.astype(x.dtype), x)


def talking_heads_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    *,
    num_heads: int,
    scale: float,
    attn_dropout_rate: float,
    is_training: bool,
    dtype: Dtype,
) -> jax.Array:
    """Attention core with pre/post-softmax head mixing (XLA path).

    Must be called from within a parent module's ``@nn.compact`` ``__call__``
    — it instantiates the two ``TalkingHeadsBlock`` submodules (named
    ``pre_softmax`` / ``post_softmax``) on the caller's scope. Shared by
    ``AttentionBlock`` and ``CvTAttentionBlock``.
    """
    logits = jnp.einsum(
        "...qhd,...khd->...hqk",
        query * jnp.asarray(scale, query.dtype),
        key,
        preferred_element_type=jnp.float32,
    )
    logits = TalkingHeadsBlock(num_heads=num_heads, dtype=dtype, name="pre_softmax")(
        logits
    )
    probs = jax.nn.softmax(logits, axis=-1)
    probs = TalkingHeadsBlock(num_heads=num_heads, dtype=dtype, name="post_softmax")(
        probs
    )
    probs = nn.Dropout(rate=attn_dropout_rate)(probs, deterministic=not is_training)
    return jnp.einsum("...hqk,...khd->...qhd", probs.astype(value.dtype), value)


class _FusedQKVProj(nn.Module):
    """Stacked QKV projection computed as three slice-of-param matmuls.

    Parameter tree is byte-identical to
    ``nn.DenseGeneral(features=(3, heads, head_ch), name=...)`` — kernel
    ``[in, 3, H, D]``, bias ``[3, H, D]`` — so checkpoints interchange with
    the declarative layout. The compute differs deliberately: a single
    einsum to ``[B, L, 3, H, D]`` followed by *middle-axis activation
    slices* makes XLA relayout every slice (~1.3 ms/layer at DeiT-S shapes,
    profiled in PERF.md §5); slicing the small *parameter* on its
    unsharded 3-axis instead and running one einsum per projection keeps
    every activation in its natural ``[B, L, H, D]`` layout. The param
    slices are also what Megatron-style tensor parallelism wants: the
    ``to_qkv`` sharding rule places the H axis, which each per-projection
    einsum preserves (no flatten of a sharded dim).
    """

    num_heads: int
    head_ch: int
    use_bias: bool = False
    # int8 quant arm (sav_tpu/ops/quant.py): "int8" routes each slice
    # einsum through the STE dot; "int8_serve" declares the stacked
    # kernel as int8 + a per-slice-channel scale. The per-slice compute
    # structure (and the TP-friendly param slicing) is unchanged.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array):
        in_ch = x.shape[-1]
        h, d = self.num_heads, self.head_ch
        hd = h * d

        def kernel_init(rng, shape, param_dtype):
            # Match DenseGeneral: lecun_normal over the flattened
            # (fan_in, prod(features)) matrix, reshaped to the tree shape.
            flat = nn.initializers.lecun_normal()(rng, (in_ch, 3 * hd), param_dtype)
            return flat.reshape(shape)

        if self.quant == "int8_serve":
            kernel = self.param(
                "kernel", nn.initializers.zeros_init(), (in_ch, 3, h, d), jnp.int8
            )
            scale = self.param(
                "scale", nn.initializers.ones_init(), (3, h, d), jnp.float32
            )
        else:
            kernel = self.param("kernel", kernel_init, (in_ch, 3, h, d), jnp.float32)
            kernel = kernel.astype(self.dtype)
        xc = x.astype(self.dtype)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (3, h, d), jnp.float32
            ).astype(self.dtype)

        if self.quant == "int8_serve":
            def proj(t):
                y = int8_serve_dot(xc, kernel[:, t], scale[t], 1).astype(self.dtype)
                return y + bias[t] if self.use_bias else y
        elif self.quant:
            qkey = quant_rng_data(self)

            def proj(t):
                y = int8_ste_dot(xc, kernel[:, t], jax.random.fold_in(qkey, t), 1)
                return y + bias[t] if self.use_bias else y
        else:
            def proj(t):
                y = jnp.einsum("...i,ihd->...hd", xc, kernel[:, t])
                return y + bias[t] if self.use_bias else y

        return proj(0), proj(1), proj(2)


class AttentionBlock(nn.Module):
    """Multi-head (cross-)attention with optional talking heads.

    Reference: attention.py:10-67. Q/K/V are ``nn.DenseGeneral`` projections
    to ``(num_heads, head_ch)``; logits scale is ``head_ch ** -0.5``; output
    merge is a ``DenseGeneral`` over ``(heads, head_ch)``.
    """

    num_heads: int
    head_ch: Optional[int] = None
    out_ch: Optional[int] = None
    talking_heads: bool = False
    attn_dropout_rate: float = 0.0
    out_dropout_rate: float = 0.0
    use_bias: bool = False
    # Stacked QKV parameter for self-attention (one [in, 3, H, D] kernel —
    # see _FusedQKVProj for how it is computed). Changes the param tree
    # (to_qkv instead of to_q/to_k/to_v) — set False for the reference's
    # three-projection layout if a checkpoint/repro needs it, and for any
    # cross-attention use (Q and K/V come from different inputs). The
    # checkpoint layout depends on this flag alone, never on call arguments.
    fused_qkv: bool = True
    # RoPE on Q/K after projection (the working rebuild of the reference's
    # broken, never-wired rotary path — SURVEY.md §2.9 #12).
    use_rotary: bool = False
    # Attention-core backend: None/'auto' = measured three-way dispatch
    # (sav_tpu.ops.attention.resolve_attention_backend — fused-short /
    # xla / flash by shape band + the attn_tune cache), or force 'xla' |
    # 'fused' | 'pallas'.
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # Sequence parallelism: route the attention core through
    # sav_tpu.parallel.seq_parallel over ``seq_mesh``'s 'seq' axis
    # ('ring' | 'ulysses'; None = single-device core). Config-reachable via
    # TrainConfig.sequence_parallel / train.py --sp N. Self-attention only,
    # deterministic only (no attention dropout), exact numerics incl. the
    # CLS-odd sequence lengths of the model zoo (pad-and-mask).
    seq_parallel: Optional[str] = None
    seq_mesh: Optional[Any] = None
    # int8 quantized projection dots ("int8" QAT / "int8_serve" — see
    # sav_tpu/ops/quant.py): Q/K/V and the output merge route through
    # the quantized dot; the attention core (QK/AV) stays in ``dtype``
    # by design (PERF §5: those dots are not matmul-roofline-bound).
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, inputs_q: jax.Array, inputs_kv: jax.Array, is_training: bool
    ) -> jax.Array:
        in_ch = inputs_q.shape[-1]
        head_ch = self.head_ch or in_ch // self.num_heads
        out_ch = self.out_ch or in_ch
        scale = head_ch**-0.5

        dense = functools.partial(
            QuantDenseGeneral if self.quant else nn.DenseGeneral,
            axis=-1,
            use_bias=self.use_bias,
            dtype=self.dtype,
            **({"mode": self.quant} if self.quant else {}),
        )
        if self.fused_qkv:
            # Self-attention: one stacked [in, 3, H, D] parameter, computed
            # as per-projection einsums over its slices (_FusedQKVProj —
            # avoids the activation-slice relayouts, keeps TP sharding).
            # Same init distribution per column as three separate
            # DenseGenerals (fan_in is identical).
            if inputs_q is not inputs_kv:
                raise ValueError(
                    "fused_qkv=True projects Q, K and V from one input and is "
                    "only valid for self-attention; pass fused_qkv=False for "
                    "cross-attention (distinct inputs_q / inputs_kv)."
                )
            query, key, value = _FusedQKVProj(
                num_heads=self.num_heads,
                head_ch=head_ch,
                use_bias=self.use_bias,
                quant=self.quant,
                dtype=self.dtype,
                name="to_qkv",
            )(inputs_q)
        else:
            proj = functools.partial(
                dense, features=(self.num_heads, head_ch)
            )
            query = proj(name="to_q")(inputs_q)
            key = proj(name="to_k")(inputs_kv)
            value = proj(name="to_v")(inputs_kv)

        if self.use_rotary:
            sincos = fixed_positional_embedding(query.shape[1], head_ch)
            query = apply_rotary_pos_emb(query, sincos)
            if key.shape[1] != query.shape[1]:
                sincos = fixed_positional_embedding(key.shape[1], head_ch)
            key = apply_rotary_pos_emb(key, sincos)

        has_attn_dropout = self.attn_dropout_rate > 0.0 and is_training
        if self.seq_parallel:
            if self.talking_heads and self.seq_parallel != "ring":
                raise ValueError(
                    "talking-heads sequence parallelism is ring-only "
                    "(Ulysses shards heads across devices; the head mix "
                    "would cross them) — use seq_parallel='ring'"
                )
            if has_attn_dropout:
                raise ValueError(
                    "sequence-parallel attention is deterministic-only; "
                    "set attn_dropout_rate=0 (the reference recipes use "
                    "stochastic depth + output dropout, not attention "
                    "dropout)"
                )
            if inputs_q is not inputs_kv:
                raise ValueError(
                    "sequence parallelism supports self-attention blocks "
                    "only (q and kv shards must cover the same sequence)"
                )
            if self.seq_mesh is None:
                raise ValueError(
                    "seq_parallel set but no seq_mesh given; pass the "
                    "training Mesh (with a 'seq' axis) to the block"
                )
            if self.backend in ("pallas", "fused"):
                raise ValueError(
                    "seq_parallel runs the dense XLA core per shard; "
                    f"backend={self.backend!r} is not routed under SP (the "
                    "bare ring_attention/ulysses_attention ops expose flash "
                    "mode for divisible lengths) — unset one of the two"
                )
            # logits_dtype does not apply here: online-softmax statistics
            # (running max / denominator) are f32 by construction — see
            # TrainConfig.sequence_parallel.
            from sav_tpu.parallel.seq_parallel import (
                sequence_parallel_attention,
            )

            th = None
            if self.talking_heads:
                # Head mixing rides the ring via head-pair accumulators
                # (parallel.ring_attention._ring_talking_heads_shard_fn);
                # same {pre,post}_softmax/kernel checkpoint layout as the
                # dense and fused paths.
                th = (
                    TalkingHeadsBlock(
                        num_heads=self.num_heads, dtype=self.dtype,
                        name="pre_softmax",
                    )(None),
                    TalkingHeadsBlock(
                        num_heads=self.num_heads, dtype=self.dtype,
                        name="post_softmax",
                    )(None),
                )
            out = sequence_parallel_attention(
                query,
                key,
                value,
                mesh=self.seq_mesh,
                method=self.seq_parallel,
                scale=scale,
                talking_heads=th,
            )
        elif self.talking_heads:
            from sav_tpu.ops.talking_heads import fused_eligible

            backend = self.backend or "auto"
            fused_ok = (
                not has_attn_dropout
                and query.ndim == 4
                and fused_eligible(self.num_heads, key.shape[1], head_ch)
            )
            if backend in ("pallas", "fused"):
                # Head mixing couples heads, so both kernel backends mean
                # the same thing here: the dedicated talking-heads kernel
                # (itself single-KV-block fused).
                if has_attn_dropout:
                    raise ValueError(
                        "pallas talking-heads attention is deterministic-only "
                        "(attention dropout runs on the XLA path)"
                    )
                use_fused = True  # kv-length guard raises inside the kernel
            else:
                # Measured crossover on v5e (tools/th_micro.py, CaiT-XXS
                # trunk shape B=256 L=197 H=4 D=48): fused wins fwd+bwd
                # (5.67 vs 7.13 ms) but loses forward-only (4.40 vs
                # 3.07 ms) — so 'auto' rides the kernel for training and
                # dense XLA for inference.
                use_fused = (
                    backend == "auto"
                    and fused_ok
                    and is_training
                    and jax.default_backend() == "tpu"
                )
            if use_fused:
                from sav_tpu.ops.talking_heads import (
                    flash_talking_heads_attention,
                )

                w_pre = TalkingHeadsBlock(
                    num_heads=self.num_heads, dtype=self.dtype, name="pre_softmax"
                )(None)
                w_post = TalkingHeadsBlock(
                    num_heads=self.num_heads, dtype=self.dtype, name="post_softmax"
                )(None)
                out = flash_talking_heads_attention(
                    query, key, value, w_pre, w_post, scale=scale
                )
            else:
                out = talking_heads_attention(
                    query,
                    key,
                    value,
                    num_heads=self.num_heads,
                    scale=scale,
                    attn_dropout_rate=self.attn_dropout_rate,
                    is_training=is_training,
                    dtype=self.dtype,
                )
        else:
            dropout_rng = self.make_rng("dropout") if has_attn_dropout else None
            # Resolved HERE (None = this block's compute dtype — the
            # reference's semantics: its logits einsum runs in the model
            # dtype, attention.py:41-48) so no jitted path ever reads the
            # deprecated process-wide default in sav_tpu.ops.attention.
            out = dot_product_attention(
                query,
                key,
                value,
                scale=scale,
                dropout_rate=self.attn_dropout_rate,
                dropout_rng=dropout_rng,
                deterministic=not is_training,
                backend=self.backend,
                logits_dtype=self.logits_dtype or self.dtype,
            )

        out = dense(
            features=out_ch,
            axis=(-2, -1),
            name="to_out",
        )(out)
        out = nn.Dropout(rate=self.out_dropout_rate)(out, deterministic=not is_training)
        return out


class SelfAttentionBlock(AttentionBlock):
    """Self-attention specialization (attention.py:70-74)."""

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:  # type: ignore[override]
        return super().__call__(inputs, inputs, is_training)
