"""Depthwise 2-D convolution as unrolled shifted multiply-adds.

TPU-first formulation: a depthwise convolution contracts nothing across
channels, so it cannot use the MXU — it is VPU elementwise work no matter
how it is written. Expressing it as k² pad→strided-slice→FMA taps gives
XLA trivially fusible elementwise ops instead of a grouped-convolution op,
which this environment's TPU compiler lowers pathologically slowly
(a single `nn.Conv(feature_group_count=C)` 3×3 block took >10 min to
compile on-chip while the whole rest of the model zoo compiles in seconds
— PERF.md §8). FLOPs and numerics are identical (k² products per output,
f32 accumulation, SAME zero-padding).

Parameter layout (`kernel`: ``[kh, kw, 1, C]``, module-scoped name
unchanged) matches ``nn.Conv(features=C, feature_group_count=C,
use_bias=False)`` exactly, so existing checkpoints interchange and the
initialization distribution (lecun_normal fans from the same shape) is
identical.

Consumers: CvT conv projections (cvt_attention.py, reference
cvt_attention.py:12-120) and CeiT LeFF (feedforward.py, reference
leff.py semantics).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


def _same_pad(size: int, k: int, s: int) -> tuple[int, tuple[int, int]]:
    """TF/XLA 'SAME' geometry: out = ceil(size/s), pad split low/high."""
    out = -(-size // s)
    total = max((out - 1) * s + k - size, 0)
    return out, (total // 2, total - total // 2)


class DepthwiseConv2D(nn.Module):
    """``[B, H, W, C] -> [B, H', W', C]`` depthwise conv, SAME padding."""

    features: int
    kernel_size: tuple[int, int] = (3, 3)
    stride: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (kh, kw, 1, self.features),
            jnp.float32,
        )
        s = self.stride
        out_h, (ph0, ph1) = _same_pad(x.shape[1], kh, s)
        out_w, (pw0, pw1) = _same_pad(x.shape[2], kw, s)
        xp = jnp.pad(
            x.astype(self.dtype), ((0, 0), (ph0, ph1), (pw0, pw1), (0, 0))
        )
        acc = None
        for di in range(kh):
            for dj in range(kw):
                tap = jax.lax.slice(
                    xp,
                    (0, di, dj, 0),
                    (
                        xp.shape[0],
                        di + (out_h - 1) * s + 1,
                        dj + (out_w - 1) * s + 1,
                        xp.shape[3],
                    ),
                    (1, s, s, 1),
                )
                term = tap.astype(jnp.float32) * kernel[di, dj, 0]
                acc = term if acc is None else acc + term
        return acc.astype(self.dtype)
