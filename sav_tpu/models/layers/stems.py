"""Patch/conv tokenization stems.

Reference: PatchEmbedBlock (/root/reference/models/layers/stems/patch_embed.py:8-26),
Image2TokenBlock (/root/reference/models/layers/stems/image_to_token.py:8-48).

PatchEmbedBlock here uses a strided conv instead of the reference's
rearrange+Dense — mathematically identical, but a conv maps straight onto the
MXU with good layouts and lets XLA pick the im2col strategy.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class PatchEmbedBlock(nn.Module):
    """Non-overlapping patch embedding: ``[B,H,W,C] → [B, (H/ph)(W/pw), D]``."""

    patch_shape: tuple[int, int]
    embed_dim: int
    use_bias: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        ph, pw = self.patch_shape
        b, h, w, _ = inputs.shape
        if h % ph or w % pw:
            raise ValueError(f"image {h}x{w} not divisible by patch {self.patch_shape}")
        x = nn.Conv(
            features=self.embed_dim,
            kernel_size=(ph, pw),
            strides=(ph, pw),
            padding="VALID",
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="proj",
        )(inputs)
        return x.reshape(b, (h // ph) * (w // pw), self.embed_dim)


class Image2TokenBlock(nn.Module):
    """CeiT conv stem: 7×7/s2 conv + BN + 3×3/s2 max-pool, then patchify+embed."""

    patch_shape: tuple[int, int]
    embed_dim: int
    stem_ch: int = 32
    conv_kernel: tuple[int, int] = (7, 7)
    conv_stride: tuple[int, int] = (2, 2)
    pool_window: tuple[int, int] = (3, 3)
    pool_stride: tuple[int, int] = (2, 2)
    use_bias: bool = True
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = nn.Conv(
            features=self.stem_ch,
            kernel_size=self.conv_kernel,
            strides=self.conv_stride,
            padding="SAME",
            use_bias=False,
            dtype=self.dtype,
            name="stem_conv",
        )(inputs)
        x = nn.BatchNorm(
            use_running_average=not is_training, momentum=0.9, dtype=self.dtype, name="stem_bn"
        )(x)
        x = nn.max_pool(x, self.pool_window, strides=self.pool_stride, padding="SAME")
        return PatchEmbedBlock(
            patch_shape=self.patch_shape,
            embed_dim=self.embed_dim,
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="patch_embed",
        )(x)
