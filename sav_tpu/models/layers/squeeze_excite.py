"""Squeeze-and-excitation gate.

Reference: /root/reference/models/layers/squeeze_excite.py:13-38, with the
pooled-array-call crash fixed (SURVEY.md §2.9 #4) so BoTNet's bottleneck
blocks can actually use it.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class SqueezeExciteBlock(nn.Module):
    se_ratio: float = 0.25
    activation_fn: Callable = nn.swish
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        ch = inputs.shape[-1]
        hidden = max(1, int(ch * self.se_ratio))
        gate = jnp.mean(inputs, axis=(1, 2))  # [B, C] global average pool
        gate = nn.Dense(hidden, dtype=self.dtype, name="reduce")(gate)
        gate = self.activation_fn(gate)
        gate = nn.Dense(ch, dtype=self.dtype, name="expand")(gate)
        gate = nn.sigmoid(gate)
        return inputs * gate[:, None, None, :]
