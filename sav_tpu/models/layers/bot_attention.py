"""BoTNet 2-D relative-position multi-head self-attention.

Fixed, working rebuild of the reference's ``BoTMHSA`` + ``RelativeLogits``
(/root/reference/models/botnet.py:70-199 — the original crashes on
``self.head_dim``/``self.config`` and contracts the wrong axes in its output
einsum; SURVEY.md §2.9 #1-3). Design: the learned relative tables produce an
additive logits bias via :func:`sav_tpu.ops.relative.relative_logits_2d`, and
the attention core is the shared ``dot_product_attention`` — so on the Pallas
path the *forward* pass streams the relative logits through the fused flash
kernel without materializing the ``[B, heads, HW, HW]`` softmax in HBM (the
backward recomputes attention; see :mod:`sav_tpu.ops.flash_attention`).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.ops.attention import dot_product_attention
from sav_tpu.ops.flash_attention import flash_botnet_attention
from sav_tpu.ops.quant import QuantDenseGeneral
from sav_tpu.ops.relative import relative_logits_2d

Dtype = Any


class BoTMHSA(nn.Module):
    """All-2-D self-attention on a ``[B, H, W, C]`` feature map.

    Returns ``[B, H, W, num_heads * head_ch]`` (no output projection — the
    surrounding bottleneck's 1×1 convs do channel mixing, botnet.py:202-252).
    """

    num_heads: int
    head_ch: Optional[int] = None
    pos_emb_init_stddev: Optional[float] = None
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # int8 quantized Q/K/V projections (sav_tpu/ops/quant.py); the
    # relative-logits tables and the attention core stay in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        b, height, width, ch = inputs.shape
        head_ch = self.head_ch or ch // self.num_heads
        inner = self.num_heads * head_ch
        scale = head_ch**-0.5

        proj_cls = (
            lambda **kw: QuantDenseGeneral(mode=self.quant, **kw)
        ) if self.quant else nn.DenseGeneral
        dense = lambda name: proj_cls(
            features=(self.num_heads, head_ch),
            axis=-1,
            use_bias=False,
            dtype=self.dtype,
            name=name,
        )
        tokens = inputs.reshape(b, height * width, ch)
        query = dense("to_q")(tokens)  # [B, HW, h, d]
        key = dense("to_k")(tokens)
        value = dense("to_v")(tokens)

        stddev = self.pos_emb_init_stddev or head_ch**-0.5
        rel_k_h = self.param(
            "rel_emb_h", nn.initializers.normal(stddev=stddev), (2 * height - 1, head_ch)
        )
        rel_k_w = self.param(
            "rel_emb_w", nn.initializers.normal(stddev=stddev), (2 * width - 1, head_ch)
        )

        backend = self.backend or "auto"
        if backend not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown attention backend: {backend!r}")
        # Fused kernel wins once the [B, heads, L, L] bias is big enough to
        # be HBM-bound (measured crossover ~L=256 on v5e); below that XLA's
        # fusion of the materialized-bias path is at parity or better.
        use_fused = backend == "pallas" or (
            backend == "auto"
            and jax.default_backend() == "tpu"
            and height * width >= 256
        )
        if use_fused:
            # Fused forward: compact per-axis relative logits expand inside
            # the flash kernel, so the forward never materializes the
            # [B, heads, L, L] bias in HBM (SURVEY.md §7 'hard parts').
            out = flash_botnet_attention(
                query, key, value, rel_k_h, rel_k_w, height, width, scale=scale
            )
        else:
            # Relative logits use the same scaled query as the content logits.
            q_grid = jnp.transpose(
                query.reshape(b, height, width, self.num_heads, head_ch),
                (0, 3, 1, 2, 4),
            )
            q_grid = q_grid * jnp.asarray(scale, q_grid.dtype)
            bias = relative_logits_2d(
                q_grid, rel_k_h.astype(q_grid.dtype), rel_k_w.astype(q_grid.dtype)
            )
            bias = bias.reshape(b, self.num_heads, height * width, height * width)
            out = dot_product_attention(
                query, key, value, bias=bias, scale=scale, backend="xla",
                # None = this block's compute dtype; resolved here so no
                # jitted path reads the deprecated process-wide default.
                logits_dtype=self.logits_dtype or self.dtype,
            )
        return out.reshape(b, height, width, inner)
