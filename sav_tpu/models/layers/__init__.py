"""Layer zoo re-exports (parity with /root/reference/models/layers/__init__.py:1-7)."""

from sav_tpu.models.layers.attention import (
    AttentionBlock,
    SelfAttentionBlock,
    TalkingHeadsBlock,
)
from sav_tpu.models.layers.bot_attention import BoTMHSA
from sav_tpu.models.layers.class_attention import (
    ClassSelfAttentionBlock,
    LCSelfAttentionBlock,
)
from sav_tpu.models.layers.cvt_attention import (
    ConvProjectionBlock,
    CvTAttentionBlock,
    CvTSelfAttentionBlock,
)
from sav_tpu.models.layers.feedforward import FFBlock, LeFFBlock
from sav_tpu.models.layers.moe import MoEFFBlock
from sav_tpu.models.layers.normalization import LayerScaleBlock
from sav_tpu.models.layers.position_embed import (
    AddAbsPosEmbed,
    FixedPositionalEmbedding,
    RotaryPositionalEmbedding,
)
from sav_tpu.models.layers.regularization import StochasticDepthBlock
from sav_tpu.models.layers.squeeze_excite import SqueezeExciteBlock
from sav_tpu.models.layers.stems import Image2TokenBlock, PatchEmbedBlock

__all__ = [
    "AttentionBlock",
    "SelfAttentionBlock",
    "TalkingHeadsBlock",
    "BoTMHSA",
    "ClassSelfAttentionBlock",
    "LCSelfAttentionBlock",
    "ConvProjectionBlock",
    "CvTAttentionBlock",
    "CvTSelfAttentionBlock",
    "FFBlock",
    "LeFFBlock",
    "MoEFFBlock",
    "LayerScaleBlock",
    "AddAbsPosEmbed",
    "FixedPositionalEmbedding",
    "RotaryPositionalEmbedding",
    "StochasticDepthBlock",
    "SqueezeExciteBlock",
    "Image2TokenBlock",
    "PatchEmbedBlock",
]
