"""LayerScale (CaiT). Reference: /root/reference/models/layers/normalizations/layerscale.py:5-23."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class LayerScaleBlock(nn.Module):
    """Per-channel learned scale on a residual branch, initialized to ``eps``."""

    eps: float = 1e-4
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        dim = inputs.shape[-1]
        scale = self.param("scale", nn.initializers.constant(self.eps), (dim,))
        return inputs * scale.astype(inputs.dtype)
