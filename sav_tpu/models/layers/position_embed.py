"""Position embedding layers.

Reference: /root/reference/models/layers/position_embed.py:8-57. The fixed
sinusoidal + rotary paths there were broken and never wired in (SURVEY.md
§2.9 #12); here they are working modules over :mod:`sav_tpu.ops.rotary`.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.ops.rotary import apply_rotary_pos_emb, fixed_positional_embedding

Dtype = Any


class AddAbsPosEmbed(nn.Module):
    """Learned absolute position table ``(1, L, D)``, normal(0.02) init."""

    init_stddev: float = 0.02
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        _, length, dim = inputs.shape
        table = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=self.init_stddev),
            (1, length, dim),
        )
        return inputs + table.astype(inputs.dtype)


class FixedPositionalEmbedding(nn.Module):
    """Adds a (non-learned) sinusoidal position embedding."""

    dtype: Dtype = jnp.float32

    def __call__(self, inputs: jax.Array) -> jax.Array:
        _, length, dim = inputs.shape
        sin, cos = fixed_positional_embedding(length, dim, dtype=jnp.float32)
        # Interleave: even channels get sin, odd get cos.
        table = jnp.where(jnp.arange(dim) % 2 == 0, sin, cos)
        return inputs + table[None].astype(inputs.dtype)


class RotaryPositionalEmbedding(nn.Module):
    """Applies RoPE to a token sequence ``[B, L, D]`` or per-head ``[B, L, H, D]``."""

    dtype: Dtype = jnp.float32

    def __call__(self, inputs: jax.Array) -> jax.Array:
        length, dim = inputs.shape[1], inputs.shape[-1]
        sincos = fixed_positional_embedding(length, dim, dtype=jnp.float32)
        return apply_rotary_pos_emb(inputs, sincos)
