"""Mixture-of-Experts feed-forward with expert parallelism.

Capability headroom beyond the reference (which has no MoE/EP —
SURVEY.md §2.7): a token-choice top-k routed FF block designed for the TPU
partitioner. Dispatch and combine are dense one-hot einsums over static
``[groups, tokens/group, experts, capacity]`` tensors — no scatter/gather,
no dynamic shapes, so XLA tiles everything onto the MXU and, with the expert weights
sharded ``P('expert', ...)`` (``sav_tpu.parallel.sharding.DEFAULT_EP_RULES``),
inserts the dispatch/return all-to-alls over ICI on its own.

Router math runs in fp32 regardless of compute dtype (routing decisions are
precision-sensitive); a Switch-Transformer-style load-balancing loss is
sown into the ``'losses'`` collection as ``moe_aux_loss`` for the trainer
to pick up.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

Dtype = Any


class MoEFFBlock(nn.Module):
    """Token-choice top-k mixture-of-experts transformer MLP.

    Drop-in replacement for :class:`FFBlock` on ``[B, L, D]`` token inputs.
    Each batch row is a routing group (GShard-style): tokens pick their
    top-``top_k`` experts, and each expert accepts at most
    ``capacity_factor · k · L / E`` tokens *per group* — overflow tokens
    fall through the residual unmodified (standard Switch/GShard behavior),
    and the dispatch tensors stay linear in total token count.
    """

    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    expand_ratio: Optional[float] = 4.0
    hidden_ch: Optional[int] = None
    dropout_rate: float = 0.0
    # Router z-loss (ST-MoE): mean(logsumexp(router logits)²), sown
    # alongside the balance loss. Keeps router logits from drifting to
    # magnitudes where the fp32 softmax saturates and routing gradients
    # vanish. Every sown loss is scaled by TrainConfig.aux_loss_weight
    # (0.01 default) in the trainer, so the default here (0.1) makes the
    # EFFECTIVE coefficient 0.1 x 0.01 = 1e-3 — the ST-MoE paper value.
    # 0 disables (and keeps the sown-losses set of older configs).
    router_z_loss_weight: float = 0.1
    activation_fn: Callable = nn.gelu
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        g, s, d = inputs.shape  # groups (batch rows) × tokens/group × dim
        hidden = self.hidden_ch or int(d * self.expand_ratio)
        n_exp, k = self.num_experts, self.top_k
        if not 1 <= k <= n_exp:
            raise ValueError(f"top_k={k} must be in [1, num_experts={n_exp}]")
        x = inputs

        # --- Router (fp32) -------------------------------------------------
        router = self.param(
            "router", nn.initializers.normal(stddev=0.02), (d, n_exp)
        )
        logits = jnp.einsum(
            "gsd,de->gse",
            x.astype(jnp.float32),
            router.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [G, S, k] each
        gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

        # Load-balancing aux loss (Switch eq. 4), over all tokens globally:
        # E · Σ_e f_e · P_e where f_e = fraction of tokens whose top-1 choice
        # is e, P_e = mean router probability for e. Minimized (=1) by a
        # uniform router.
        # Sown-loss convention: every 'losses' entry is a ready-to-sum
        # penalty at its RELATIVE scale — balance at coefficient 1, z-loss
        # pre-multiplied by router_z_loss_weight — and the trainer's single
        # aux_loss_weight converts relative units to loss units for the
        # whole collection (trainer.py loss_fn).
        top1_frac = jnp.mean(jax.nn.one_hot(expert_idx[..., 0], n_exp), axis=(0, 1))
        aux_loss = n_exp * jnp.sum(top1_frac * jnp.mean(probs, axis=(0, 1)))
        self.sow("losses", "moe_aux_loss", aux_loss)
        if self.router_z_loss_weight:
            z = jax.nn.logsumexp(logits, axis=-1)  # [G, S]
            self.sow(
                "losses",
                "moe_router_z_loss",
                self.router_z_loss_weight * jnp.mean(z * z),
            )

        # --- Capacity-based dispatch/combine, GShard-style grouped --------
        # Capacity is per *group* (each batch row routes independently), so
        # the dispatch tensors are [G, S, E, C] with C ∝ S/E — total memory
        # and FLOPs stay linear in token count instead of quadratic.
        capacity = max(k, math.ceil(self.capacity_factor * k * s / n_exp))
        counts = jnp.zeros((g, n_exp), jnp.int32)
        dispatch = jnp.zeros((g, s, n_exp, capacity), jnp.float32)
        combine = jnp.zeros((g, s, n_exp, capacity), jnp.float32)
        for slot in range(k):  # k is static and tiny — unrolled
            onehot = jax.nn.one_hot(expert_idx[..., slot], n_exp, dtype=jnp.int32)
            # Position of each token in its expert's buffer: running
            # per-(group, expert) count from earlier slots + cumulative count
            # within this slot. one_hot maps positions ≥ capacity to the
            # all-zero row, which is exactly the overflow-drop semantics.
            pos = jnp.cumsum(onehot, axis=1) - 1 + counts[:, None, :]
            pos_tok = jnp.sum(pos * onehot, axis=-1)  # [G, S]
            slot_mask = (
                onehot.astype(jnp.float32)[..., None]
                * jax.nn.one_hot(pos_tok, capacity)[..., None, :]
            )
            dispatch = dispatch + slot_mask
            combine = combine + slot_mask * gate_vals[..., slot][..., None, None]
            counts = counts + jnp.sum(onehot, axis=1)

        # --- Expert computation (batched over the expert dim) -------------
        fan_init = nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal")
        w1 = self.param("experts_w1", fan_init, (n_exp, d, hidden))
        b1 = self.param("experts_b1", nn.initializers.zeros, (n_exp, hidden))
        w2 = self.param("experts_w2", fan_init, (n_exp, hidden, d))
        b2 = self.param("experts_b2", nn.initializers.zeros, (n_exp, d))

        cdt = self.dtype
        xe = jnp.einsum("gsec,gsd->egcd", dispatch.astype(cdt), x.astype(cdt))
        h = self.activation_fn(
            jnp.einsum("egcd,edh->egch", xe, w1.astype(cdt))
            + b1.astype(cdt)[:, None, None, :]
        )
        h = nn.Dropout(rate=self.dropout_rate)(h, deterministic=not is_training)
        ye = jnp.einsum("egch,ehd->egcd", h, w2.astype(cdt)) + b2.astype(cdt)[
            :, None, None, :
        ]
        y = jnp.einsum("gsec,egcd->gsd", combine.astype(cdt), ye)
        y = nn.Dropout(rate=self.dropout_rate)(y, deterministic=not is_training)
        return y.astype(inputs.dtype)
