"""Feed-forward blocks: transformer MLP and CeiT's locally-enhanced FF.

Reference: FFBlock (/root/reference/models/layers/feedforwards/ff.py:8-34),
LeFFBlock (/root/reference/models/layers/feedforwards/leff.py:9-63).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers.depthwise import DepthwiseConv2D
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class FFBlock(nn.Module):
    """Dense(expand) → act → dropout → Dense(in_ch) → dropout."""

    expand_ratio: Optional[float] = 4.0
    hidden_ch: Optional[int] = None
    dropout_rate: float = 0.0
    activation_fn: Callable = nn.gelu
    use_bias: bool = True
    # int8 quantized dots ("int8" QAT / "int8_serve") — both FFN
    # matmuls route through sav_tpu/ops/quant.py; None = plain nn.Dense.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        in_ch = inputs.shape[-1]
        hidden = self.hidden_ch or int(in_ch * self.expand_ratio)
        dense = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        x = dense(hidden, use_bias=self.use_bias, dtype=self.dtype, name="fc1")(inputs)
        x = self.activation_fn(x)
        x = nn.Dropout(rate=self.dropout_rate)(x, deterministic=not is_training)
        x = dense(in_ch, use_bias=self.use_bias, dtype=self.dtype, name="fc2")(x)
        x = nn.Dropout(rate=self.dropout_rate)(x, deterministic=not is_training)
        return x


class LeFFBlock(nn.Module):
    """CeiT locally-enhanced feed-forward.

    Splits the CLS token off, expands patch tokens, re-grids them to √L×√L,
    applies a depthwise conv (default 5×5), projects back, and re-concats the
    CLS token. BatchNorm after each stage as in the reference (leff.py:39-59).
    """

    expand_ratio: Optional[float] = 4.0
    hidden_ch: Optional[int] = None
    kernel_size: tuple[int, int] = (5, 5)
    activation_fn: Callable = nn.gelu
    # int8 quantized expand/project dots; the depthwise conv and the
    # BatchNorms stay in ``dtype`` (conv is not a projection/FFN dot).
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        in_ch = inputs.shape[-1]
        hidden = self.hidden_ch or int(in_ch * self.expand_ratio)
        dense = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        cls_tok, tokens = inputs[:, :1], inputs[:, 1:]
        b, l, _ = tokens.shape
        side = int(round(l**0.5))
        if side * side != l:
            raise ValueError(f"LeFF requires a square token grid, got {l} tokens")

        norm = lambda name: nn.BatchNorm(
            use_running_average=not is_training, momentum=0.9, dtype=self.dtype, name=name
        )
        x = dense(hidden, dtype=self.dtype, name="expand")(tokens)
        x = self.activation_fn(norm("bn1")(x))
        x = x.reshape(b, side, side, hidden)
        # Shifted-FMA depthwise (param-compatible with the nn.Conv grouped
        # form; see layers/depthwise.py for why not feature_group_count).
        x = DepthwiseConv2D(
            features=hidden,
            kernel_size=self.kernel_size,
            dtype=self.dtype,
            name="dwconv",
        )(x)
        x = self.activation_fn(norm("bn2")(x))
        x = x.reshape(b, l, hidden)
        x = dense(in_ch, dtype=self.dtype, name="project")(x)
        x = self.activation_fn(norm("bn3")(x))
        return jnp.concatenate([cls_tok, x], axis=1)
