"""CvT conv-projection attention.

Reference: /root/reference/models/layers/attentions/cvt_attention.py:12-120.
Q/K/V projections are depthwise 3×3 conv + BatchNorm followed by a pointwise
projection to ``(heads, head_ch)``, with per-projection strides (default
``(1, 2, 2)`` → K/V grids downsampled 2×). Unlike the reference (which takes
a 4-D feature map and cannot carry a CLS token correctly — SURVEY.md §2.9
#19), this block takes a token sequence plus its grid shape and handles an
optional leading CLS token the paper's way: the CLS token skips the depthwise
conv and joins the sequence for the pointwise head projection.

The attention core itself is the shared backend-dispatched
``dot_product_attention`` → the fused Pallas kernel applies to CvT as well;
only the conv projections stay in XLA (convs already map optimally to the
MXU).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers.attention import talking_heads_attention
from sav_tpu.models.layers.depthwise import DepthwiseConv2D
from sav_tpu.ops.attention import dot_product_attention
from sav_tpu.ops.quant import QuantDenseGeneral

Dtype = Any


class ConvProjectionBlock(nn.Module):
    """Depthwise 3×3 conv + BN on the token grid, then pointwise head projection.

    Returns head-split tokens ``[B, L', heads, head_ch]``.
    """

    num_heads: int
    head_ch: int
    kernel_size: tuple[int, int] = (3, 3)
    stride: int = 1
    use_bias: bool = False
    with_cls: bool = False
    # int8 quant arm: the pointwise head projection routes through
    # sav_tpu/ops/quant.py; the depthwise conv + BN stay in ``dtype``
    # (convs already map optimally to the MXU — module docstring).
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, tokens: jax.Array, grid_shape: tuple[int, int], is_training: bool
    ) -> jax.Array:
        b = tokens.shape[0]
        h, w = grid_shape
        ch = tokens.shape[-1]
        if self.with_cls:
            cls_tok, grid_tokens = tokens[:, :1], tokens[:, 1:]
        else:
            cls_tok, grid_tokens = None, tokens
        x = grid_tokens.reshape(b, h, w, ch)
        # Shifted-FMA depthwise (param-compatible with the nn.Conv grouped
        # form; see layers/depthwise.py for why not feature_group_count).
        x = DepthwiseConv2D(
            features=ch,
            kernel_size=self.kernel_size,
            stride=self.stride,
            dtype=self.dtype,
            name="depthwise",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not is_training, momentum=0.9, dtype=self.dtype, name="bn"
        )(x)
        x = x.reshape(b, -1, ch)
        if cls_tok is not None:
            x = jnp.concatenate([cls_tok, x], axis=1)
        pointwise = (
            functools.partial(QuantDenseGeneral, mode=self.quant)
            if self.quant else nn.DenseGeneral
        )
        return pointwise(
            features=(self.num_heads, self.head_ch),
            axis=-1,
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="pointwise",
        )(x)


class CvTAttentionBlock(nn.Module):
    """Attention over a token grid with conv Q/K/V projections."""

    num_heads: int
    head_ch: Optional[int] = None
    out_ch: Optional[int] = None
    strides: tuple[int, int, int] = (1, 2, 2)  # (q, k, v)
    talking_heads: bool = False
    attn_dropout_rate: float = 0.0
    out_dropout_rate: float = 0.0
    use_bias: bool = False
    with_cls: bool = False
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # int8 quantized projection dots (pointwise Q/K/V + output merge);
    # the attention core and the depthwise convs stay in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, inputs: jax.Array, grid_shape: tuple[int, int], is_training: bool
    ) -> jax.Array:
        in_ch = inputs.shape[-1]
        head_ch = self.head_ch or in_ch // self.num_heads
        out_ch = self.out_ch or in_ch
        scale = head_ch**-0.5

        proj = functools.partial(
            ConvProjectionBlock,
            num_heads=self.num_heads,
            head_ch=head_ch,
            use_bias=self.use_bias,
            with_cls=self.with_cls,
            quant=self.quant,
            dtype=self.dtype,
        )
        sq, sk, sv = self.strides
        query = proj(stride=sq, name="to_q")(inputs, grid_shape, is_training)
        key = proj(stride=sk, name="to_k")(inputs, grid_shape, is_training)
        value = proj(stride=sv, name="to_v")(inputs, grid_shape, is_training)

        has_attn_dropout = self.attn_dropout_rate > 0.0 and is_training
        if self.talking_heads:
            out = talking_heads_attention(
                query,
                key,
                value,
                num_heads=self.num_heads,
                scale=scale,
                attn_dropout_rate=self.attn_dropout_rate,
                is_training=is_training,
                dtype=self.dtype,
            )
        else:
            dropout_rng = self.make_rng("dropout") if has_attn_dropout else None
            out = dot_product_attention(
                query,
                key,
                value,
                scale=scale,
                dropout_rate=self.attn_dropout_rate,
                dropout_rng=dropout_rng,
                deterministic=not is_training,
                backend=self.backend,
                # None = this block's compute dtype; resolved here so no
                # jitted path reads the deprecated process-wide default.
                logits_dtype=self.logits_dtype or self.dtype,
            )

        out_dense = (
            functools.partial(QuantDenseGeneral, mode=self.quant)
            if self.quant else nn.DenseGeneral
        )
        out = out_dense(
            features=out_ch,
            axis=(-2, -1),
            use_bias=self.use_bias,
            dtype=self.dtype,
            name="to_out",
        )(out)
        out = nn.Dropout(rate=self.out_dropout_rate)(out, deterministic=not is_training)
        return out


class CvTSelfAttentionBlock(CvTAttentionBlock):
    """Alias kept for reference API parity (cvt_attention.py:116-120); the
    block is already self-attention over its token grid."""
