"""Single-query class-attention blocks (CaiT / CeiT).

Reference: CaiT ``ClassSelfAttentionBlock`` (/root/reference/models/cait.py:10-15,
query = token 0) and CeiT ``LCSelfAttentionBlock`` (/root/reference/models/ceit.py:11-16,
query = last token). Both are O(L): one query row attending over the full
sequence. On the Pallas path the single query row rides the fused kernel with
a (padded) minimal q block.
"""

from __future__ import annotations

import jax

from sav_tpu.models.layers.attention import AttentionBlock


class ClassSelfAttentionBlock(AttentionBlock):
    """Query is the first (CLS) token only; K/V span the full sequence."""

    # Q comes from a different (sliced) tensor than K/V — cross-attention
    # layout, so the fused single-matmul QKV projection does not apply.
    fused_qkv: bool = False

    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:  # type: ignore[override]
        return super().__call__(inputs[:, 0:1], inputs, is_training)


class LCSelfAttentionBlock(AttentionBlock):
    """Query is the last token only (CeiT layer-wise class attention)."""

    fused_qkv: bool = False

    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:  # type: ignore[override]
        return super().__call__(inputs[:, -1:], inputs, is_training)
