"""Stochastic depth (per-sample residual drop).

Reference: /root/reference/models/layers/regularization/stochastic_depth.py:6-28,
with the ``scale_by_keep=False`` crash fixed (SURVEY.md §2.9 #5). Uses its own
``'stochastic_depth'`` RNG stream as the reference does.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class StochasticDepthBlock(nn.Module):
    drop_rate: float = 0.0
    scale_by_keep: bool = True

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        if not is_training or self.drop_rate == 0.0:
            return inputs
        keep_prob = 1.0 - self.drop_rate
        rng = self.make_rng("stochastic_depth")
        mask_shape = (inputs.shape[0],) + (1,) * (inputs.ndim - 1)
        mask = jax.random.bernoulli(rng, keep_prob, mask_shape).astype(inputs.dtype)
        if self.scale_by_keep:
            mask = mask / keep_prob
        return inputs * mask
