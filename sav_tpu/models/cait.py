"""CaiT — Class-Attention in Image Transformers.

Reference: /root/reference/models/cait.py:10-183. Self-attention trunk with
talking heads + LayerScale + stochastic depth, followed by class-attention
blocks that only update a CLS token created *after* the body. The reference's
missing-dtype bug (cait.py:147-154, SURVEY.md §2.9 #16 — trunk silently ran
fp32) is fixed: dtype threads through every block.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import (
    AddAbsPosEmbed,
    ClassSelfAttentionBlock,
    FFBlock,
    LayerScaleBlock,
    PatchEmbedBlock,
    SelfAttentionBlock,
    StochasticDepthBlock,
)
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class EncoderBlock(nn.Module):
    """Talking-heads SA + LayerScale + StochasticDepth per branch (cait.py:18-53)."""

    num_heads: int
    expand_ratio: float = 4.0
    layerscale_eps: float = 1e-5
    stoch_depth_rate: float = 0.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    seq_parallel: Optional[str] = None  # 'ring' only (talking-heads trunk)
    seq_mesh: Optional[Any] = None
    # int8 quantized projection/FFN dots; the talking-heads mixing
    # kernels ([H, H], tiny) and the attention core stay in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = nn.LayerNorm(dtype=self.dtype)(inputs)
        x = SelfAttentionBlock(
            num_heads=self.num_heads,
            talking_heads=True,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            seq_parallel=self.seq_parallel,
            seq_mesh=self.seq_mesh,
            quant=self.quant,
            dtype=self.dtype,
        )(x, is_training)
        x = LayerScaleBlock(eps=self.layerscale_eps, dtype=self.dtype)(x)
        x = StochasticDepthBlock(drop_rate=self.stoch_depth_rate)(x, is_training)
        x = x + inputs
        y = nn.LayerNorm(dtype=self.dtype)(x)
        y = FFBlock(
            expand_ratio=self.expand_ratio,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
        )(y, is_training)
        y = LayerScaleBlock(eps=self.layerscale_eps, dtype=self.dtype)(y)
        y = StochasticDepthBlock(drop_rate=self.stoch_depth_rate)(y, is_training)
        return x + y


class CAEncoderBlock(nn.Module):
    """Class-attention block: CLS attends over [CLS; tokens] (cait.py:86-122)."""

    num_heads: int
    expand_ratio: float = 4.0
    layerscale_eps: float = 1e-5
    stoch_depth_rate: float = 0.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    quant: Optional[str] = None  # see EncoderBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, cls_tok: jax.Array, tokens: jax.Array, is_training: bool
    ) -> jax.Array:
        concat = jnp.concatenate([cls_tok, tokens], axis=1)
        x = nn.LayerNorm(dtype=self.dtype)(concat)
        x = ClassSelfAttentionBlock(
            num_heads=self.num_heads,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            quant=self.quant,
            dtype=self.dtype,
        )(x, is_training)
        x = LayerScaleBlock(eps=self.layerscale_eps, dtype=self.dtype)(x)
        x = StochasticDepthBlock(drop_rate=self.stoch_depth_rate)(x, is_training)
        cls_tok = cls_tok + x
        y = nn.LayerNorm(dtype=self.dtype)(cls_tok)
        y = FFBlock(
            expand_ratio=self.expand_ratio,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
        )(y, is_training)
        y = LayerScaleBlock(eps=self.layerscale_eps, dtype=self.dtype)(y)
        y = StochasticDepthBlock(drop_rate=self.stoch_depth_rate)(y, is_training)
        return cls_tok + y


class CaiT(nn.Module):
    num_classes: int
    embed_dim: int
    num_layers: int
    num_layers_token_only: int
    num_heads: int
    patch_shape: tuple[int, int]
    expand_ratio: float = 4.0
    layerscale_eps: float = 1e-5
    stoch_depth_rate: float = 0.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # Sequence parallelism over the SA trunk ('ring' only — the talking-
    # heads mix rides head-pair accumulators, see parallel.ring_attention).
    # The class-attention head (single-query CLS over L tokens) stays
    # unsharded: its logits are [B, H, 1, L] — there is no L x L term to
    # shard away.
    seq_parallel: Optional[str] = None
    seq_mesh: Optional[Any] = None
    quant: Optional[str] = None  # see EncoderBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = PatchEmbedBlock(
            patch_shape=self.patch_shape, embed_dim=self.embed_dim, dtype=self.dtype
        )(inputs)
        x = AddAbsPosEmbed(dtype=self.dtype)(x)
        x = nn.Dropout(rate=self.dropout_rate)(x, deterministic=not is_training)
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads,
                expand_ratio=self.expand_ratio,
                layerscale_eps=self.layerscale_eps,
                stoch_depth_rate=self.stoch_depth_rate,
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                seq_parallel=self.seq_parallel,
                seq_mesh=self.seq_mesh,
                quant=self.quant,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x, is_training)

        # CLS token enters only for the class-attention stage (cait.py:157-160).
        cls_tok = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
        cls_tok = jnp.broadcast_to(
            cls_tok.astype(x.dtype), (x.shape[0], 1, self.embed_dim)
        )
        for i in range(self.num_layers_token_only):
            cls_tok = CAEncoderBlock(
                num_heads=self.num_heads,
                expand_ratio=self.expand_ratio,
                layerscale_eps=self.layerscale_eps,
                stoch_depth_rate=0.0,  # class-attention stage runs undropped
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                quant=self.quant,
                dtype=self.dtype,
                name=f"ca_block_{i}",
            )(cls_tok, x, is_training)

        out = nn.LayerNorm(dtype=self.dtype)(cls_tok[:, 0])
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(out)
