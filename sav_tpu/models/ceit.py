"""CeiT — Convolution-enhanced image Transformer.

Reference: /root/reference/models/ceit.py:11-156. Image-to-Token conv stem,
post-norm encoder blocks with LeFF feed-forwards, per-layer CLS collection,
and a final layer-wise class-attention over the collected CLS tokens. Two
reference gaps fixed: absolute position embeddings are present (the paper
uses them; the reference dropped them — SURVEY.md §2.9 #20), and the unused
``LCAEncoderBlock`` dead code is not reproduced (#17) — the final stage is
the bare LC attention + FF the reference actually runs.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import (
    AddAbsPosEmbed,
    Image2TokenBlock,
    LCSelfAttentionBlock,
    LeFFBlock,
    SelfAttentionBlock,
)
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class EncoderBlock(nn.Module):
    """Post-norm block: SA→res→LN, LeFF→res→LN (ceit.py:19-44)."""

    num_heads: int
    expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    seq_parallel: Optional[str] = None
    seq_mesh: Optional[Any] = None
    # int8 quantized projection dots + LeFF expand/project dots; the
    # LeFF depthwise conv and all norms stay in ``dtype``.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = SelfAttentionBlock(
            num_heads=self.num_heads,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            seq_parallel=self.seq_parallel,
            seq_mesh=self.seq_mesh,
            quant=self.quant,
            dtype=self.dtype,
        )(inputs, is_training)
        x = nn.LayerNorm(dtype=self.dtype)(x + inputs)
        y = LeFFBlock(
            expand_ratio=self.expand_ratio, quant=self.quant, dtype=self.dtype
        )(x, is_training)
        return nn.LayerNorm(dtype=self.dtype)(y + x)


class CeiT(nn.Module):
    num_classes: int
    embed_dim: int
    num_layers: int
    num_heads: int
    patch_shape: tuple[int, int]
    stem_ch: int = 32
    expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # SP shards the trunk token sequence; the LCA head (single-query class
    # attention over L_layers CLS tokens) stays unsharded.
    seq_parallel: Optional[str] = None
    seq_mesh: Optional[Any] = None
    quant: Optional[str] = None  # see EncoderBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        x = Image2TokenBlock(
            patch_shape=self.patch_shape,
            embed_dim=self.embed_dim,
            stem_ch=self.stem_ch,
            dtype=self.dtype,
        )(inputs, is_training)
        b = x.shape[0]
        cls_tok = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
        cls_tok = jnp.broadcast_to(cls_tok.astype(x.dtype), (b, 1, self.embed_dim))
        x = jnp.concatenate([cls_tok, x], axis=1)
        x = AddAbsPosEmbed(dtype=self.dtype)(x)
        x = nn.Dropout(rate=self.dropout_rate)(x, deterministic=not is_training)

        cls_collection = []
        for i in range(self.num_layers):
            x = EncoderBlock(
                num_heads=self.num_heads,
                expand_ratio=self.expand_ratio,
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                seq_parallel=self.seq_parallel,
                seq_mesh=self.seq_mesh,
                quant=self.quant,
                dtype=self.dtype,
                name=f"block_{i}",
            )(x, is_training)
            cls_collection.append(x[:, 0])

        # Layer-wise class attention over the L collected CLS tokens; the
        # query is the final layer's CLS (last token), ceit.py:147-155.
        cls_seq = jnp.stack(cls_collection, axis=1)  # [B, L_layers, D]
        out = LCSelfAttentionBlock(
            num_heads=self.num_heads,
            attn_dropout_rate=self.attn_dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            quant=self.quant,
            dtype=self.dtype,
            name="lca",
        )(cls_seq, is_training)
        out = nn.LayerNorm(dtype=self.dtype)(out[:, -1])
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(out)
