"""TNT — Transformer-in-Transformer.

Reference: /root/reference/models/tnt.py:10-182. Two token streams: an inner
transformer over per-patch "pixel" tokens and an outer transformer over patch
tokens, with the inner stream folded into the outer one every block. The
pixel stream folds patches into the batch dim (``[B·P, inner_tokens, C]``) —
TPU-friendly batch-dim blocking, as in the reference. The reference's
patch-shape index typo (tnt.py:22-25, SURVEY.md §2.9 #18) and the swapped
S/B hyperparameters (create_model.py:50-63 vs tests, #13) are fixed in the
registry.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from sav_tpu.models.layers import (
    AddAbsPosEmbed,
    FFBlock,
    PatchEmbedBlock,
    SelfAttentionBlock,
)
from sav_tpu.ops.quant import QuantDense

Dtype = Any


class PixelEmbedBlock(nn.Module):
    """Per-patch pixel tokens: each ``ph×pw`` patch becomes a grid of inner
    tokens via a strided conv (tnt.py:10-33)."""

    patch_shape: tuple[int, int]
    inner_ch: int
    inner_stride: int = 4
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        b, h, w, c = inputs.shape
        ph, pw = self.patch_shape
        num_patches = (h // ph) * (w // pw)
        # [B, H, W, C] -> [B*P, ph, pw, C]
        x = inputs.reshape(b, h // ph, ph, w // pw, pw, c)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b * num_patches, ph, pw, c)
        x = nn.Conv(
            features=self.inner_ch,
            kernel_size=(7, 7),
            strides=(self.inner_stride, self.inner_stride),
            padding="SAME",
            dtype=self.dtype,
            name="proj",
        )(x)
        inner_tokens = x.shape[1] * x.shape[2]
        return x.reshape(b * num_patches, inner_tokens, self.inner_ch)


class Inner2OuterBlock(nn.Module):
    """Fold pixel tokens into patch tokens: LN → Dense over the flattened
    pixel dims → add at patch positions (offset 1 for CLS) (tnt.py:36-50)."""

    embed_dim: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, pixel_tokens: jax.Array, patch_tokens: jax.Array) -> jax.Array:
        b, num_patches_plus_1, _ = patch_tokens.shape
        num_patches = num_patches_plus_1 - 1
        flat = pixel_tokens.reshape(b, num_patches, -1)
        flat = nn.LayerNorm(dtype=self.dtype)(flat)
        fold = nn.Dense(self.embed_dim, dtype=self.dtype, name="proj")(flat)
        return patch_tokens.at[:, 1:].add(fold)


class EncoderBlock(nn.Module):
    """Inner transformer on pixel tokens → fold → outer transformer (tnt.py:53-93)."""

    embed_dim: int
    num_heads: int
    inner_num_heads: int
    expand_ratio: float = 4.0
    inner_expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    # SP shards the OUTER (patch-token) sequence only: the inner stream's
    # per-patch sequences are tiny and already parallel over B*P.
    seq_parallel: Optional[str] = None
    seq_mesh: Optional[Any] = None
    # int8 quantized projection/FFN dots on BOTH streams; the fold
    # projection (Inner2OuterBlock) stays float — it runs once per block
    # on tiny flattened tokens and its output seeds a residual stream.
    quant: Optional[str] = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self, pixel_tokens: jax.Array, patch_tokens: jax.Array, is_training: bool
    ):
        # Inner transformer (pre-LN) on [B*P, inner_tokens, inner_ch].
        x = nn.LayerNorm(dtype=self.dtype)(pixel_tokens)
        x = SelfAttentionBlock(
            num_heads=self.inner_num_heads,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            quant=self.quant,
            dtype=self.dtype,
            name="inner_attn",
        )(x, is_training)
        pixel_tokens = pixel_tokens + x
        y = nn.LayerNorm(dtype=self.dtype)(pixel_tokens)
        y = FFBlock(
            expand_ratio=self.inner_expand_ratio,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
            name="inner_ff",
        )(y, is_training)
        pixel_tokens = pixel_tokens + y

        patch_tokens = Inner2OuterBlock(embed_dim=self.embed_dim, dtype=self.dtype)(
            pixel_tokens, patch_tokens
        )

        # Outer transformer on [B, P+1, embed_dim].
        z = nn.LayerNorm(dtype=self.dtype)(patch_tokens)
        z = SelfAttentionBlock(
            num_heads=self.num_heads,
            attn_dropout_rate=self.attn_dropout_rate,
            out_dropout_rate=self.dropout_rate,
            backend=self.backend,
            logits_dtype=self.logits_dtype,
            seq_parallel=self.seq_parallel,
            seq_mesh=self.seq_mesh,
            quant=self.quant,
            dtype=self.dtype,
            name="outer_attn",
        )(z, is_training)
        patch_tokens = patch_tokens + z
        w = nn.LayerNorm(dtype=self.dtype)(patch_tokens)
        w = FFBlock(
            expand_ratio=self.expand_ratio,
            dropout_rate=self.dropout_rate,
            quant=self.quant,
            dtype=self.dtype,
            name="outer_ff",
        )(w, is_training)
        patch_tokens = patch_tokens + w
        return pixel_tokens, patch_tokens


class TNT(nn.Module):
    num_classes: int
    embed_dim: int
    inner_ch: int
    num_layers: int
    num_heads: int
    inner_num_heads: int
    patch_shape: tuple[int, int]
    inner_stride: int = 4
    expand_ratio: float = 4.0
    inner_expand_ratio: float = 4.0
    attn_dropout_rate: float = 0.0
    dropout_rate: float = 0.0
    backend: Optional[str] = None
    logits_dtype: Optional[Dtype] = None  # None = inherit dtype (softmax math)
    seq_parallel: Optional[str] = None  # outer-stream SP; see EncoderBlock
    seq_mesh: Optional[Any] = None
    quant: Optional[str] = None  # see EncoderBlock.quant
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, inputs: jax.Array, is_training: bool) -> jax.Array:
        b = inputs.shape[0]
        pixel_tokens = PixelEmbedBlock(
            patch_shape=self.patch_shape,
            inner_ch=self.inner_ch,
            inner_stride=self.inner_stride,
            dtype=self.dtype,
        )(inputs)
        patch_tokens = PatchEmbedBlock(
            patch_shape=self.patch_shape, embed_dim=self.embed_dim, dtype=self.dtype
        )(inputs)
        cls_tok = self.param("cls", nn.initializers.zeros, (1, 1, self.embed_dim))
        cls_tok = jnp.broadcast_to(cls_tok.astype(patch_tokens.dtype), (b, 1, self.embed_dim))
        patch_tokens = jnp.concatenate([cls_tok, patch_tokens], axis=1)

        pixel_tokens = AddAbsPosEmbed(dtype=self.dtype, name="inner_pos_embed")(
            pixel_tokens
        )
        patch_tokens = AddAbsPosEmbed(dtype=self.dtype, name="outer_pos_embed")(
            patch_tokens
        )
        patch_tokens = nn.Dropout(rate=self.dropout_rate)(
            patch_tokens, deterministic=not is_training
        )

        for i in range(self.num_layers):
            pixel_tokens, patch_tokens = EncoderBlock(
                embed_dim=self.embed_dim,
                num_heads=self.num_heads,
                inner_num_heads=self.inner_num_heads,
                expand_ratio=self.expand_ratio,
                inner_expand_ratio=self.inner_expand_ratio,
                attn_dropout_rate=self.attn_dropout_rate,
                dropout_rate=self.dropout_rate,
                backend=self.backend,
                logits_dtype=self.logits_dtype,
                seq_parallel=self.seq_parallel,
                seq_mesh=self.seq_mesh,
                quant=self.quant,
                dtype=self.dtype,
                name=f"block_{i}",
            )(pixel_tokens, patch_tokens, is_training)

        out = nn.LayerNorm(dtype=self.dtype)(patch_tokens[:, 0])
        head = (
            functools.partial(QuantDense, mode=self.quant)
            if self.quant else nn.Dense
        )
        return head(
            self.num_classes,
            kernel_init=nn.initializers.zeros,
            dtype=self.dtype,
            name="head",
        )(out)
