"""Serving latency ledger — p50/p95/p99, throughput, queue, padding waste.

The serving twin of the trainer's :class:`~sav_tpu.obs.goodput.GoodputLedger`:
one host-side accumulator whose summary lands in the run manifest so
``tools/regression_sentinel.py`` gates serving perf exactly like training
perf (metrics ``p99_latency_ms`` lower-better, ``serve_throughput``
higher-better — docs/serving.md). Recording is the engine's completion
path only — one observation per finished *batch*, request latencies
computed from host wall clocks the engine already holds. Nothing here
ever touches a device value (savlint SAV115 owns the batcher-drain
functions; this ledger is plain float bookkeeping).

Stdlib-only; ``clock`` is injectable for deterministic tests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


def percentile(sorted_values: list, q: float) -> float:
    """Linear-interpolated percentile of an already-sorted list
    (numpy's default method, stdlib-only so the data layer stays
    importable without numpy)."""
    if not sorted_values:
        raise ValueError("percentile of an empty series")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile q must be in [0, 100], got {q}")
    n = len(sorted_values)
    if n == 1:
        return float(sorted_values[0])
    pos = (q / 100.0) * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return float(sorted_values[lo] * (1.0 - frac) + sorted_values[hi] * frac)


class LatencyLedger:
    """Per-request latency + per-batch serving telemetry.

    ``observe_batch`` records one shipped batch: the request latencies
    (submit -> result ready, seconds), the bucket it padded to, the queue
    depth at drain time, and the device step seconds. ``summary()``
    renders the serving headline: latency percentiles, throughput over
    the serving window, bucket occupancy, measured padding-waste
    fraction, queue stats, and deadline-overrun accounting.
    """

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        *,
        window=None,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._t0: Optional[float] = None
        self._t_last: Optional[float] = None
        self._latencies: list = []
        self._overruns: list = []  # positive seconds past the deadline
        self._batches: dict = {}  # bucket -> [batches, real_rows]
        self._queue_sum = 0
        self._queue_max = 0
        self._step_s = 0.0
        self._rejected = 0
        # Live windowed view (sav_tpu/serve/telemetry.py LiveWindow or
        # None): fed from the SAME observation path as the cumulative
        # accumulators, so the final summary() stays bit-identical with
        # the window on or off (tests/test_serve_telemetry.py pins it)
        # while mid-run percentiles become observable via live().
        self._window = window

    def start(self) -> None:
        """Mark the start of the serving window (throughput denominator).
        Called once when the engine opens for traffic — startup/compile
        time must not dilute the measured serving rate."""
        with self._lock:
            self._t0 = self._clock()

    def observe_batch(
        self,
        *,
        bucket: int,
        latencies_s: list,
        overruns_s: list,
        queue_depth: int,
        step_s: float,
    ) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = self._clock()
            self._t_last = self._clock()
            self._latencies.extend(float(v) for v in latencies_s)
            self._overruns.extend(float(v) for v in overruns_s if v > 0.0)
            stats = self._batches.setdefault(bucket, [0, 0])
            stats[0] += 1
            stats[1] += len(latencies_s)
            self._queue_sum += int(queue_depth)
            self._queue_max = max(self._queue_max, int(queue_depth))
            self._step_s += float(step_s)
        if self._window is not None:
            self._window.observe_window(
                latencies_s=latencies_s,
                overruns_s=overruns_s,
                bucket=bucket,
                queue_depth=queue_depth,
                step_s=step_s,
            )

    def observe_rejected(self, n: int = 1) -> None:
        """Requests refused at admission (bounded queue full)."""
        with self._lock:
            self._rejected += int(n)
        if self._window is not None:
            self._window.observe_shed(n)

    def live(self) -> Optional[dict]:
        """The windowed mid-run view (None with no window attached).
        Safe at any point — before the first completed batch the
        percentiles are None, never an exception."""
        if self._window is None:
            return None
        return self._window.snapshot()

    @property
    def requests(self) -> int:
        with self._lock:
            return len(self._latencies)

    def summary(self) -> dict:
        with self._lock:
            lat = sorted(self._latencies)
            n = len(lat)
            batches = sum(b for b, _ in self._batches.values())
            padded_rows = sum(
                bucket * b for bucket, (b, _) in self._batches.items()
            )
            real_rows = sum(r for _, r in self._batches.values())
            wall = (
                (self._t_last - self._t0)
                if (self._t0 is not None and self._t_last is not None)
                else 0.0
            )
            out = {
                "requests": n,
                "batches": batches,
                "rejected": self._rejected,
                "wall_s": round(wall, 4),
                "throughput_rps": round(n / wall, 2) if wall > 0 else 0.0,
                "step_s_total": round(self._step_s, 4),
                "padding_waste_frac": round(
                    1.0 - real_rows / padded_rows, 4
                ) if padded_rows else 0.0,
                "bucket_occupancy": {
                    str(bucket): {
                        "batches": b,
                        "fill": round(r / (bucket * b), 4) if b else 0.0,
                    }
                    for bucket, (b, r) in sorted(self._batches.items())
                },
                "queue_depth_avg": round(
                    self._queue_sum / batches, 2
                ) if batches else 0.0,
                "queue_depth_max": self._queue_max,
                "deadline_overruns": len(self._overruns),
                "deadline_overrun_max_ms": round(
                    max(self._overruns) * 1e3, 3
                ) if self._overruns else 0.0,
            }
            if n:
                out["latency_ms"] = {
                    "p50": round(percentile(lat, 50.0) * 1e3, 3),
                    "p95": round(percentile(lat, 95.0) * 1e3, 3),
                    "p99": round(percentile(lat, 99.0) * 1e3, 3),
                    "max": round(lat[-1] * 1e3, 3),
                }
            return out

    def flat_metrics(self, prefix: str = "serve/") -> dict:
        """Flat scalar view for the run manifest (the keys
        ``sav_tpu.obs.manifest._manifest_metrics`` reads back into the
        sentinel's ``p99_latency_ms``/``serve_throughput``)."""
        s = self.summary()
        out = {
            prefix + "requests": float(s["requests"]),
            prefix + "batches": float(s["batches"]),
            prefix + "rejected": float(s["rejected"]),
            prefix + "wall_s": s["wall_s"],
            prefix + "throughput_rps": s["throughput_rps"],
            prefix + "padding_waste_frac": s["padding_waste_frac"],
            prefix + "queue_depth_avg": s["queue_depth_avg"],
            prefix + "queue_depth_max": float(s["queue_depth_max"]),
            prefix + "deadline_overruns": float(s["deadline_overruns"]),
        }
        if "latency_ms" in s:
            for k, v in s["latency_ms"].items():
                out[prefix + k + "_latency_ms"] = v
        return out
