"""Serve replica pool: N supervised engine replicas behind one log dir.

The horizontal half of the serving subsystem (ROADMAP item 3): a
:class:`ReplicaPool` spawns (or adopts) N serve replicas — each one a
real OS process running its own :class:`~sav_tpu.serve.engine.ServeEngine`
(one SpecLayout mesh per replica: a big model spans its chips via TP, a
small model replicates across replicas) under a PR-9
:class:`~sav_tpu.train.supervisor.Supervisor` in serve mode, so a
SIGKILLed replica restarts with bounded backoff and warm-starts every
bucket executable from the shared persistent compile cache
(``compiled_from_scratch == 0``, the PR-10 proof). All replicas share
ONE log dir: heartbeats land in ``fleet/proc_<rank>.jsonl`` (identity
via the ``SAV_FLEET_PROC`` override — the documented seam for fleets
not coordinated through ``jax.distributed``), manifests in
``manifest-serve-r<rank>.json``, and each replica registers its wire
endpoint in ``fleet/replica_<rank>.json`` so the router and the
offline tools discover the fleet from artifacts alone.

:class:`TcpTransport` is the wire between the
:class:`~sav_tpu.serve.router.Router` and the replica servers
(``tools/serve_fleet.py --replica-rank``): one request per localhost
TCP connection, a JSON header line + raw uint8 payload out, one JSON
reply line back. A connection-level failure surfaces as
:class:`~sav_tpu.serve.router.ReplicaTransportError` — the router's
cue to mark the replica down and reroute — and a replica-side
admission reject as :class:`~sav_tpu.serve.router.ReplicaShedError`.

Import contract: **stdlib-only at module scope** (no jax, no numpy) —
the pool runs in the parent of on-chip replicas, where importing the
backend is exactly what hangs (the supervisor/backend_probe
philosophy), and the transport runs inside the router's no-jax
surface. docs/serving.md "Fleet" is the subsystem guide.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import threading
import time
from typing import Callable, Optional

from sav_tpu.serve.router import ReplicaShedError, ReplicaTransportError
from sav_tpu.train.supervisor import Supervisor

FLEET_POOL_SCHEMA = 1

#: Reply wait beyond the request deadline before the client socket
#: gives up. The PR-10 batcher contract lets an ADMITTED request finish
#: up to one bucket step PAST its deadline (the replica server holds
#: its future for deadline + grace for exactly this), so a socket
#: timeout pinned at the bare deadline would misread every legitimate
#: overrun as a dead replica — down-flapping a healthy server and
#: double-executing its work. Matches the server's RESULT_GRACE_S.
REPLY_GRACE_S = 5.0


# ------------------------------------------------------------- endpoints


def endpoint_path(log_dir: str, rank: int) -> str:
    """``fleet/replica_<rank>.json`` — the replica's wire registration
    (host/port/pid/startup report), rewritten on every (re)start so the
    transport always resolves the CURRENT process."""
    return os.path.join(log_dir, "fleet", f"replica_{int(rank)}.json")


def write_endpoint(
    log_dir: str,
    rank: int,
    *,
    host: str,
    port: int,
    pid: Optional[int] = None,
    startup: Optional[dict] = None,
    platform: Optional[str] = None,
) -> Optional[str]:
    """Atomically register one replica's endpoint (tmp + ``os.replace``,
    the manifest discipline — a reader never sees a torn file). Returns
    the path, or None on I/O failure (registration is telemetry-grade:
    it must not take the replica down; the router just won't find it)."""
    path = endpoint_path(log_dir, rank)
    doc = {
        "schema": FLEET_POOL_SCHEMA,
        "rank": int(rank),
        "host": host,
        "port": int(port),
        "pid": int(pid if pid is not None else os.getpid()),
        "t": round(time.time(), 3),
    }
    if platform:
        doc["platform"] = platform
    if startup:
        doc["startup"] = startup
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, path)
        return path
    except OSError:
        return None


def read_endpoint(log_dir: str, rank: int) -> Optional[dict]:
    try:
        with open(endpoint_path(log_dir, rank)) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def read_endpoints(log_dir: str) -> dict:
    """Every registered replica endpoint in a log dir, by rank."""
    root = os.path.join(log_dir, "fleet")
    out: dict = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if not (name.startswith("replica_") and name.endswith(".json")):
            continue
        try:
            rank = int(name[len("replica_"):-len(".json")])
        except ValueError:
            continue
        doc = read_endpoint(log_dir, rank)
        if doc is not None:
            out[rank] = doc
    return out


def pid_alive(pid) -> bool:
    """Is the process alive (signal-0 probe)? False on bad input."""
    try:
        os.kill(int(pid), 0)
    except (OSError, OverflowError, TypeError, ValueError):
        return False
    return True


# ------------------------------------------------------------- transport


class TcpTransport:
    """One-request-per-connection localhost wire to the replica servers.

    Protocol (both sides stdlib-only):

    - request: one JSON header line (``{"op": "infer", "deadline_ms":
      D, "nbytes": N, ...meta}``) terminated by ``\\n``, then exactly
      N raw payload bytes (the uint8 image row).
    - reply: one JSON line — ``{"ok": true, "pred": k, ...}`` on
      success, ``{"ok": false, "shed": true, ...}`` on a replica-side
      admission reject (raised as :class:`ReplicaShedError`),
      ``{"ok": false, ...}`` on an application error (raised as
      ``RuntimeError``). Connection-level failures (refused, reset,
      torn reply — the replica died) raise
      :class:`ReplicaTransportError`, the router's reroute cue.

    Endpoints resolve from the log dir's registration files, cached per
    rank and invalidated on any failure — a supervisor-restarted
    replica rewrites its file with the new port, and the next send
    after its recovery re-reads it.
    """

    def __init__(
        self,
        log_dir: str,
        *,
        connect_timeout_s: float = 2.0,
    ):
        self.log_dir = log_dir
        self.connect_timeout_s = float(connect_timeout_s)
        self._lock = threading.Lock()
        self._cache: dict = {}

    def resolve(self, rank: int, *, refresh: bool = False) -> tuple:
        with self._lock:
            if not refresh and rank in self._cache:
                return self._cache[rank]
        doc = read_endpoint(self.log_dir, rank)
        if doc is None:
            raise ReplicaTransportError(
                f"replica {rank} has no endpoint registration under "
                f"{os.path.join(self.log_dir, 'fleet')}"
            )
        endpoint = (doc.get("host") or "127.0.0.1", int(doc["port"]))
        with self._lock:
            self._cache[rank] = endpoint
        return endpoint

    def invalidate(self, rank: int) -> None:
        with self._lock:
            self._cache.pop(rank, None)

    #: Router trace seam: send() accepts ``stamp_fn`` and stamps
    #: ``connect``/``sent`` at the real socket instants (ISSUE 16).
    supports_stamps = True

    def _exchange(
        self,
        rank: int,
        header: dict,
        payload: bytes,
        timeout_s: float,
        stamp_fn=None,
    ) -> dict:
        host, port = self.resolve(rank)
        try:
            with socket.create_connection(
                (host, port),
                timeout=min(self.connect_timeout_s, max(timeout_s, 0.05)),
            ) as sock:
                if stamp_fn is not None:
                    stamp_fn("connect")
                # Reply timeout = deadline remainder + grace: a dead
                # process fails the CONNECT instantly (refused/reset);
                # a reply is allowed the same past-deadline slack the
                # engine contract grants, so an overrun completes late
                # instead of down-flapping its replica.
                sock.settimeout(max(timeout_s, 0.05) + REPLY_GRACE_S)
                sock.sendall(
                    json.dumps(header).encode("utf-8") + b"\n" + payload
                )
                if stamp_fn is not None:
                    stamp_fn("sent")
                chunks = []
                while True:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    chunks.append(chunk)
                    if b"\n" in chunk:
                        break
        except OSError as e:
            self.invalidate(rank)
            raise ReplicaTransportError(
                f"replica {rank} at {host}:{port}: {e}"
            ) from None
        line = b"".join(chunks).split(b"\n", 1)[0]
        if not line:
            self.invalidate(rank)
            raise ReplicaTransportError(
                f"replica {rank} at {host}:{port} closed without a reply"
            )
        try:
            reply = json.loads(line)
        except json.JSONDecodeError:
            self.invalidate(rank)
            raise ReplicaTransportError(
                f"replica {rank} sent a torn reply"
            ) from None
        if reply.get("shed"):
            raise ReplicaShedError(
                reply.get("error") or f"replica {rank} shed the request"
            )
        if not reply.get("ok"):
            raise RuntimeError(
                reply.get("error") or f"replica {rank} failed the request"
            )
        return reply

    def send(
        self,
        rank: int,
        payload: bytes,
        meta: dict,
        timeout_s: float,
        stamp_fn=None,
    ) -> dict:
        """One inference exchange (the Router's dispatch wire).
        ``stamp_fn`` (optional, ISSUE 16) is called with ``"connect"``
        when the socket opens and ``"sent"`` when the request bytes are
        handed off — the router's trace stamps at the real wire
        instants. The trace id itself rides the header: the router puts
        it in ``meta["trace"]`` and the replica server hands it to
        ``engine.submit``."""
        header = dict(meta or {})
        header["op"] = "infer"
        header["nbytes"] = len(payload)
        header.setdefault("deadline_ms", round(timeout_s * 1e3, 3))
        return self._exchange(
            rank, header, bytes(payload), timeout_s, stamp_fn=stamp_fn
        )

    def ping(self, rank: int, timeout_s: float = 5.0) -> dict:
        """Health probe: the replica answers with its rank/pid/platform
        and current startup report (the warm-restart proof reads
        ``startup.compiled_from_scratch`` from here)."""
        return self._exchange(rank, {"op": "ping"}, b"", timeout_s)


# ------------------------------------------------------------------ pool


class _PoolEntry:
    __slots__ = ("rank", "adopted", "supervisor", "thread", "exit_code")

    def __init__(self, rank: int):
        self.rank = rank
        self.adopted = False
        self.supervisor: Optional[Supervisor] = None
        self.thread: Optional[threading.Thread] = None
        self.exit_code: Optional[int] = None


class ReplicaPool:
    """Spawn/adopt N supervised serve replicas sharing one log dir.

    Args:
      replicas: fleet size.
      child_argv_fn: ``rank -> argv`` for the replica server process
        (``tools/serve_fleet.py`` builds the standard one). The child
        must register its endpoint and heartbeat into the shared
        ``log_dir``.
      log_dir: the shared artifact sink (heartbeats, endpoints,
        manifests). Per-replica supervisor chains live under
        ``<log_dir>/replicas/rank_<i>/``.
      env_fn: optional ``rank -> extra env`` for the child (chaos
        seams). The pool always sets the fleet identity override
        (``SAV_FLEET_PROC``/``SAV_FLEET_PROCS``) so heartbeat streams
        and endpoint files namespace by rank.
      max_restarts / backoff_base_s / backoff_max_s: each replica's
        supervisor budget (PR-9 semantics; serving restarts want a
        short backoff — a dead replica is lost capacity every second).
      adopt: when True (default), a rank whose endpoint already names a
        LIVE pid is adopted instead of spawned — a pool restart
        attaches to surviving replicas rather than double-spawning.
    """

    def __init__(
        self,
        *,
        replicas: int,
        child_argv_fn: Callable[[int], list],
        log_dir: str,
        env_fn: Optional[Callable[[int], dict]] = None,
        max_restarts: int = 4,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 10.0,
        capture: bool = True,
        adopt: bool = True,
    ):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.replicas = int(replicas)
        self.child_argv_fn = child_argv_fn
        self.log_dir = log_dir
        self.env_fn = env_fn
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.capture = capture
        self.adopt = adopt
        self._entries: dict[int, _PoolEntry] = {}
        self._started = False
        self._stopped = False

    # ------------------------------------------------------------ lifecycle

    def rank_dir(self, rank: int) -> str:
        return os.path.join(self.log_dir, "replicas", f"rank_{int(rank)}")

    def start(self) -> "ReplicaPool":
        if self._started:
            raise RuntimeError("pool already started")
        self._started = True
        os.makedirs(os.path.join(self.log_dir, "fleet"), exist_ok=True)
        for rank in range(self.replicas):
            entry = self._entries[rank] = _PoolEntry(rank)
            existing = read_endpoint(self.log_dir, rank)
            if (
                self.adopt
                and existing is not None
                and pid_alive(existing.get("pid"))
            ):
                entry.adopted = True
                continue
            env = {
                "SAV_FLEET_PROC": str(rank),
                "SAV_FLEET_PROCS": str(self.replicas),
            }
            if self.env_fn is not None:
                env.update(self.env_fn(rank) or {})
            supervisor = Supervisor(
                self.child_argv_fn(rank),
                log_dir=self.rank_dir(rank),
                checkpoint_dir=None,
                max_restarts=self.max_restarts,
                backoff_base_s=self.backoff_base_s,
                backoff_max_s=self.backoff_max_s,
                capture=self.capture,
                env=env,
                serve=True,
                manifest_src=os.path.join(
                    self.log_dir, f"manifest-serve-r{rank}.json"
                ),
            )
            entry.supervisor = supervisor

            def _run(entry=entry, supervisor=supervisor):
                entry.exit_code = supervisor.run()

            entry.thread = threading.Thread(
                target=_run, name=f"replica-supervisor-{rank}", daemon=True
            )
            entry.thread.start()
        return self

    def wait_ready(
        self,
        timeout_s: float = 600.0,
        *,
        transport: Optional[TcpTransport] = None,
        poll_s: float = 0.25,
    ) -> dict:
        """Block until every rank has a live endpoint (and answers a
        ping, when a transport is given). Returns ``{rank: endpoint
        doc}``; raises ``TimeoutError`` naming the ranks still missing
        — a replica that never comes up is a failure, not a hang — and
        fails FAST (``RuntimeError``) when a rank's supervisor chain
        has already ended without an endpoint (budget exhausted on a
        startup crash, usage error): sitting out the full timeout adds
        nothing once the restart budget is spent."""
        deadline = time.monotonic() + float(timeout_s)
        ready: dict = {}
        while True:
            for rank in range(self.replicas):
                if rank in ready:
                    continue
                entry = self._entries.get(rank)
                if (
                    entry is not None
                    and entry.thread is not None
                    and not entry.thread.is_alive()
                    and entry.exit_code not in (None, 0)
                ):
                    raise RuntimeError(
                        f"replica {rank}'s supervisor chain ended "
                        f"(exit {entry.exit_code}) before the replica "
                        f"came up — see {self.rank_dir(rank)}/attempts/ "
                        "for its output"
                    )
                doc = read_endpoint(self.log_dir, rank)
                if doc is None or not pid_alive(doc.get("pid")):
                    continue
                if transport is not None:
                    try:
                        transport.invalidate(rank)
                        doc = dict(doc, ping=transport.ping(rank))
                    except (ReplicaTransportError, RuntimeError):
                        continue
                ready[rank] = doc
            if len(ready) == self.replicas:
                return ready
            if time.monotonic() >= deadline:
                missing = sorted(
                    set(range(self.replicas)) - set(ready)
                )
                raise TimeoutError(
                    f"replicas {missing} not ready after {timeout_s}s "
                    f"(see {self.log_dir}/replicas/rank_*/attempts/ for "
                    "their output)"
                )
            time.sleep(poll_s)

    def child_pid(self, rank: int) -> Optional[int]:
        """The rank's CURRENT serving pid: the supervisor's live child,
        or the adopted endpoint registration."""
        entry = self._entries.get(rank)
        if entry is not None and entry.supervisor is not None:
            child = entry.supervisor.child
            if child is not None and child.poll() is None:
                return child.pid
        doc = read_endpoint(self.log_dir, rank)
        if doc is not None and pid_alive(doc.get("pid")):
            return int(doc["pid"])
        return None

    def kill(self, rank: int, sig: int = signal.SIGKILL) -> Optional[int]:
        """Send ``sig`` to the rank's current process (the chaos arm's
        hook). Returns the pid signalled, or None when nothing is
        alive. A SIGKILL here is exactly the fault the supervisor
        exists to absorb: bounded-backoff restart, warm compile cache,
        router reroute in the meantime."""
        pid = self.child_pid(rank)
        if pid is None:
            return None
        try:
            os.kill(pid, sig)
        except OSError:
            return None
        return pid

    def stop(self, timeout_s: float = 60.0) -> dict:
        """Graceful fleet shutdown: tell every supervisor the stop is
        REQUESTED (so a terminating child ends the chain instead of
        triggering a restart), SIGTERM the replicas (they drain +
        finalize + exit 0), and join the supervisor threads —
        escalating to SIGKILL past the timeout. Idempotent; returns
        :meth:`status`."""
        if self._stopped:
            return self.status()
        self._stopped = True
        for entry in self._entries.values():
            if entry.supervisor is not None:
                entry.supervisor.request_stop()
        for rank, entry in self._entries.items():
            pid = self.child_pid(rank)
            if pid is not None:
                try:
                    os.kill(pid, signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + float(timeout_s)
        for entry in self._entries.values():
            if entry.thread is None:
                continue
            entry.thread.join(max(deadline - time.monotonic(), 0.1))
            if entry.thread.is_alive():
                pid = self.child_pid(entry.rank)
                if pid is not None:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
                entry.thread.join(10.0)
        return self.status()

    def __enter__(self) -> "ReplicaPool":
        return self if self._started else self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    # -------------------------------------------------------------- reading

    def status(self) -> dict:
        """Pool view from the supervisors + endpoint registry: per-rank
        chain attempts/restarts, live pid, and the newest startup
        report (the warm-restart proof reads
        ``startup.compiled_from_scratch`` of the restarted rank)."""
        ranks = {}
        for rank in range(self.replicas):
            entry = self._entries.get(rank)
            doc = read_endpoint(self.log_dir, rank) or {}
            view = {
                "adopted": bool(entry.adopted) if entry else False,
                "pid": doc.get("pid"),
                "alive": pid_alive(doc.get("pid")),
                "endpoint": (
                    {"host": doc.get("host"), "port": doc.get("port")}
                    if doc else None
                ),
                "startup": doc.get("startup"),
                "platform": doc.get("platform"),
            }
            if entry is not None and entry.supervisor is not None:
                attempts = entry.supervisor.attempts
                view["attempts"] = len(attempts)
                view["restarts"] = max(len(attempts) - 1, 0)
                view["restart_reasons"] = [
                    a.get("restart_reason") for a in attempts
                    if a.get("restart_reason")
                ]
                view["exit_code"] = entry.exit_code
            ranks[str(rank)] = view
        return {
            "schema": FLEET_POOL_SCHEMA,
            "log_dir": self.log_dir,
            "replicas": self.replicas,
            "restarts": sum(
                v.get("restarts", 0) for v in ranks.values()
            ),
            "ranks": ranks,
        }
